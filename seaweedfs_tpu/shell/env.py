"""CommandEnv: the shell's connection to the cluster.

Reference: weed/shell/commands.go (CommandEnv with MasterClient + exclusive
lock) and weed/wdclient/exclusive_locks/exclusive_locker.go (the admin
lease that gates mutating commands — `lock`/`unlock`, confirmIsLocked).
"""

from __future__ import annotations

import threading

from ..cluster import rpc
from ..cluster.client import WeedClient


class ShellError(Exception):
    pass


class CommandEnv:
    def __init__(self, master_url: str, filer_url: str | None = None):
        self.master_url = master_url.rstrip("/")
        self.client = WeedClient(self.master_url)
        self._lock_token: int | None = None
        self._renewer: threading.Timer | None = None
        self.cwd = "/"  # for fs.* commands
        self.filer_url = filer_url.rstrip("/") if filer_url else None

    def filer(self):
        """FilerProxy for fs.* commands (shell -filer=host:8888)."""
        if self.filer_url is None:
            raise ShellError(
                "no filer configured — start the shell with "
                "-filer=host:8888")
        from ..filer.client import FilerProxy
        return FilerProxy(self.filer_url)

    def resolve(self, path: str) -> str:
        """cwd-relative -> absolute filer path (fs.cd semantics)."""
        import posixpath
        if not path:
            return self.cwd
        if not path.startswith("/"):
            path = posixpath.join(self.cwd, path)
        return posixpath.normpath(path)

    # -- cluster views -------------------------------------------------------

    def topology(self) -> dict:
        return rpc.call(f"{self.master_url}/vol/list")

    def data_nodes(self) -> list[dict]:
        """Flattened node list with dc/rack annotations."""
        out = []
        topo = self.topology()["topology"]
        for dc in topo["data_centers"]:
            for rack in dc["racks"]:
                for n in rack["nodes"]:
                    n = dict(n)
                    n["dc"] = dc["id"]
                    n["rack"] = rack["id"]
                    out.append(n)
        return out

    def volume_locations(self, vid: int) -> list[str]:
        """Always fresh from the master — maintenance decisions must not
        act on the client cache's 60s-stale view."""
        resp = rpc.call(f"{self.master_url}/dir/lookup?volumeId={vid}")
        return [loc["url"] for loc in resp.get("locations", [])]

    def ec_shard_locations(self, vid: int) -> dict[int, list[str]]:
        resp = rpc.call(f"{self.master_url}/dir/lookup?volumeId={vid}")
        return {int(s): [d["url"] for d in dns]
                for s, dns in resp.get("ecShards", {}).items()}

    def ec_codec(self, vid: int) -> str:
        """The erasure codec an EC volume was encoded with, as learned
        by the master from shard-holder heartbeats."""
        resp = rpc.call(f"{self.master_url}/dir/lookup?volumeId={vid}")
        return resp.get("ecCodec", "rs")

    def debug_servers(self, flags: dict) -> list[str]:
        """Base URLs for per-process debug surfaces (/debug/traces,
        /debug/faults, /debug/events): master first, then every
        registered volume server, then the filer — or just the
        -server flag's target.  The shared walk behind trace.ls,
        fault.ls/set, and events.ls."""
        if flags.get("server"):
            url = flags["server"]
            return [url if "://" in url else f"http://{url}"]
        urls = [self.master_url]
        try:
            urls += [f"http://{n['url']}" for n in self.data_nodes()]
        except Exception:  # noqa: BLE001 — master down: others may
            pass           # still answer
        if self.filer_url:
            urls.append(self.filer_url)
        return urls

    # -- volume server RPC shorthands ---------------------------------------

    def vs_call(self, url: str, path: str, payload: dict | None = None,
                timeout: float = 120.0) -> dict:
        return rpc.call_json(f"http://{url}{path}", payload=payload,
                             timeout=timeout)

    # -- exclusive admin lock ------------------------------------------------

    def lock(self, name: str = "shell") -> None:
        resp = rpc.call_json(f"{self.master_url}/admin/lease",
                             payload={"name": name,
                                      "token": self._lock_token})
        self._lock_token = resp["token"]
        ttl = float(resp.get("ttl", 10.0))
        self._schedule_renew(name, ttl / 2)

    def _schedule_renew(self, name: str, delay: float) -> None:
        self._cancel_renew()

        def renew():
            try:
                self.lock(name)
            except Exception:  # noqa: BLE001 — lost the lease; commands
                self._lock_token = None  # will fail confirm_is_locked

        self._renewer = threading.Timer(delay, renew)
        self._renewer.daemon = True
        self._renewer.start()

    def _cancel_renew(self) -> None:
        if self._renewer is not None:
            self._renewer.cancel()
            self._renewer = None

    def unlock(self) -> None:
        self._cancel_renew()
        if self._lock_token is not None:
            rpc.call_json(f"{self.master_url}/admin/release",
                          payload={"token": self._lock_token})
            self._lock_token = None

    def confirm_is_locked(self) -> None:
        if self._lock_token is None:
            raise ShellError(
                "lock is lost, or this command requires the `lock` first")

    def close(self) -> None:
        self._cancel_renew()
        try:
            self.unlock()
        except Exception:  # noqa: BLE001
            pass
