"""cluster.lifecycle / volume.tier.status — the data-lifecycle plane.

`cluster.lifecycle` renders the master daemon's status (rules, scan
history, recent actions) and can force a synchronous scan;
`volume.tier.status` walks every volume server's /debug/tier for
per-volume tier state plus the shared block cache's live numbers.
"""

from __future__ import annotations

import time

from ..cluster import rpc
from .commands import Command, register
from .env import CommandEnv, ShellError


@register
class ClusterLifecycle(Command):
    name = "cluster.lifecycle"
    help = ("cluster.lifecycle [run] — lifecycle daemon status (rules, "
            "scans, recent actions); `run` forces one policy scan now")

    def do(self, args: list[str], env: CommandEnv) -> str:
        _flags, rest = self.parse_flags(args)
        if rest and rest[0] == "run":
            out = rpc.call_json(f"{env.master_url}/cluster/lifecycle/run",
                                payload={}, timeout=300.0)
            return (f"scan complete: tiered={out.get('tiered', [])} "
                    f"vacuumed={out.get('vacuumed', [])} "
                    f"errors={len(out.get('errors', []))}")
        st = rpc.call(f"{env.master_url}/cluster/lifecycle", timeout=10.0)
        if not isinstance(st, dict):
            raise ShellError("bad /cluster/lifecycle answer")
        lines = [f"enabled: {st.get('enabled')}   "
                 f"interval: {st.get('interval')}s   "
                 f"scans: {st.get('scans')}   "
                 f"last_scan_age: {st.get('last_scan_age')}"]
        rules = st.get("rules", [])
        lines.append(f"rules ({len(rules)}):")
        for r in rules:
            cond = " ".join(f"{k}={v}" for k, v in sorted(r.items())
                            if k not in ("collection", "action"))
            lines.append(f"  {r.get('collection', '*'):12} "
                         f"{r.get('action', ''):7} {cond}")
        acts = st.get("actions", {})
        lines.append("actions: " + "  ".join(
            f"{k}={acts[k]}" for k in sorted(acts)))
        recent = st.get("recent", [])
        if recent:
            lines.append("recent:")
            for a in recent[-10:]:
                at = time.strftime("%H:%M:%S",
                                   time.localtime(a.get("at", 0)))
                extra = " ".join(
                    f"{k}={v}" for k, v in sorted(a.items())
                    if k not in ("at", "kind", "volume", "node"))
                lines.append(f"  {at}  {a.get('kind', ''):12} "
                             f"vol {a.get('volume')} @ "
                             f"{a.get('node')} {extra}")
        return "\n".join(lines)


@register
class VolumeTierStatus(Command):
    name = "volume.tier.status"
    help = ("volume.tier.status [-server host:port] — per-volume tier "
            "state and the remote block cache's live numbers from "
            "every volume server's /debug/tier")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _rest = self.parse_flags(args)
        if flags.get("server"):
            targets = [flags["server"]]
        else:
            targets = [n["url"] for n in env.data_nodes()]
        if not targets:
            raise ShellError("no volume servers registered")
        lines = [f"{'NODE':21}  {'VOL':>5}  {'COLLECTION':12}  "
                 f"{'TTL':6}  {'STATE':7}  REMOTE"]
        caches = []
        for url in targets:
            try:
                out = rpc.call(f"http://{url}/debug/tier", timeout=10.0)
            except Exception as e:  # noqa: BLE001
                lines.append(f"{url:21}  unreachable: {e}")
                continue
            if not isinstance(out, dict):
                continue
            caches.append((url, out.get("cache", {})))
            for v in out.get("volumes", []):
                state = "remote" if v.get("tiered") else "local"
                remote = ""
                if v.get("tiered"):
                    r = v.get("remote", {})
                    remote = (f"{r.get('backend_spec')} "
                              f"key={r.get('key')} "
                              f"hits={v.get('hits_in_window', 0)}")
                lines.append(f"{url:21}  {v.get('volume', 0):>5}  "
                             f"{v.get('collection') or '-':12}  "
                             f"{v.get('ttl') or '-':6}  {state:7}  "
                             f"{remote}")
        for url, c in caches:
            lines.append(
                f"cache @ {url}: {c.get('used_bytes', 0)}/"
                f"{c.get('max_bytes', 0)} bytes in "
                f"{c.get('blocks', 0)} blocks, "
                f"hit={c.get('hit_bytes', 0)}B "
                f"miss={c.get('miss_bytes', 0)}B "
                f"fetch p99={c.get('fetch_ms', {}).get('p99')}ms")
        return "\n".join(lines)
