"""fs.* and bucket.* shell commands: browse and manage the filer
namespace from the admin shell.

Reference: weed/shell/command_fs_cd.go, _ls.go, _du.go, _cat.go,
_tree.go, _mv.go, _rm (via fs delete), _pwd.go, _mkdir,
command_fs_meta_save.go / _load.go / _cat.go, command_bucket_create.go /
_delete.go / _list.go.
"""

from __future__ import annotations

import json

from .commands import Command, register
from .env import CommandEnv, ShellError

BUCKETS_PATH = "/buckets"


@register
class FsPwd(Command):
    name = "fs.pwd"
    help = "fs.pwd — print the current filer directory"

    def do(self, args: list[str], env: CommandEnv) -> str:
        return env.cwd


@register
class FsCd(Command):
    name = "fs.cd"
    help = "fs.cd <dir> — change the current filer directory"

    def do(self, args: list[str], env: CommandEnv) -> str:
        target = env.resolve(args[0] if args else "/")
        if target != "/":
            meta = env.filer().meta(target)
            if meta is None:
                raise ShellError(f"{target}: no such directory")
            if not meta.get("is_directory"):
                raise ShellError(f"{target}: not a directory")
        env.cwd = target
        return ""


@register
class FsLs(Command):
    name = "fs.ls"
    help = "fs.ls [-l] [dir]"

    def do(self, args: list[str], env: CommandEnv) -> str:
        # Boolean flags parsed by hand: the generic parser would eat a
        # following positional as the flag's value.
        long = "-l" in args
        rest = [a for a in args if not a.startswith("-")]
        path = env.resolve(rest[0] if rest else "")
        entries = env.filer().list_all(path)
        if not long:
            return "\n".join(e["name"] + ("/" if e["is_directory"]
                                          else "")
                             for e in entries)
        lines = []
        for e in entries:
            kind = "d" if e["is_directory"] else "-"
            mode = e.get("mode", 0)
            lines.append(f"{kind}{mode & 0o7777:04o} "
                         f"{e.get('size', 0):>12} {e['name']}")
        return "\n".join(lines)


@register
class FsDu(Command):
    name = "fs.du"
    help = "fs.du [dir] — recursive size/file/dir counts"

    def do(self, args: list[str], env: CommandEnv) -> str:
        root = env.resolve(args[0] if args else "")
        proxy = env.filer()
        total, files, dirs = 0, 0, 0
        stack = [root]
        while stack:
            d = stack.pop()
            for e in proxy.list_all(d):
                if e["is_directory"]:
                    dirs += 1
                    stack.append(e["FullPath"])
                else:
                    files += 1
                    total += e.get("size", 0)
        return (f"{total} bytes, {files} files, {dirs} directories "
                f"under {root}")


@register
class FsCat(Command):
    name = "fs.cat"
    help = "fs.cat <file>"

    def do(self, args: list[str], env: CommandEnv) -> str:
        import urllib.error
        if not args:
            raise ShellError("usage: fs.cat <file>")
        path = env.resolve(args[0])
        try:
            with env.filer().get(path) as resp:
                return resp.read().decode("utf-8", "replace")
        except urllib.error.HTTPError as e:
            raise ShellError(f"{path}: HTTP {e.code}") from None


@register
class FsTree(Command):
    name = "fs.tree"
    help = "fs.tree [dir]"

    def do(self, args: list[str], env: CommandEnv) -> str:
        root = env.resolve(args[0] if args else "")
        proxy = env.filer()
        lines = [root]

        def walk(d: str, prefix: str) -> None:
            entries = proxy.list_all(d)
            for i, e in enumerate(entries):
                last = i == len(entries) - 1
                branch = "└── " if last else "├── "
                lines.append(prefix + branch + e["name"] +
                             ("/" if e["is_directory"] else ""))
                if e["is_directory"]:
                    walk(e["FullPath"],
                         prefix + ("    " if last else "│   "))
        walk(root, "")
        return "\n".join(lines)


@register
class FsMkdir(Command):
    name = "fs.mkdir"
    help = "fs.mkdir <dir>"

    def do(self, args: list[str], env: CommandEnv) -> str:
        if not args:
            raise ShellError("usage: fs.mkdir <dir>")
        env.filer().mkdir(env.resolve(args[0]))
        return ""


@register
class FsMv(Command):
    name = "fs.mv"
    help = "fs.mv <src> <dst>"

    def do(self, args: list[str], env: CommandEnv) -> str:
        if len(args) != 2:
            raise ShellError("usage: fs.mv <src> <dst>")
        src, dst = env.resolve(args[0]), env.resolve(args[1])
        env.filer().rename(src, dst)
        return f"moved {src} -> {dst}"


@register
class FsRm(Command):
    name = "fs.rm"
    help = "fs.rm [-r] <path>"

    def do(self, args: list[str], env: CommandEnv) -> str:
        recursive = "-r" in args
        rest = [a for a in args if not a.startswith("-")]
        if not rest:
            raise ShellError("usage: fs.rm [-r] <path>")
        path = env.resolve(rest[0])
        if not env.filer().delete(path, recursive=recursive):
            raise ShellError(f"{path}: not found")
        return f"removed {path}"


# -- metadata export/import (command_fs_meta_save.go / _load.go) -----------

@register
class FsMetaSave(Command):
    name = "fs.meta.save"
    help = ("fs.meta.save [-o=meta.jsonl] [dir] — dump entries (with "
            "chunk lists) as JSONL")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, rest = self.parse_flags(args)
        root = env.resolve(rest[0] if rest else "")
        out_path = flags.get("o", "filer-meta.jsonl")
        proxy = env.filer()
        count = 0
        with open(out_path, "w") as f:
            stack = [root]
            while stack:
                d = stack.pop()
                for e in proxy.list_all(d):
                    full = proxy.meta(e["FullPath"])
                    if full is not None:
                        f.write(json.dumps(full,
                                           separators=(",", ":"))
                                + "\n")
                        count += 1
                    if e["is_directory"]:
                        stack.append(e["FullPath"])
        return f"saved {count} entries from {root} to {out_path}"


@register
class FsMetaLoad(Command):
    name = "fs.meta.load"
    help = "fs.meta.load <meta.jsonl> — re-create entries from a dump"

    def do(self, args: list[str], env: CommandEnv) -> str:
        if not args:
            raise ShellError("usage: fs.meta.load <meta.jsonl>")
        proxy = env.filer()
        count = 0
        with open(args[0]) as f:
            for line in f:
                if not line.strip():
                    continue
                entry = json.loads(line)
                if entry.get("is_directory"):
                    proxy.mkdir(entry["path"])
                else:
                    proxy.create_entry(entry["path"], entry)
                count += 1
        return f"loaded {count} entries"


@register
class FsMetaNotify(Command):
    """Walk a subtree and publish one create event per entry to the
    notification queue (command_fs_meta_notify.go) — bootstraps a
    freshly-attached replication sink with the existing namespace."""
    name = "fs.meta.notify"
    help = ("fs.meta.notify [-queue=<spec>] [dir] — publish a create "
            "event per entry (queue from notification.toml when no "
            "-queue)")

    def do(self, args: list[str], env: CommandEnv) -> str:
        from ..replication.notification import (queue_for_spec,
                                                queue_from_config)
        flags, rest = self.parse_flags(args)
        root = env.resolve(rest[0] if rest else "")
        spec = flags.get("queue", "")
        if spec:
            queue = queue_for_spec(spec)
        else:
            from ..utils.config import load_configuration
            queue = queue_from_config(
                load_configuration("notification"))
            if queue is None:
                raise ShellError(
                    "no notification queue: enable one in "
                    "notification.toml or pass -queue=<spec>")
        proxy = env.filer()
        count = 0
        stack = [root]
        while stack:
            d = stack.pop()
            for e in proxy.list_all(d):
                full = proxy.meta(e["FullPath"])
                if full is not None:
                    queue.publish(e["FullPath"],
                                  {"directory": d, "old_entry": None,
                                   "new_entry": full})
                    count += 1
                if e["is_directory"]:
                    stack.append(e["FullPath"])
        queue.close()
        return f"notified {count} entries under {root}"


@register
class FsMetaCat(Command):
    name = "fs.meta.cat"
    help = "fs.meta.cat <path> — print one entry's full metadata"

    def do(self, args: list[str], env: CommandEnv) -> str:
        if not args:
            raise ShellError("usage: fs.meta.cat <path>")
        meta = env.filer().meta(env.resolve(args[0]))
        if meta is None:
            raise ShellError(f"{args[0]}: not found")
        return json.dumps(meta, indent=2)


# -- buckets (command_bucket_*.go) -----------------------------------------

@register
class BucketList(Command):
    name = "bucket.list"
    help = "bucket.list"

    def do(self, args: list[str], env: CommandEnv) -> str:
        entries = env.filer().list_all(BUCKETS_PATH)
        return "\n".join(e["name"] for e in entries
                         if e["is_directory"]) or "no buckets"


@register
class BucketCreate(Command):
    name = "bucket.create"
    help = "bucket.create -name <bucket>"

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, rest = self.parse_flags(args)
        name = flags.get("name") or (rest[0] if rest else "")
        if not name:
            raise ShellError("bucket.create requires -name <bucket>")
        env.filer().mkdir(f"{BUCKETS_PATH}/{name}")
        return f"created bucket {name}"


@register
class BucketDelete(Command):
    name = "bucket.delete"
    help = "bucket.delete -name <bucket>"

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, rest = self.parse_flags(args)
        name = flags.get("name") or (rest[0] if rest else "")
        if not name:
            raise ShellError("bucket.delete requires -name <bucket>")
        if not env.filer().delete(f"{BUCKETS_PATH}/{name}",
                                  recursive=True):
            raise ShellError(f"bucket {name} not found")
        return f"deleted bucket {name}"
