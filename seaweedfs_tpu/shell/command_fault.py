"""fault.ls / fault.set — inspect and arm fault-injection points.

Fault points live per process (fault/registry.py) and are served by
each server's `/debug/faults` (mounted when the process was started
with SEAWEEDFS_TPU_FAULTS set, or SEAWEEDFS_TPU_FAULTS_DEBUG=1).
These commands aggregate across every reachable server — master, all
registered volume servers, and the filer when configured — mirroring
trace.ls/trace.get: in a multi-process deployment each process arms
its own faults.
"""

from __future__ import annotations

from ..cluster import rpc
from ..fault import registry as _registry
from .commands import Command, register
from .env import CommandEnv, ShellError


def _fetch(url: str, qs: str = "", method: str = "GET") -> dict | None:
    try:
        out = rpc.call(f"{url}/debug/faults{qs}", method, timeout=5.0)
        return out if isinstance(out, dict) else None
    except Exception:  # noqa: BLE001 — endpoint off / server gone
        return None


@register
class FaultLs(Command):
    name = "fault.ls"
    help = ("fault.ls [-server host:port] — fault-point catalog and "
            "armed state per server (needs servers started with "
            "SEAWEEDFS_TPU_FAULTS set)")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _rest = self.parse_flags(args)
        lines = [f"{'POINT':18}  DESCRIPTION"]
        for name in sorted(_registry.POINTS):
            lines.append(f"{name:18}  {_registry.POINTS[name]}")
        reached = 0
        armed_lines: list[str] = []
        for url in env.debug_servers(flags):
            out = _fetch(url)
            if out is None:
                continue
            reached += 1
            for row in out.get("points", []):
                if row.get("armed"):
                    armed_lines.append(
                        f"{url:28}  {row['point']:18}  "
                        f"{row.get('spec', '')}  "
                        f"hits={row.get('hits', 0)} "
                        f"triggered={row.get('triggered', 0)} "
                        f"remaining={row.get('remaining', -1)}")
        if not reached:
            raise ShellError(
                "no /debug/faults endpoint reachable — start servers "
                "with SEAWEEDFS_TPU_FAULTS set (may be empty) or "
                "SEAWEEDFS_TPU_FAULTS_DEBUG=1")
        lines.append("")
        if armed_lines:
            lines.append(f"{'SERVER':28}  {'POINT':18}  SPEC")
            lines += armed_lines
        else:
            lines.append(f"nothing armed on {reached} server(s)")
        return "\n".join(lines)


@register
class FaultSet(Command):
    name = "fault.set"
    help = ("fault.set <point> <spec|off> [-server host:port] — arm "
            "(or disarm) a fault point on every reachable server; "
            "spec grammar: kind[:arg][*times][@prob][~match], kinds "
            "fail|delay|status|drop (see README Robustness)")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, rest = self.parse_flags(args)
        if len(rest) < 2:
            raise ShellError(
                "usage: fault.set <point> <spec|off> [-server ...]")
        point, spec = rest[0], rest[1]
        if spec not in ("off", "none"):
            # Validate locally before spraying it at the cluster.
            if point not in _registry.POINTS:
                raise ShellError(f"unknown fault point {point!r}")
            try:
                _registry.FaultSpec(point, spec)
            except ValueError as e:
                raise ShellError(str(e)) from None
        import urllib.parse
        qs = (f"?point={urllib.parse.quote(point)}"
              f"&spec={urllib.parse.quote(spec)}")
        done, failed = [], []
        for url in env.debug_servers(flags):
            out = _fetch(url, qs, method="POST")
            (done if out is not None else failed).append(url)
        if not done:
            raise ShellError(
                "no /debug/faults endpoint accepted the change — "
                "start servers with SEAWEEDFS_TPU_FAULTS set")
        verb = "disarmed" if spec in ("off", "none") else \
            f"armed {spec!r}"
        out = [f"{point}: {verb} on {len(done)} server(s)"]
        out += [f"  {u}" for u in done]
        if failed:
            out.append(f"unreachable/disabled: {len(failed)}")
        return "\n".join(out)
