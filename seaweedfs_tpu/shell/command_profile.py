"""cluster.profile — one flamegraph for the whole cluster, plus diffs.

Fans out to every reachable server's `/debug/pprof/profile` (mounted
with SEAWEEDFS_TPU_PPROF=1), pulls collapsed stacks — instantly from
each node's always-on ring (`?window=N`) or via a live sample
(`-seconds S`) — and merges them into ONE collapsed-stack corpus with
each stack rooted at a `node:<host:port>` frame, so a single
flamegraph shows the cluster's time split first by node, then by code.

`-diff baseline.collapsed` compares the live merge against a saved
baseline (node frames stripped, counts normalized to per-mille of
total samples) and ranks the biggest stack-share movements — the
gating artifact for hot-path refactors: profile before, land the
change, profile after, and the diff names exactly which stacks paid.
"""

from __future__ import annotations

from collections import Counter

from ..cluster import rpc
from .commands import Command, register
from .env import CommandEnv, ShellError

NODE_FRAME_PREFIX = "node:"


def parse_collapsed(text: str) -> Counter:
    """`frame;frame;... count` lines -> Counter keyed by the stack
    string.  Unparseable lines are skipped (profiles are operator
    artifacts, not a wire format)."""
    out: Counter = Counter()
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, count = line.rpartition(" ")
        if not stack or not count.isdigit():
            continue
        out[stack] += int(count)
    return out


def strip_node_frames(counts: Counter) -> Counter:
    """Drop the leading `node:<addr>` frame so profiles from different
    clusters/ports compare stack-for-stack in -diff."""
    out: Counter = Counter()
    for stack, n in counts.items():
        frames = stack.split(";")
        if frames and frames[0].startswith(NODE_FRAME_PREFIX):
            frames = frames[1:]
        if frames:
            out[";".join(frames)] += n
    return out


def fetch_node_profile(url: str, seconds: float | None,
                       window: int | None,
                       timeout: float = 45.0) -> Counter | None:
    """One node's collapsed stacks, each prefixed with its node frame;
    None when the node has no pprof surface (env off / unreachable)."""
    if seconds is not None:
        qs = f"?format=collapsed&seconds={seconds:g}"
    else:
        qs = f"?format=collapsed&window={window or 5}"
    try:
        raw = rpc.call(f"{url}/debug/pprof/profile{qs}",
                       timeout=timeout)
    except Exception:  # noqa: BLE001 — node gone or pprof off
        return None
    if isinstance(raw, dict):  # error doc from a JSON answer
        return None
    node = url.split("://", 1)[-1]
    counts: Counter = Counter()
    for stack, n in parse_collapsed(
            raw.decode("utf-8", "replace")).items():
        counts[f"{NODE_FRAME_PREFIX}{node};{stack}"] += n
    return counts


def merge_cluster_profile(urls: list[str], seconds: float | None = None,
                          window: int | None = None) -> tuple[Counter,
                                                              list[str]]:
    """Fan out + merge; returns (merged counts, nodes that answered).
    Live samples (`seconds`) run CONCURRENTLY so a 10s cluster profile
    costs 10s, not 10s x nodes — and every node samples the same
    interval of cluster time."""
    merged: Counter = Counter()
    nodes: list[str] = []
    if seconds is None:
        for url in urls:
            c = fetch_node_profile(url, None, window)
            if c is not None:
                merged.update(c)
                nodes.append(url)
        return merged, nodes
    import concurrent.futures
    with concurrent.futures.ThreadPoolExecutor(
            max_workers=max(len(urls), 1)) as pool:
        futs = {pool.submit(fetch_node_profile, url, seconds, None):
                url for url in urls}
        for fut in concurrent.futures.as_completed(futs):
            c = fut.result()
            if c is not None:
                merged.update(c)
                nodes.append(futs[fut])
    return merged, nodes


def diff_profiles(baseline: Counter, current: Counter,
                  top: int = 20) -> list[dict]:
    """Rank stacks by |share delta| (per-mille of total samples) —
    share, not raw counts, so a longer/denser profile doesn't read as
    'everything got slower'."""
    base_total = sum(baseline.values()) or 1
    cur_total = sum(current.values()) or 1
    deltas = []
    for stack in set(baseline) | set(current):
        b = baseline.get(stack, 0) / base_total
        c = current.get(stack, 0) / cur_total
        if b == c:
            continue
        deltas.append({"stack": stack,
                       "baseline_share": b, "current_share": c,
                       "delta_share": c - b})
    deltas.sort(key=lambda d: -abs(d["delta_share"]))
    return deltas[:top]


@register
class ClusterProfile(Command):
    name = "cluster.profile"
    help = ("cluster.profile [-seconds N | -window N] [-node "
            "host:port] [-o out.collapsed] [-diff baseline.collapsed] "
            "[-top N] — merge every node's /debug/pprof stacks into "
            "one cluster flamegraph input (stacks rooted at "
            "node:<addr>).  Default: instant, from each node's "
            "always-on ring (last 5 windows); -seconds takes a live "
            "concurrent sample.  -o writes collapsed stacks for "
            "flamegraph.pl/speedscope; -diff ranks stack-share "
            "deltas vs a saved baseline (the refactor gate)")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _rest = self.parse_flags(args)
        seconds = window = None
        if flags.get("seconds"):
            try:
                seconds = min(max(float(flags["seconds"]), 0.1), 30.0)
            except ValueError:
                raise ShellError(
                    f"-seconds {flags['seconds']!r} is not a number") \
                    from None
        elif flags.get("window"):
            try:
                window = max(1, int(flags["window"]))
            except ValueError:
                raise ShellError(
                    f"-window {flags['window']!r} is not a number") \
                    from None
        try:
            top = int(flags.get("top", "20"))
        except ValueError:
            raise ShellError(
                f"-top {flags['top']!r} is not a number") from None
        if flags.get("node"):
            node = flags["node"]
            urls = [node if "://" in node else f"http://{node}"]
        else:
            urls = env.debug_servers({})
        merged, nodes = merge_cluster_profile(urls, seconds, window)
        if not nodes:
            raise ShellError(
                "no /debug/pprof/profile endpoint reachable — start "
                "servers with SEAWEEDFS_TPU_PPROF=1")
        total = sum(merged.values())
        lines = [f"{len(nodes)} node(s), {total} samples "
                 + (f"(live {seconds:g}s sample)" if seconds is not None
                    else f"(ring, last {window or 5} windows)")]
        if flags.get("o"):
            with open(flags["o"], "w") as f:
                for stack, n in merged.most_common():
                    f.write(f"{stack} {n}\n")
            lines.append(f"wrote {len(merged)} collapsed stacks to "
                         f"{flags['o']} (flamegraph.pl / speedscope "
                         f"input)")
        if flags.get("diff"):
            try:
                with open(flags["diff"]) as f:
                    baseline = parse_collapsed(f.read())
            except OSError as e:
                raise ShellError(
                    f"cannot read baseline {flags['diff']}: {e}") \
                    from None
            rows = diff_profiles(strip_node_frames(baseline),
                                 strip_node_frames(merged), top)
            lines.append("")
            lines.append(f"{'DELTA':>8}  {'BASE':>6}  {'NOW':>6}  "
                         "STACK (leaf last; shares in per-mille of "
                         "samples)")
            for d in rows:
                stack = d["stack"]
                if len(stack) > 110:
                    stack = "..." + stack[-107:]
                lines.append(
                    f"{1000 * d['delta_share']:+8.1f}  "
                    f"{1000 * d['baseline_share']:6.1f}  "
                    f"{1000 * d['current_share']:6.1f}  {stack}")
            if not rows:
                lines.append("no stack-share movement vs baseline")
            return "\n".join(lines)
        lines.append("")
        lines.append(f"{'SAMPLES':>8}  {'SHARE':>6}  STACK (leaf last)")
        for stack, n in merged.most_common(top):
            s = stack if len(stack) <= 110 else "..." + stack[-107:]
            lines.append(f"{n:8d}  {100.0 * n / total:5.1f}%  {s}")
        return "\n".join(lines)
