"""cluster.mirror.* — operate the cross-cluster async mirror.

The mirror pairing itself is configuration (`-replicate.peer` on the
primary's volume servers, `-replicate.lag.slo` on the master); these
commands are the runbook verbs on top of it:

- `cluster.mirror.status`   one table: per-volume ship watermarks and
                            lag, from the master's `/cluster/mirror`
                            rollup (heartbeat-fed), plus each node's
                            `/debug/replication` role.
- `cluster.mirror.pause`    stop shipping (the change logs keep
                            journaling; lag grows) — the knob for WAN
                            maintenance windows.
- `cluster.mirror.resume`   start shipping again and kick an immediate
                            tick.
- `cluster.mirror.cutover`  the verified failover: drain the primary's
                            volume servers (new writes refused with
                            503 + Retry-After — PR 5 drain semantics),
                            wait until every change log is acked up to
                            its last record, then pause the shippers
                            and declare the standby authoritative.
                            Zero acked-write loss by construction: a
                            write is only acked to clients after it is
                            journaled, and cutover only completes after
                            every journaled record is acked by the
                            standby.

Convergence after cutover is machine-checkable with
`volume.fsck -crc -json` against both clusters (README "Disaster
recovery").
"""

from __future__ import annotations

import time

from ..cluster import rpc
from ..events import emit as emit_event
from .commands import Command, register
from .env import CommandEnv, ShellError


def _mirror_doc(env: CommandEnv) -> dict:
    try:
        out = rpc.call(f"{env.master_url}/cluster/mirror", timeout=10.0)
    except Exception as e:  # noqa: BLE001
        raise ShellError(
            f"cannot reach {env.master_url}/cluster/mirror: {e}") \
            from None
    if not isinstance(out, dict):
        raise ShellError(f"unexpected /cluster/mirror reply: {out!r}")
    return out


def _shipper_nodes(env: CommandEnv) -> list[tuple[str, dict]]:
    """(node url, /debug/replication doc) for every data node that has
    a shipper configured — the primary side of the mirror."""
    out = []
    for n in env.data_nodes():
        try:
            doc = rpc.call(f"http://{n['url']}/debug/replication",
                           timeout=5.0)
        except Exception:  # noqa: BLE001 — node gone mid-walk
            continue
        if isinstance(doc, dict) and "primary" in doc.get("role", []):
            out.append((n["url"], doc))
    return out


@register
class ClusterMirrorStatus(Command):
    name = "cluster.mirror.status"
    help = ("cluster.mirror.status [-watch] [-interval S] [-count N] "
            "— per-volume mirror state from the master's "
            "/cluster/mirror: change-log watermarks, ship lag "
            "(records + seconds), pause state, geo lease holders, and "
            "the lag SLO.  -watch repolls every -interval seconds "
            "(default 2) until interrupted (or -count polls)")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _rest = self.parse_flags(args)
        watch = flags.get("watch") == "true"
        interval = float(flags.get("interval", "2"))
        count = int(flags.get("count", "0"))
        if not watch:
            return self._render(_mirror_doc(env))
        polls = 0
        out = ""
        try:
            while True:
                out = self._render(_mirror_doc(env))
                polls += 1
                if count and polls >= count:
                    break
                print(out)
                print("---")
                time.sleep(interval)
        except KeyboardInterrupt:
            pass
        return out

    @staticmethod
    def _render(doc: dict) -> str:
        if not doc.get("paired"):
            return ("not paired: no volume server reports a "
                    "-replicate.peer")
        lines = [f"peer(s): {', '.join(doc.get('peers', [])) or '-'}"
                 + (f"  lag SLO: {doc['lag_slo']:g}s"
                    if doc.get("lag_slo") is not None else "")
                 + ("  CAUGHT UP" if doc.get("caught_up")
                    else "  SHIPPING")]
        if doc.get("cluster_id"):
            lines[0] += f"  cluster: {doc['cluster_id']}"
        if doc.get("paused_nodes"):
            lines.append("paused: "
                         + ", ".join(doc["paused_nodes"]))
        leases = doc.get("leases") or {}
        rows = doc.get("volumes", [])
        if rows:
            lines.append("")
            lines.append(f"{'VOLUME':>6}  {'NODE':21}  {'LAST':>8}  "
                         f"{'ACKED':>8}  {'LAG':>6}  {'LAG SEC':>8}  "
                         f"{'LEASE':12}")
            for r in sorted(rows, key=lambda r: (r["volume"],
                                                 r["node"])):
                lr = leases.get(str(r["volume"]))
                lease = (f"{lr['cluster_id']}@e{lr['epoch']}"
                         + ("*" if lr.get("moving") else "")
                         if lr else "-")
                lines.append(
                    f"{r['volume']:6d}  {r['node']:21}  "
                    f"{r.get('last_seq', 0):8d}  "
                    f"{r.get('acked_seq', 0):8d}  "
                    f"{r.get('lag_seq', 0):6d}  "
                    f"{r.get('lag_seconds', 0.0):8.1f}  "
                    f"{lease:12}")
        return "\n".join(lines)


class _PauseResume(Command):
    _pause = True

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _rest = self.parse_flags(args)
        verb = "pause" if self._pause else "resume"
        if flags.get("node"):
            nodes = [flags["node"]]
        else:
            nodes = [u for u, _doc in _shipper_nodes(env)]
        if not nodes:
            raise ShellError("no volume server with a shipper "
                             "(-replicate.peer) reachable")
        done = []
        for node in nodes:
            try:
                env.vs_call(node, f"/admin/replication/{verb}",
                            payload={}, timeout=10.0)
                done.append(node)
            except rpc.RpcError as e:
                if e.status != 400:  # 400 = no shipper there
                    raise ShellError(
                        f"cannot {verb} shipping on {node}: {e}") \
                        from None
        if not done:
            raise ShellError(f"no shipper {verb}d")
        return (f"shipping {verb}d on {len(done)} node(s): "
                + ", ".join(done))


@register
class ClusterMirrorPause(_PauseResume):
    name = "cluster.mirror.pause"
    help = ("cluster.mirror.pause [-node host:port] — stop shipping "
            "change-log batches to the standby (journaling continues; "
            "lag grows until resume)")
    _pause = True


@register
class ClusterMirrorResume(_PauseResume):
    name = "cluster.mirror.resume"
    help = ("cluster.mirror.resume [-node host:port] — resume shipping "
            "and kick an immediate tick")
    _pause = False


@register
class ClusterMirrorCutover(Command):
    name = "cluster.mirror.cutover"
    help = ("cluster.mirror.cutover [-grace N] [-timeout N] — verified "
            "failover to the standby cluster: drain every primary "
            "volume server (new writes 503 + Retry-After), wait until "
            "each change log is acked up to its last record, pause the "
            "shippers, and declare the standby authoritative.  "
            "Requires `lock`.  Zero acked-write loss: cutover only "
            "completes once every journaled record is acked")

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, _rest = self.parse_flags(args)
        grace = float(flags.get("grace", "30"))
        deadline = time.monotonic() + float(flags.get("timeout", "60"))
        t0 = time.monotonic()

        # 1. Who ships?  Collect BEFORE draining: a drained node says
        # goodbye to the master and drops out of the topology walk.
        primaries = _shipper_nodes(env)
        if not primaries:
            raise ShellError("no volume server with a shipper "
                             "(-replicate.peer) reachable — nothing "
                             "to cut over")
        peers = sorted({doc.get("shipper", {}).get("peer", "")
                        for _u, doc in primaries if doc.get("shipper")})

        # 2. Drain the primary: from here on, no client write can land,
        # so the change logs stop growing and catch-up can terminate.
        for node, _doc in primaries:
            try:
                env.vs_call(node, "/admin/drain",
                            payload={"grace": grace},
                            timeout=grace + 10.0)
            except Exception as e:  # noqa: BLE001
                raise ShellError(
                    f"cannot drain {node}: {e}") from None

        # 3. Standby catches up: every journaled record acked.  The
        # drained servers keep serving admin/debug routes and their
        # shippers keep shipping; resume-kick forces immediate ticks.
        volumes = 0
        while True:
            behind = []
            volumes = 0
            for node, _doc in primaries:
                try:
                    doc = rpc.call(
                        f"http://{node}/debug/replication",
                        timeout=5.0)
                except Exception as e:  # noqa: BLE001
                    raise ShellError(
                        f"{node} unreachable during catch-up: {e}") \
                        from None
                for vid, st in (doc.get("rlog") or {}).items():
                    volumes += 1
                    if st.get("acked_seq", 0) < st.get("last_seq", 0):
                        behind.append((node, vid,
                                       st["last_seq"]
                                       - st["acked_seq"]))
            if not behind:
                break
            if time.monotonic() > deadline:
                detail = ", ".join(
                    f"volume {vid}@{node} {n} record(s) behind"
                    for node, vid, n in behind[:8])
                raise ShellError(
                    f"cutover timed out waiting for catch-up: {detail}")
            for node, _doc in primaries:
                try:  # resume == kick: ship NOW, not next tick
                    env.vs_call(node, "/admin/replication/resume",
                                payload={}, timeout=10.0)
                except Exception:  # noqa: BLE001
                    pass
            time.sleep(0.05)

        # 4. Quiesce the old primary's shippers: the standby is
        # authoritative now; nothing must ship INTO it as a mirror.
        for node, _doc in primaries:
            try:
                env.vs_call(node, "/admin/replication/pause",
                            payload={}, timeout=10.0)
            except Exception:  # noqa: BLE001 — already drained away
                pass

        seconds = round(time.monotonic() - t0, 3)
        emit_event("replication.cutover",
                   peers=",".join(p for p in peers if p),
                   drained=",".join(u for u, _d in primaries),
                   volumes=volumes, seconds=seconds)
        return ("cutover complete in "
                f"{seconds:.1f}s: {len(primaries)} primary node(s) "
                f"drained, {volumes} change log(s) fully acked, "
                "shipping paused.  The standby cluster is "
                "authoritative — point clients at its master"
                + (f" ({', '.join(p for p in peers if p)})"
                   if any(peers) else "")
                + ".  Verify convergence: volume.fsck -crc -json "
                  "against both clusters")


@register
class ClusterLeaseLs(Command):
    name = "cluster.lease.ls"
    help = ("cluster.lease.ls — per-volume geo write leases from the "
            "master's /cluster/mirror rollup: holding cluster, fencing "
            "epoch, and whether a transfer is mid-drain")

    def do(self, args: list[str], env: CommandEnv) -> str:
        doc = _mirror_doc(env)
        leases = doc.get("leases") or {}
        if not leases:
            return ("no geo leases: no volume server reports a "
                    ".lease sidecar (active/passive mirroring, or "
                    "-geo.cluster.id unset)")
        lines = []
        if doc.get("cluster_id"):
            lines.append(f"this cluster: {doc['cluster_id']}")
        lines.append(f"{'VOLUME':>6}  {'NODE':21}  {'HOLDER':10}  "
                     f"{'EPOCH':>6}  {'LOCAL':>5}  {'MOVING':>6}")
        for vid, lr in sorted(leases.items(), key=lambda kv:
                              int(kv[0])):
            lines.append(
                f"{int(vid):6d}  {lr.get('node', '-'):21}  "
                f"{lr.get('cluster_id', '?'):10}  "
                f"{lr.get('epoch', 0):6d}  "
                f"{'yes' if lr.get('holder_is_local') else 'no':>5}  "
                f"{'yes' if lr.get('moving') else 'no':>6}")
        return "\n".join(lines)


@register
class ClusterLeaseMove(Command):
    name = "cluster.lease.move"
    help = ("cluster.lease.move -volume V -to CLUSTER [-timeout N] — "
            "transfer a volume's geo write lease to the named peer "
            "cluster: the holder refuses new writes, drains its "
            "change log to the peer, then demotes itself at epoch+1 "
            "BEFORE the peer acquires (a partition mid-move leaves NO "
            "holder — fail-closed, never split-brained).  Requires "
            "`lock`")

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, _rest = self.parse_flags(args)
        if not flags.get("volume") or not flags.get("to"):
            raise ShellError("usage: cluster.lease.move -volume V "
                             "-to CLUSTER [-timeout N]")
        vid = int(flags["volume"])
        to = flags["to"]
        timeout = float(flags.get("timeout", "10"))
        try:
            out = rpc.call(
                f"{env.master_url}/dir/lookup?volumeId={vid}",
                timeout=10.0)
            locs = out.get("locations") or []
        except Exception as e:  # noqa: BLE001
            raise ShellError(f"lookup of volume {vid} failed: {e}") \
                from None
        if not locs:
            raise ShellError(f"volume {vid}: no locations known to "
                             f"{env.master_url}")
        node = locs[0].get("url") or locs[0].get("publicUrl")
        try:
            doc = env.vs_call(node, "/admin/lease/move",
                              payload={"volume": vid, "to": to,
                                       "timeout": timeout},
                              timeout=timeout + 10.0)
        except rpc.RpcError as e:
            raise ShellError(
                f"lease move failed on {node}: {e.message}") from None
        msg = (f"volume {vid}: lease moved to cluster {to} at epoch "
               f"{doc.get('epoch')} (drained on {node})")
        if not doc.get("peer_acquired"):
            msg += ("\nwarning: " + doc.get(
                "warning", "peer did not confirm the acquire"))
        return msg
