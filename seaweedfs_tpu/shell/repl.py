"""Interactive admin shell (reference: weed/shell/shell_liner.go)."""

from __future__ import annotations

import sys

from .commands import run_command
from .env import CommandEnv, ShellError


def run_shell(master_url: str, commands: list[str] | None = None,
              filer_url: str | None = None) -> int:
    """REPL against a master; with `commands`, run them and exit."""
    env = CommandEnv(master_url, filer_url=filer_url)
    rc = 0
    try:
        if commands:
            for line in commands:
                try:
                    out = run_command(env, line)
                    if out:
                        print(out)
                except (ShellError, Exception) as e:  # noqa: BLE001
                    print(f"error: {e}", file=sys.stderr)
                    rc = 1
            return rc
        print(f"connected to {master_url} — `help` lists commands, "
              "`exit` quits")
        while True:
            try:
                line = input("> ")
            except (EOFError, KeyboardInterrupt):
                break
            if line.strip() in ("exit", "quit"):
                break
            try:
                out = run_command(env, line)
                if out:
                    print(out)
            except ShellError as e:
                print(f"error: {e}", file=sys.stderr)
            except Exception as e:  # noqa: BLE001 — keep the REPL alive
                print(f"error: {type(e).__name__}: {e}", file=sys.stderr)
        return rc
    finally:
        env.close()
