"""EC lifecycle commands: ec.encode / ec.rebuild / ec.balance / ec.decode.

Reference: weed/shell/command_ec_encode.go (freeze -> generate -> spread ->
delete original, :55-264), command_ec_rebuild.go (:57-240),
command_ec_balance.go (dedupe + spread), command_ec_decode.go, and the
shared helpers in command_ec_common.go (collectEcNodes, moveMountedShard).
"""

from __future__ import annotations

from ..cluster import rpc
from ..ec import TOTAL_SHARDS
from ..ec.shard_bits import ShardBits
from .commands import Command, register
from .env import CommandEnv, ShellError

ECX_EXTS = (".ecx", ".ecj", ".vif")


# -- shared helpers (command_ec_common.go) ----------------------------------

def collect_ec_nodes(env: CommandEnv, dc: str = "") -> list[dict]:
    """Data nodes with free EC-slot estimates, most-free first
    (collectEcNodes / sortEcNodesByFreeslotsDecending)."""
    nodes = []
    for n in env.data_nodes():
        if dc and n["dc"] != dc:
            continue
        shard_count = sum(
            ShardBits(e["shard_bits"]).shard_id_count()
            for e in n["ec_shards"])
        # One volume slot holds ~10 shards (erasure_coding.DataShardsCount).
        free = n["max_volume_count"] * 10 - len(n["volumes"]) * 10 \
            - shard_count
        n = dict(n)
        n["ec_shard_count"] = shard_count
        n["free_ec_slots"] = max(free, 0)
        nodes.append(n)
    nodes.sort(key=lambda n: -n["free_ec_slots"])
    return nodes


def node_shard_map(env: CommandEnv, vid: int) -> dict[str, list[int]]:
    """url -> sorted shard ids currently held for vid."""
    out: dict[str, list[int]] = {}
    for sid, urls in env.ec_shard_locations(vid).items():
        for url in urls:
            out.setdefault(url, []).append(sid)
    return {u: sorted(s) for u, s in out.items()}


def copy_shards(env: CommandEnv, vid: int, target: str, source: str,
                shards: list[int], copy_ecx: bool = False) -> None:
    env.vs_call(target, "/admin/ec/copy_shard",
                {"volume": vid, "source": source, "shards": shards,
                 "copy_ecx": copy_ecx})


def mount_shards(env: CommandEnv, vid: int, url: str) -> None:
    env.vs_call(url, "/admin/ec/mount", {"volume": vid})


def delete_shards(env: CommandEnv, vid: int, url: str,
                  shards: list[int]) -> None:
    env.vs_call(url, "/admin/ec/delete_shards",
                {"volume": vid, "shards": shards})


def move_shard(env: CommandEnv, vid: int, sid: int, source: str,
               target: str) -> None:
    """Copy -> mount on target -> delete from source (moveMountedShard)."""
    copy_shards(env, vid, target, source, [sid], copy_ecx=True)
    mount_shards(env, vid, target)
    delete_shards(env, vid, source, [sid])


def balanced_distribution(nodes: list[dict],
                          n_shards: int = TOTAL_SHARDS
                          ) -> dict[str, list[int]]:
    """Round-robin shard ids over nodes that still have free slots
    (balancedEcDistribution, command_ec_encode.go:248-264) — spreading
    wide maximises surviving shards when a node dies."""
    if not nodes:
        raise ShellError("no data nodes available for EC spread")
    picked: dict[str, list[int]] = {n["url"]: [] for n in nodes}
    free = {n["url"]: n["free_ec_slots"] for n in nodes}
    order = [n["url"] for n in nodes]
    sid, i, stuck = 0, 0, 0
    while sid < n_shards:
        url = order[i % len(order)]
        i += 1
        if free[url] > 0:
            picked[url].append(sid)
            free[url] -= 1
            sid += 1
            stuck = 0
        else:
            stuck += 1
            if stuck >= len(order):  # no free slots anywhere: overflow
                free[max(free, key=free.get)] += 1  # type: ignore[arg-type]
    return {u: s for u, s in picked.items() if s}


# -- ec.encode ---------------------------------------------------------------

@register
class EcEncode(Command):
    name = "ec.encode"
    help = ("ec.encode -volumeId <id>[,<id>...] | -collection <name> "
            "[-fullPercent 95] [-codec rs|lrc] [-batch] "
            "[-maxBatchMB 256] — erasure-code volumes and spread the "
            "shards across the cluster.  Default: per-volume generate "
            "on the holder (VolumeEcShardsGenerate).  -codec lrc: "
            "LRC(10,2,2) — single-shard repair reads 5 shards instead "
            "of 10.  -batch: pull quiet volumes, encode MANY at once "
            "in mesh-batched compiled steps (volumes data-parallel "
            "over chips), scatter shards + .ecx back (SURVEY §2.3 "
            "'shard scatter after encode')")

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, _ = self.parse_flags(args)
        from ..codecs import get_codec
        codec = get_codec(flags.get("codec") or None).name
        vids = self._collect_vids(flags, env)
        if not vids:
            return "no volumes to encode"
        if flags.get("batch") == "true":
            return self.encode_batch(env, vids, flags, codec)
        out = []
        for vid in vids:
            out.append(self.encode_one(env, vid, codec))
        return "\n".join(out)

    def encode_batch(self, env: CommandEnv, vids: list[int],
                     flags: dict, codec: str = "rs") -> str:
        from ..parallel import cluster_encode
        mesh = cluster_encode.make_mesh()
        max_mb = int(flags.get("maxBatchMB", 256))
        messages = cluster_encode.batch_encode(
            env, vids, mesh=mesh, max_batch_bytes=max_mb << 20,
            codec=codec)
        return "\n".join(messages) or "no volumes to encode"

    def _collect_vids(self, flags: dict, env: CommandEnv) -> list[int]:
        if "volumeId" in flags:
            return [int(v) for v in flags["volumeId"].split(",")]
        collection = flags.get("collection", "")
        full_pct = float(flags.get("fullPercent", 95))
        topo = env.topology()
        limit = topo["volume_size_limit"]
        vids = set()
        for dc in topo["topology"]["data_centers"]:
            for rack in dc["racks"]:
                for n in rack["nodes"]:
                    for v in n["volumes"]:
                        if v.get("collection", "") != collection:
                            continue
                        if v["size"] >= limit * full_pct / 100.0:
                            vids.add(v["id"])
        return sorted(vids)

    def encode_one(self, env: CommandEnv, vid: int,
                   codec: str = "rs") -> str:
        from ..codecs import get_codec
        total = get_codec(codec).total_shards
        locations = env.volume_locations(vid)
        if not locations:
            raise ShellError(f"volume {vid} not found")
        # 1. freeze: mark every replica readonly (markVolumeReadonly).
        for url in locations:
            env.vs_call(url, "/admin/readonly",
                        {"volume": vid, "readonly": True})
        # 2. generate the codec's shards + .ecx + .vif on one holder.
        source = locations[0]
        env.vs_call(source, "/admin/ec/generate",
                    {"volume": vid, "codec": codec})
        # 3. spread: balanced distribution over free slots.
        plan = balanced_distribution(collect_ec_nodes(env),
                                     n_shards=total)
        # Copy everywhere before trimming anything: the source must keep
        # its full set until every target has pulled its shards.
        for url, shards in plan.items():
            if url != source:
                copy_shards(env, vid, url, source, shards, copy_ecx=True)
        for url, shards in plan.items():
            mount_shards(env, vid, url)
            drop = [s for s in range(total) if s not in shards]
            if url == source:
                delete_shards(env, vid, url, drop)
            # Non-source targets only ever copied their own shards.
        if source not in plan:  # source got no shards: clear its full set
            delete_shards(env, vid, source, list(range(total)))
        # 4. delete the original volume from every replica.
        for url in locations:
            env.vs_call(url, "/admin/delete_volume", {"volume": vid})
        return (f"volume {vid} -> ec shards on "
                f"{len(plan)} servers: "
                + ", ".join(f"{u}:{s}" for u, s in sorted(plan.items())))


# -- ec.rebuild --------------------------------------------------------------

@register
class EcRebuild(Command):
    name = "ec.rebuild"
    help = ("ec.rebuild [-volumeId <id>[,<id>...]] [-batch] "
            "[-maxBatchMB 256] — regenerate missing EC shards.  Default: "
            "one volume at a time on a rebuilder node.  -batch: gather "
            "survivors from their holders, rebuild EVERY volume in "
            "mesh-batched compiled steps (volumes data-parallel over "
            "chips), scatter the shards back — the multi-volume path "
            "(BASELINE configs #3/#5)")

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, _ = self.parse_flags(args)
        if "volumeId" in flags:
            vids = [int(v) for v in flags["volumeId"].split(",")]
        else:
            vids = self._all_ec_vids(env)
        if flags.get("batch") == "true":
            return self.rebuild_batch(env, vids, flags)
        out = []
        for vid in vids:
            msg = self.rebuild_one(env, vid)
            if msg:
                out.append(msg)
        return "\n".join(out) or "nothing to rebuild"

    def rebuild_batch(self, env: CommandEnv, vids: list[int],
                      flags: dict) -> str:
        from ..parallel import cluster_rebuild
        mesh = cluster_rebuild.make_mesh()
        max_mb = int(flags.get("maxBatchMB", 256))
        messages = cluster_rebuild.batch_rebuild(
            env, vids, mesh=mesh, max_batch_bytes=max_mb << 20)
        return "\n".join(messages) or "nothing to rebuild"

    def _all_ec_vids(self, env: CommandEnv) -> list[int]:
        vids = set()
        for n in env.data_nodes():
            for e in n["ec_shards"]:
                vids.add(e["id"])
        return sorted(vids)

    def rebuild_one(self, env: CommandEnv, vid: int) -> str | None:
        from ..codecs import get_codec
        codec = get_codec(env.ec_codec(vid))
        holders = node_shard_map(env, vid)
        present = sorted({s for shards in holders.values() for s in shards})
        missing = [s for s in range(codec.total_shards)
                   if s not in present]
        if not missing:
            return None
        try:
            codec.repair_plan(tuple(present), missing)
        except ValueError:
            raise ShellError(
                f"volume {vid}: only {len(present)} shards survive; "
                "cannot rebuild") from None
        # Rebuilder: the holder with most shards (prepareDataToRecover
        # copies the rest to it).
        rebuilder = max(holders, key=lambda u: len(holders[u]))
        local = set(holders[rebuilder])
        borrowed: list[int] = []
        for url, shards in holders.items():
            if url == rebuilder:
                continue
            need = [s for s in shards if s not in local and
                    s not in borrowed]
            if need:
                copy_shards(env, vid, rebuilder, url, need, copy_ecx=True)
                borrowed.extend(need)
        resp = env.vs_call(rebuilder, "/admin/ec/rebuild", {"volume": vid})
        rebuilt = resp.get("rebuilt_shards", missing)
        # Keep only (original locals + rebuilt missing); drop borrowed helps.
        drop = [s for s in borrowed if s not in rebuilt]
        if drop:
            delete_shards(env, vid, rebuilder, drop)
        mount_shards(env, vid, rebuilder)
        return f"volume {vid}: rebuilt shards {rebuilt} on {rebuilder}"


# -- ec.balance --------------------------------------------------------------

@register
class EcBalance(Command):
    name = "ec.balance"
    help = ("ec.balance [-collection <name>] — dedupe replicated shards "
            "and spread EC shards evenly across data nodes")

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        vids = sorted({e["id"] for n in env.data_nodes()
                       for e in n["ec_shards"]})
        moves = []
        for vid in vids:
            moves += self._dedupe(env, vid)
        moves += self._spread(env, vids)
        return "\n".join(moves) or "already balanced"

    def _dedupe(self, env: CommandEnv, vid: int) -> list[str]:
        """Remove duplicate copies of a shard (deleteDuplicatedEcShards):
        keep the copy on the least-loaded node."""
        out = []
        holders = node_shard_map(env, vid)
        load = {u: len(s) for u, s in holders.items()}
        for sid, urls in sorted(env.ec_shard_locations(vid).items()):
            if len(urls) <= 1:
                continue
            keep = min(urls, key=lambda u: load.get(u, 0))
            for url in urls:
                if url != keep:
                    delete_shards(env, vid, url, [sid])
                    load[url] = load.get(url, 1) - 1
                    out.append(f"volume {vid} shard {sid}: dropped dup "
                               f"on {url}")
        return out

    def _spread(self, env: CommandEnv, vids: list[int]) -> list[str]:
        """Even out total shard counts across nodes (balanceEcShards)."""
        out = []
        for _round in range(TOTAL_SHARDS * max(len(vids), 1)):
            nodes = collect_ec_nodes(env)
            if len(nodes) < 2:
                break
            counts = {n["url"]: n["ec_shard_count"] for n in nodes}
            lo = min(counts, key=counts.get)  # type: ignore[arg-type]
            hi = max(counts, key=counts.get)  # type: ignore[arg-type]
            if counts[hi] - counts[lo] <= 1:
                break
            moved = False
            for vid in vids:
                holders = node_shard_map(env, vid)
                src_shards = holders.get(hi, [])
                dst_shards = set(holders.get(lo, []))
                for sid in src_shards:
                    if sid not in dst_shards:
                        move_shard(env, vid, sid, hi, lo)
                        out.append(f"volume {vid} shard {sid}: "
                                   f"{hi} -> {lo}")
                        moved = True
                        break
                if moved:
                    break
            if not moved:
                break
        return out


# -- ec.decode ---------------------------------------------------------------

@register
class EcDecode(Command):
    name = "ec.decode"
    help = ("ec.decode -volumeId <id> | -collection <name> — convert EC "
            "shards back into a normal volume")

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, _ = self.parse_flags(args)
        if "volumeId" in flags:
            vids = [int(flags["volumeId"])]
        else:
            vids = sorted({e["id"] for n in env.data_nodes()
                           for e in n["ec_shards"]})
        out = []
        for vid in vids:
            out.append(self.decode_one(env, vid))
        return "\n".join(out) or "no ec volumes"

    def decode_one(self, env: CommandEnv, vid: int) -> str:
        holders = node_shard_map(env, vid)
        if not holders:
            raise ShellError(f"no EC shards for volume {vid}")
        present = {s for shards in holders.values() for s in shards}
        data_missing_everywhere = [s for s in range(10) if s not in present]
        if data_missing_everywhere and len(present) < 10:
            raise ShellError(
                f"volume {vid}: cannot decode, shards lost beyond repair")
        # Collector: node with most data shards.
        collector = max(holders,
                        key=lambda u: len([s for s in holders[u]
                                           if s < 10]))
        local = set(holders[collector])
        # Pull missing data shards (and parity if reconstruction needed).
        want = set(range(10))
        if data_missing_everywhere:
            want |= present  # need >=10 of anything to rebuild data shards
        for url, shards in holders.items():
            if url == collector:
                continue
            need = [s for s in shards if s in want and s not in local]
            if need:
                copy_shards(env, vid, collector, url, need, copy_ecx=True)
                local |= set(need)
        if data_missing_everywhere:
            env.vs_call(collector, "/admin/ec/rebuild", {"volume": vid})
        env.vs_call(collector, "/admin/ec/to_volume", {"volume": vid})
        # Drop all EC shards cluster-wide; the volume lives on collector.
        for url in holders:
            try:
                env.vs_call(url, "/admin/ec/unmount", {"volume": vid})
            except rpc.RpcError:
                pass
            all_sids = list(range(TOTAL_SHARDS))
            try:
                delete_shards(env, vid, url, all_sids)
            except rpc.RpcError:
                pass
        return f"volume {vid}: decoded back to normal volume on {collector}"
