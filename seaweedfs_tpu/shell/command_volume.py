"""Volume maintenance commands: list/balance/fix.replication/move/....

Reference: weed/shell/command_volume_list.go, command_volume_balance.go
(ideal-ratio moves), command_volume_fix_replication.go (under-replicated
copy), command_volume_move.go / _copy.go / _delete.go / _mount.go,
command_volume_vacuum (via master /vol/vacuum).
"""

from __future__ import annotations

from ..core.replica_placement import ReplicaPlacement
from ..cluster import rpc
from .commands import Command, register
from .env import CommandEnv, ShellError


def _volumes_by_id(env: CommandEnv) -> dict[int, list[tuple[dict, dict]]]:
    """vid -> [(node, vinfo), ...] across the cluster."""
    out: dict[int, list[tuple[dict, dict]]] = {}
    for n in env.data_nodes():
        for v in n["volumes"]:
            out.setdefault(v["id"], []).append((n, v))
    return out


@register
class VolumeList(Command):
    name = "volume.list"
    help = "volume.list — topology tree with every volume and EC shard"

    def do(self, args: list[str], env: CommandEnv) -> str:
        topo = env.topology()["topology"]
        lines = []
        for dc in topo["data_centers"]:
            lines.append(f"DataCenter {dc['id']}")
            for rack in dc["racks"]:
                lines.append(f"  Rack {rack['id']}")
                for n in rack["nodes"]:
                    lines.append(
                        f"    DataNode {n['url']} "
                        f"volumes:{len(n['volumes'])}"
                        f"/{n['max_volume_count']} "
                        f"ec_volumes:{len(n['ec_shards'])}")
                    for v in sorted(n["volumes"], key=lambda v: v["id"]):
                        rp = ReplicaPlacement.from_byte(
                            v.get("replica_placement", 0))
                        lines.append(
                            f"      volume id:{v['id']} "
                            f"collection:{v.get('collection', '') or '-'} "
                            f"size:{v['size']} "
                            f"files:{v['file_count']} "
                            f"replication:{rp} "
                            f"{'readonly' if v.get('read_only') else 'rw'}")
                    for e in sorted(n["ec_shards"], key=lambda e: e["id"]):
                        from ..ec.shard_bits import ShardBits
                        sids = ShardBits(e["shard_bits"]).shard_ids()
                        lines.append(
                            f"      ec volume id:{e['id']} shards:{sids}")
        return "\n".join(lines)


@register
class VolumeMove(Command):
    name = "volume.move"
    help = ("volume.move -volumeId <id> -source <host:port> "
            "-target <host:port> — copy a volume then remove the source")

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, _ = self.parse_flags(args)
        vid = int(flags["volumeId"])
        source, target = flags["source"], flags["target"]
        copy_volume(env, vid, source, target)
        env.vs_call(source, "/admin/delete_volume", {"volume": vid})
        return f"moved volume {vid}: {source} -> {target}"


def copy_volume(env: CommandEnv, vid: int, source: str, target: str) -> None:
    """Freeze the source, copy .idx+.dat to the target, restore.

    Without the freeze a write landing between the two file fetches would
    be referenced by neither copy — after a `move` deletes the source,
    that needle would be lost (the reference freezes/tails instead)."""
    locs = _volumes_by_id(env).get(vid, [])
    collection = locs[0][1].get("collection", "") if locs else ""
    was_readonly = bool(locs and locs[0][1].get("read_only"))
    env.vs_call(source, "/admin/readonly",
                {"volume": vid, "readonly": True})
    try:
        env.vs_call(target, "/admin/copy_volume",
                    {"volume": vid, "source": source,
                     "collection": collection})
    finally:
        if not was_readonly:
            env.vs_call(source, "/admin/readonly",
                        {"volume": vid, "readonly": False})


@register
class VolumeCopy(Command):
    name = "volume.copy"
    help = ("volume.copy -volumeId <id> -source <host:port> "
            "-target <host:port>")

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, _ = self.parse_flags(args)
        vid = int(flags["volumeId"])
        copy_volume(env, vid, flags["source"], flags["target"])
        return f"copied volume {vid} to {flags['target']}"


@register
class VolumeDelete(Command):
    name = "volume.delete"
    help = "volume.delete -volumeId <id> -node <host:port>"

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, _ = self.parse_flags(args)
        vid = int(flags["volumeId"])
        env.vs_call(flags["node"], "/admin/delete_volume", {"volume": vid})
        return f"deleted volume {vid} on {flags['node']}"


@register
class VolumeMount(Command):
    name = "volume.mount"
    help = "volume.mount -volumeId <id> -node <host:port>"

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, _ = self.parse_flags(args)
        env.vs_call(flags["node"], "/admin/mount",
                    {"volume": int(flags["volumeId"])})
        return "mounted"


@register
class VolumeConfigureReplication(Command):
    """Change a volume's intended replica placement on every holder
    (command_volume_configure_replication.go); follow with
    volume.fix.replication to create/trim actual copies."""
    name = "volume.configure.replication"
    help = ("volume.configure.replication -volumeId <id> "
            "-replication <xyz>")

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, _ = self.parse_flags(args)
        vid = int(flags["volumeId"])
        replication = flags.get("replication", "")
        if not replication:
            # parse("") would quietly mean 000 and trim real replicas
            raise ShellError("empty -replication value")
        rp = ReplicaPlacement.parse(replication)  # validates format
        changed = []
        topo = env.topology()["topology"]
        for dc in topo["data_centers"]:
            for rack in dc["racks"]:
                for n in rack["nodes"]:
                    for v in n["volumes"]:
                        if v["id"] == vid and \
                                v["replica_placement"] != rp.to_byte():
                            env.vs_call(n["url"],
                                        "/admin/configure_replication",
                                        {"volume": vid,
                                         "replication": replication})
                            changed.append(n["url"])
        if not changed:
            raise ShellError(f"no volume {vid} replica needs change")
        return (f"configured {replication} on {len(changed)} "
                f"holder(s): {', '.join(changed)} — run "
                f"volume.fix.replication to realize it")


@register
class VolumeUnmount(Command):
    name = "volume.unmount"
    help = "volume.unmount -volumeId <id> -node <host:port>"

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, _ = self.parse_flags(args)
        env.vs_call(flags["node"], "/admin/unmount",
                    {"volume": int(flags["volumeId"])})
        return "unmounted"


@register
class VolumeFsck(Command):
    name = "volume.fsck"
    help = ("volume.fsck [-v] [-crc] [-json] — verify every filer "
            "chunk resolves to a live needle (command_volume_fsck.go's "
            "findMissingChunksInVolumeServers direction); -crc HEADs "
            "EVERY replica and compares the stored needle CRC "
            "(X-Needle-Checksum) so divergent copies are caught "
            "without transferring bodies; -json emits a machine-"
            "readable report (per-volume per-needle checksum sets + "
            "verdict) whose `volumes` map is node-address-free, so two "
            "mirrored clusters converged exactly when their reports' "
            "`volumes` maps are equal")

    @staticmethod
    def _head_checksum(url: str, fid: str) -> str:
        import urllib.request
        req = urllib.request.Request(f"http://{url}/{fid}",
                                     method="HEAD")
        resp = urllib.request.urlopen(req, timeout=10)
        resp.read()
        return resp.headers.get("X-Needle-Checksum", "")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _ = self.parse_flags(args)
        crc_mode = "crc" in flags
        json_mode = "json" in flags
        proxy = env.filer()
        checked, missing, diverged = 0, [], []
        # vid -> fid -> sorted distinct replica checksums.  Keyed by
        # needle, not node, so two clusters' reports compare directly.
        vols: dict[str, dict[str, list[str]]] = {}
        stack = ["/"]
        while stack:
            d = stack.pop()
            for e in proxy.list_all(d):
                if e["is_directory"]:
                    stack.append(e["FullPath"])
                    continue
                meta = proxy.meta(e["FullPath"])
                for chunk in (meta or {}).get("chunks", []):
                    checked += 1
                    fid = chunk["file_id"]
                    try:
                        vid = int(fid.split(",")[0])
                        locs = env.volume_locations(vid)
                        if not locs:
                            raise LookupError("no locations")
                        if not crc_mode and not json_mode:
                            self._head_checksum(locs[0], fid)
                            continue
                        crcs = {}
                        for url in locs if crc_mode else locs[:1]:
                            crcs[url] = self._head_checksum(url, fid)
                        vols.setdefault(str(vid), {})[fid] = \
                            sorted(set(crcs.values()))
                        if len(set(crcs.values())) > 1:
                            diverged.append(
                                (e["FullPath"], fid,
                                 ", ".join(f"{u}={c or '?'}"
                                           for u, c in
                                           sorted(crcs.items()))))
                    except Exception as err:  # noqa: BLE001
                        missing.append((e["FullPath"], fid, str(err)))
        if json_mode:
            import json as _json
            verdict = "missing" if missing else \
                "divergent" if diverged else "ok"
            return _json.dumps(
                {"verdict": verdict, "checked": checked,
                 "missing": [{"path": p, "fid": f, "error": err}
                             for p, f, err in missing],
                 "diverged": [{"path": p, "fid": f, "detail": d}
                              for p, f, d in diverged],
                 "volumes": {vid: dict(sorted(fids.items()))
                             for vid, fids in sorted(vols.items())}},
                indent=1, sort_keys=True)
        lines = [f"checked {checked} chunks, {len(missing)} missing"
                 + (f", {len(diverged)} replica CRC mismatches"
                    if crc_mode else "")]
        if "v" in flags or missing:
            lines += [f"  MISSING {path} chunk {fid}: {err}"
                      for path, fid, err in missing[:50]]
        lines += [f"  CRC MISMATCH {path} chunk {fid}: {detail}"
                  for path, fid, detail in diverged[:50]]
        return "\n".join(lines)


@register
class VolumeScrub(Command):
    name = "volume.scrub"
    help = ("volume.scrub [-volumeId <id>] [-node <host:port>] "
            "[-repair] — CRC-verify every live needle and EC shard "
            "block on the targeted server(s) now; -repair heals "
            "corruption from replicas / EC decode "
            "(volume_checking.go's direction, on demand)")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _ = self.parse_flags(args)
        repair = "repair" in flags
        if repair:
            env.confirm_is_locked()
        vid = int(flags["volumeId"]) if "volumeId" in flags else \
            int(flags["vid"]) if "vid" in flags else None
        nodes = [flags["node"]] if "node" in flags else \
            [n["url"] for n in env.data_nodes()]
        payload: dict = {"repair": repair}
        if vid is not None:
            payload["volume"] = vid
        lines = []
        for node in nodes:
            out = env.vs_call(node, "/admin/scrub", payload)
            for r in out.get("volumes", []):
                lines.append(
                    f"{node} {r['kind']} volume {r['id']}: "
                    f"checked {r['checked']}, corrupt {r['corrupt']}, "
                    f"repaired {r['repaired']}"
                    + (f", quarantined {r['quarantined']}"
                       if r.get("quarantined") else "")
                    + (f", unrepaired {r['unrepaired']}"
                       if r.get("unrepaired") else ""))
        return "\n".join(lines) or "nothing to scrub"


@register
class VolumeCheckDisk(Command):
    name = "volume.check.disk"
    help = ("volume.check.disk [-volumeId <id>] [-n] — compare the "
            "needle sets of every replicated volume's holders (via "
            "their .idx files) and heal divergence: a needle missing "
            "or quarantined on one holder is re-fetched from a "
            "healthy sibling (command_volume_check_disk.go)")

    @staticmethod
    def _idx_state(node: str, vid: int
                   ) -> tuple[set[int], set[int], set[int]]:
        """(live_keys, seen_keys, quarantined_keys) from one holder.
        `seen` includes tombstoned keys: a key a holder has *deleted*
        must not be mistaken for one it never received — resurrecting
        a tombstoned needle would undo an acknowledged delete.
        `quarantined` (open repair tickets, /admin/scrub/status) tells
        a scrub-quarantine tombstone apart from a user delete: that
        holder needs a REPAIR, and its tombstone must never be
        propagated as a delete — it would erase the healthy copies."""
        import io

        from ..core import idx as idx_mod
        from ..core import types as t
        raw = rpc.call(f"http://{node}/admin/volume_file?"
                       f"volume={vid}&ext=.idx")
        last: dict[int, tuple[int, int]] = {}
        for e in idx_mod.iter_index(io.BytesIO(bytes(raw))):
            last[e.key] = (e.offset, e.size)
        live = {k for k, (off, size) in last.items()
                if off > 0 and t.size_is_valid(size)}
        quarantined: set[int] = set()
        try:
            st = rpc.call(f"http://{node}/admin/scrub/status")
            row = next((r for r in st.get("volumes", [])
                        if r["id"] == vid), None)
            if row:
                quarantined = {int(k, 16) for k in row["tickets"]}
        except Exception:  # noqa: BLE001 — older server: no tickets
            pass
        return live, set(last), quarantined

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, _ = self.parse_flags(args)
        dry = "n" in flags
        only = int(flags["volumeId"]) if "volumeId" in flags else None
        out = []
        for vid, holders in sorted(_volumes_by_id(env).items()):
            if only is not None and vid != only:
                continue
            if len(holders) < 2:
                continue
            states = {}
            for n, _v in holders:
                try:
                    states[n["url"]] = self._idx_state(n["url"], vid)
                except Exception as e:  # noqa: BLE001 — holder down
                    out.append(f"volume {vid}: cannot read idx on "
                               f"{n['url']}: {e}")
            if len(states) < 2:
                continue
            union_live: set[int] = set().union(
                *(live | quar for live, _seen, quar
                  in states.values()))
            for key in sorted(union_live):
                # A USER tombstone anywhere wins: the delete was
                # acknowledged to a client, so holders still serving
                # the needle get the delete, never the reverse
                # (command_volume_check_disk.go resolves direction by
                # timestamp; deletes are strictly newer here).  A
                # QUARANTINE tombstone is the opposite case — that
                # holder lost its copy to rot and needs a repair.
                deleters = [u for u, (live, seen, quar)
                            in states.items()
                            if key in seen and key not in live
                            and key not in quar]
                for url, (live, seen, quar) in sorted(states.items()):
                    if key in quar and not deleters:
                        if dry:
                            out.append(f"volume {vid}: {url} "
                                       f"quarantined needle {key:x} "
                                       f"(would repair)")
                            continue
                        try:
                            env.vs_call(url, "/admin/scrub/repair",
                                        {"volume": vid, "key": key})
                            out.append(f"volume {vid}: repaired "
                                       f"quarantined needle {key:x} "
                                       f"on {url}")
                        except Exception as e:  # noqa: BLE001
                            out.append(f"volume {vid}: FAILED to "
                                       f"repair quarantined needle "
                                       f"{key:x} on {url}: {e}")
                    elif deleters and key in live:
                        fid = f"{vid},{key:x}{0:08x}"
                        if dry:
                            out.append(f"volume {vid}: {url} still "
                                       f"serves deleted needle "
                                       f"{key:x} (would delete)")
                            continue
                        try:
                            rpc.call(f"http://{url}/{fid}"
                                     "?type=replicate", "DELETE")
                            out.append(f"volume {vid}: propagated "
                                       f"delete of {key:x} to {url}")
                        except Exception as e:  # noqa: BLE001
                            out.append(f"volume {vid}: FAILED to "
                                       f"delete {key:x} on {url}: {e}")
                    elif not deleters and key not in seen:
                        if dry:
                            out.append(f"volume {vid}: {url} missing "
                                       f"needle {key:x} (would repair)")
                            continue
                        try:
                            env.vs_call(url, "/admin/scrub/repair",
                                        {"volume": vid, "key": key})
                            out.append(f"volume {vid}: repaired "
                                       f"needle {key:x} on {url}")
                        except Exception as e:  # noqa: BLE001
                            out.append(f"volume {vid}: FAILED to "
                                       f"repair needle {key:x} on "
                                       f"{url}: {e}")
        return "\n".join(out) or "all replicas agree"


@register
class VolumeServerLeave(Command):
    name = "volumeServer.leave"
    help = ("volumeServer.leave -node <host:port> — stop the server's "
            "heartbeats so the master drains it "
            "(command_volume_server_leave.go)")

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, _ = self.parse_flags(args)
        env.vs_call(flags["node"], "/admin/leave", {})
        return f"{flags['node']} is leaving the cluster"


@register
class VolumeTierUpload(Command):
    name = "volume.tier.upload"
    help = ("volume.tier.upload -volumeId <id> -node <host:port> "
            "-dest <s3://host/bucket | local:///dir> [-keepLocal] "
            "(shell/command_volume_tier_upload.go: marks the volume "
            "readonly, then moves its .dat to the backend)")

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, _ = self.parse_flags(args)
        vid = int(flags["volumeId"])
        node = flags["node"]
        env.vs_call(node, "/admin/readonly",
                    {"volume": vid, "readonly": True})
        out = env.vs_call(node, "/admin/tier_upload", {
            "volume": vid, "dest": flags["dest"],
            "keep_local": "keepLocal" in flags,
            "access_key": flags.get("accessKey", ""),
            "secret_key": flags.get("secretKey", "")})
        r = out["remote"]
        return (f"volume {vid} tiered to {r['backend_spec']} "
                f"({r['file_size']} bytes)")


@register
class VolumeTierDownload(Command):
    name = "volume.tier.download"
    help = ("volume.tier.download -volumeId <id> -node <host:port> "
            "[-keepRemote] (command_volume_tier_download.go)")

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, _ = self.parse_flags(args)
        vid = int(flags["volumeId"])
        env.vs_call(flags["node"], "/admin/tier_download", {
            "volume": vid, "keep_remote": "keepRemote" in flags,
            "access_key": flags.get("accessKey", ""),
            "secret_key": flags.get("secretKey", "")})
        return f"volume {vid} downloaded back to local storage"


@register
class VolumeBalance(Command):
    name = "volume.balance"
    help = ("volume.balance [-collection <name>] — move volumes so every "
            "node is at a similar fill ratio")

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, _ = self.parse_flags(args)
        collection = flags.get("collection")
        out = []
        for _ in range(64):
            nodes = env.data_nodes()
            if len(nodes) < 2:
                break
            ratios = {n["url"]: len(n["volumes"]) / max(
                n["max_volume_count"], 1) for n in nodes}
            hi = max(ratios, key=ratios.get)  # type: ignore[arg-type]
            lo = min(ratios, key=ratios.get)  # type: ignore[arg-type]
            hi_n = next(n for n in nodes if n["url"] == hi)
            lo_n = next(n for n in nodes if n["url"] == lo)
            if (len(hi_n["volumes"]) - len(lo_n["volumes"])) <= 1:
                break
            lo_vids = {v["id"] for v in lo_n["volumes"]}
            candidates = [v for v in hi_n["volumes"]
                          if v["id"] not in lo_vids
                          and (collection is None
                               or v.get("collection", "") == collection)]
            if not candidates:
                break
            v = min(candidates, key=lambda v: v["size"])
            copy_volume(env, v["id"], hi, lo)
            env.vs_call(hi, "/admin/delete_volume", {"volume": v["id"]})
            out.append(f"moved volume {v['id']}: {hi} -> {lo}")
        return "\n".join(out) or "already balanced"


@register
class VolumeFixReplication(Command):
    name = "volume.fix.replication"
    help = ("volume.fix.replication [-n] — one manual pass of the "
            "durability autopilot's re-replication planner "
            "(cluster/repair_daemon.py): -n renders the risk-ranked "
            "plan the daemon would execute (see it before arming "
            "-repair); without -n the master runs the plan "
            "synchronously — same risk ordering, placement-aware "
            "target choice, verified crash-safe copies, and surplus "
            "dedupe as the armed daemon")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _ = self.parse_flags(args)
        dry = "n" in flags
        if dry:
            doc = rpc.call(f"{env.master_url}/cluster/repair",
                           timeout=30.0)
            rows = [r for r in doc.get("plan", [])
                    if r["kind"] == "replicate"]
            out = []
            for r in rows:
                note = " (drain-fenced: would NOT auto-repair)" \
                    if r.get("suppressed") else ""
                out.append(
                    f"volume {r['volume']}: would re-replicate "
                    f"{r['have']}/{r['want']} "
                    f"(risk={r['risk']}, rp={r['replication']})"
                    f"{note}")
            return "\n".join(out) or \
                "all volumes sufficiently replicated"
        env.confirm_is_locked()
        doc = rpc.call_json(f"{env.master_url}/cluster/repair/run",
                            payload={"kinds": ["replicate"]},
                            timeout=600.0)
        out = []
        for r in doc.get("results", []):
            if r.get("outcome") == "ok":
                out.append(f"volume {r['volume']}: copied — restored "
                           f"{r['want']}/{r['want']}")
            else:
                out.append(f"volume {r['volume']}: "
                           f"{r.get('outcome', '?')}"
                           + (f" ({r['error']})"
                              if r.get("error") else ""))
        for r in doc.get("trimmed", []):
            out.append(f"volume {r['volume']}: trimmed surplus copy "
                       f"on {r['node']}")
        return "\n".join(out) or "all volumes sufficiently replicated"


@register
class VolumeVacuum(Command):
    name = "volume.vacuum"
    help = "volume.vacuum [-garbageThreshold 0.3] — trigger a vacuum scan"

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _ = self.parse_flags(args)
        q = ""
        if "garbageThreshold" in flags:
            q = f"?garbageThreshold={flags['garbageThreshold']}"
        resp = rpc.call_json(f"{env.master_url}/vol/vacuum{q}")
        return f"vacuumed volumes: {resp.get('vacuumed', [])}"


@register
class VolumeServerEvacuate(Command):
    name = "volumeServer.evacuate"
    help = ("volumeServer.evacuate -node <host:port> — move every volume "
            "and EC shard off one server")

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, _ = self.parse_flags(args)
        node = flags["node"]
        me = next((n for n in env.data_nodes() if n["url"] == node), None)
        if me is None:
            raise ShellError(f"node {node} not found")
        out = []
        failed = []
        i = 0
        for v in me["volumes"]:
            # Re-fetch per move: capacities change as copies land.
            others = [n for n in env.data_nodes() if n["url"] != node]
            if not others:
                raise ShellError("no other nodes to evacuate to")
            placed = False
            for _ in range(len(others)):
                target = others[i % len(others)]
                i += 1
                if len(target["volumes"]) < target["max_volume_count"] and \
                        v["id"] not in {x["id"] for x in target["volumes"]}:
                    copy_volume(env, v["id"], node, target["url"])
                    env.vs_call(node, "/admin/delete_volume",
                                {"volume": v["id"]})
                    out.append(f"volume {v['id']} -> {target['url']}")
                    placed = True
                    break
            if not placed:
                failed.append(f"volume {v['id']}")
        from .command_ec import move_shard
        from ..ec.shard_bits import ShardBits
        for e in me["ec_shards"]:
            others = [n for n in env.data_nodes() if n["url"] != node]
            for sid in ShardBits(e["shard_bits"]).shard_ids():
                target = others[i % len(others)]
                i += 1
                move_shard(env, e["id"], sid, node, target["url"])
                out.append(f"ec {e['id']}.{sid} -> {target['url']}")
        if failed:
            # The node is NOT safe to decommission — refuse to report
            # success with replicas still aboard.
            raise ShellError(
                "evacuation incomplete, no capacity for: "
                + ", ".join(failed)
                + ("\n" + "\n".join(out) if out else ""))
        return "\n".join(out) or "nothing to evacuate"
