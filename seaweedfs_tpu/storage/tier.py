"""Volume tiering: move a volume's .dat to a remote backend.

Reference: weed/storage/volume_tier.go:11-32 (the `.vif` VolumeInfo
sidecar + maybeLoadVolumeInfo/LoadRemoteFile),
server/volume_grpc_tier_upload.go (VolumeTierMoveDatToRemote) and
_download.go (back).  The `.idx` stays local; needle reads proxy
through ranged reads against the backend.
"""

from __future__ import annotations

import json
import os
import time

from .backend import backend_for_spec
from .volume import Volume, VolumeError


def vif_path(base: str) -> str:
    return base + ".vif"


def save_vif(base: str, info: dict) -> None:
    tmp = vif_path(base) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(info, f, indent=1)
    os.replace(tmp, vif_path(base))


def load_vif(base: str) -> dict | None:
    try:
        with open(vif_path(base)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def tier_key(collection: str, vid: int) -> str:
    name = f"{collection}_{vid}" if collection else str(vid)
    return f"{name}.dat"


def move_dat_to_remote(volume: Volume, dest_spec: str,
                       keep_local: bool = False,
                       access_key: str = "",
                       secret_key: str = "") -> dict:
    """Upload the .dat, write the .vif sidecar, switch the volume to
    remote reads.  The volume must be readonly (the reference requires
    the same)."""
    if volume.remote_file is not None:
        raise VolumeError(f"volume {volume.vid} is already remote")
    if not volume.readonly:
        raise VolumeError(
            f"volume {volume.vid} must be readonly before tiering")
    backend = backend_for_spec(dest_spec, access_key, secret_key)
    base = volume.file_name()
    key = tier_key(volume.collection, volume.vid)
    volume.sync()
    want = os.path.getsize(base + ".dat")
    try:
        size = backend.upload_file(key, base + ".dat")
    except Exception:  # noqa: BLE001
        # A previously-crashed upload can leave a partial/stale object
        # at the key and some backends refuse the overwrite.  Clear it
        # and re-upload once; a second failure propagates with the
        # volume still fully local (.vif not yet written).
        try:
            backend.delete(key)
        except Exception:  # noqa: BLE001
            pass
        size = backend.upload_file(key, base + ".dat")
    if size != want:
        # Never publish a .vif pointing at a short object; the local
        # .dat is still authoritative.
        try:
            backend.delete(key)
        except Exception:  # noqa: BLE001
            pass
        raise VolumeError(
            f"tier upload of volume {volume.vid} wrote {size} bytes, "
            f"local .dat has {want}")
    # No credentials in the sidecar: the .vif sits on the data dir and
    # must never leak keys (the reference keeps backend credentials in
    # centrally-distributed config) — they come from server config/env
    # at open time.
    # modified_at is the volume's newest-WRITE time, not the upload
    # time: TTL expiry decisions must survive the round-trip through
    # the remote tier.
    info = {"volume_id": volume.vid, "version": volume.version,
            "collection": volume.collection,
            "files": [{"backend_spec": dest_spec, "key": key,
                       "file_size": size,
                       "modified_at": int(getattr(
                           volume, "modified_at", 0) or time.time())}]}
    save_vif(base, info)
    # The fd swap rides the same write lock vacuum uses, so a reader
    # mid-pread can never observe a closed fd.
    with volume._file_lock.write():
        volume.remote_file = backend.open_file(key, size)
        dat = volume._dat
        volume._dat = None
    if dat is not None:
        dat.close()
    if not keep_local:
        os.remove(base + ".dat")
    from ..events import emit as emit_event
    from ..stats import flows as _flows
    from ..stats import metrics as _metrics
    _metrics.tier_moved_bytes_total.inc(size, direction="upload")
    # Tier transfers bypass the rpc plane (backend SDK / file copy):
    # feed the wire-flow ledger directly, peer = the backend spec.
    _flows.LEDGER.note("tier.up", "out", size, peer=dest_spec,
                       peer_role="remote")
    emit_event("tier.move", vid=volume.vid, direction="upload",
               dest=dest_spec, bytes=size, keep_local=keep_local)
    return info


def _tier_credentials() -> tuple[str, str]:
    """Backend credentials from server-level config (env), NOT from the
    .vif (which must stay secret-free)."""
    return (os.environ.get("WEED_TIER_ACCESS_KEY", ""),
            os.environ.get("WEED_TIER_SECRET_KEY", ""))


def move_dat_from_remote(volume: Volume, keep_remote: bool = False,
                         access_key: str = "",
                         secret_key: str = "") -> None:
    """Download the .dat back and resume local reads
    (VolumeTierMoveDatFromRemote)."""
    base = volume.file_name()
    info = load_vif(base)
    if info is None or volume.remote_file is None:
        raise VolumeError(f"volume {volume.vid} is not tiered")
    fdesc = info["files"][0]
    if not access_key:
        access_key, secret_key = _tier_credentials()
    backend = backend_for_spec(fdesc["backend_spec"],
                               access_key, secret_key)
    # Download to a temp name and os.replace only after verifying the
    # byte count: a crash mid-download must never leave a truncated
    # .dat beside a live .vif (the remount would prefer the torn local
    # copy over the intact remote one).
    tmp = base + ".dat.tmpdl"
    try:
        os.remove(tmp)
    except FileNotFoundError:
        pass
    got = backend.download_file(fdesc["key"], tmp)
    want = fdesc.get("file_size")
    if want is not None and got != want:
        try:
            os.remove(tmp)
        except FileNotFoundError:
            pass
        raise VolumeError(
            f"tier download of volume {volume.vid} got {got} bytes, "
            f".vif records {want}")
    os.replace(tmp, base + ".dat")
    with volume._file_lock.write():
        remote = volume.remote_file
        volume._dat = open(base + ".dat", "r+b")
        volume.remote_file = None
    remote.close()
    # The remote copy may be deleted next; stale cached blocks must not
    # outlive it (a re-tier to the same key would serve old bytes).
    from .remote_cache import CACHE
    CACHE.drop_file(fdesc["backend_spec"], fdesc["key"])
    os.remove(vif_path(base))
    if not keep_remote:
        backend.delete(fdesc["key"])
    from ..events import emit as emit_event
    from ..stats import flows as _flows
    from ..stats import metrics as _metrics
    _metrics.tier_moved_bytes_total.inc(got, direction="download")
    _flows.LEDGER.note("tier.down", "in", got,
                       peer=fdesc["backend_spec"], peer_role="remote")
    emit_event("tier.move", vid=volume.vid, direction="download",
               source=fdesc["backend_spec"],
               bytes=fdesc.get("file_size", 0),
               keep_remote=keep_remote)


def open_remote_volume(dir_: str, collection: str, vid: int) -> Volume:
    """Open a tiered volume from its .vif + local .idx (the startup
    path when the .dat is absent — maybeLoadVolumeInfo)."""
    name = f"{collection}_{vid}" if collection else str(vid)
    base = os.path.join(dir_, name)
    info = load_vif(base)
    if info is None or not info.get("files"):
        # A files-less .vif is EC/version metadata (ec/volume_info.py),
        # not a tier marker.
        raise VolumeError(f"volume {vid} at {base} is not tiered")
    fdesc = info["files"][0]
    ak, sk = _tier_credentials()
    backend = backend_for_spec(fdesc["backend_spec"], ak, sk)
    remote = backend.open_file(fdesc["key"], fdesc["file_size"])
    v = Volume(dir_, collection, vid, create=False,
               remote_file=remote)
    v.modified_at = float(fdesc.get("modified_at", 0) or 0)
    return v
