"""Needle maps: needle id -> (offset, size) with live counters.

The reference offers several NeedleMapper implementations
(weed/storage/needle_map.go:12-36); this module mirrors that menu with
memory-profiles fitting each volume state:

- `CompactNeedleMap` (default): bounded-memory mapper holding the index
  as sorted numpy column arrays (16 bytes/entry — the same density as
  the `.idx` file itself) with a small dict overflow merged in batches.
  This is the reference `CompactMap`'s sectioned-sorted-arrays design
  (weed/storage/needle_map/compact_map.go:173-218) in its natural numpy
  form: one big sorted section + batch merges, vectorized load.
- `MemoryNeedleMap`: plain dict (O(1) puts, ~10x the RAM); the small-
  volume / test mapper.
- `SortedFileNeedleMap`: O(1)-RAM mapper for read-only volumes that
  binary-searches a sorted index file (`.sdx`) on disk per lookup
  (weed/storage/needle_map_sorted_file.go).
- `MemDb`: sorted map used to build `.ecx` files
  (weed/storage/needle_map/memdb.go).

All mappers write-through appends to the `.idx` journal like the
reference's baseNeedleMapper.
"""

from __future__ import annotations

import io
import os
import threading
from dataclasses import dataclass, field

import numpy as np

from ..core import idx as idx_mod
from ..core import types as t

# The on-disk .idx record, vectorizable: big-endian u64 key, then the
# offset in /8 units (u32, or u32-low + u1-high for the 5-byte/8TB
# flavor), then i32 size.  Resolved per call so set_offset_flavor()
# takes effect.
_IDX_DTYPE_4 = np.dtype([("key", ">u8"), ("offset", ">u4"),
                         ("size", ">i4")])
_IDX_DTYPE_5 = np.dtype([("key", ">u8"), ("offset", ">u4"),
                         ("off_hi", "u1"), ("size", ">i4")])


def _idx_dtype() -> np.dtype:
    return _IDX_DTYPE_4 if t.OFFSET_SIZE == 4 else _IDX_DTYPE_5


def _units_col(arr: np.ndarray) -> np.ndarray:
    """Offset column in /8 units as uint64, either flavor."""
    units = arr["offset"].astype(np.uint64)
    if "off_hi" in (arr.dtype.names or ()):
        units |= arr["off_hi"].astype(np.uint64) << 32
    return units


def _off_np_dtype():
    """In-memory width for stored offset units: u32 suffices for the
    4-byte flavor; the 8TB flavor needs 40 bits."""
    return np.uint32 if t.OFFSET_SIZE == 4 else np.uint64


def _keep_last_live(arr: np.ndarray) -> np.ndarray:
    """Vectorized .idx replay: the LAST occurrence per key decides its
    fate; returns the live selection ascending by key.  (np.unique
    returns the FIRST index, so scan the reversed key array.)"""
    keys = arr["key"].astype(np.uint64)
    _uniq, idx_rev = np.unique(keys[::-1], return_index=True)
    last = len(keys) - 1 - idx_rev  # ascending-key order
    sel = arr[last]
    live = (_units_col(sel) > 0) & \
           (sel["size"].astype(np.int32) > 0)
    return sel[live]


@dataclass
class MapMetrics:
    file_count: int = 0
    deletion_count: int = 0
    file_byte_count: int = 0
    deletion_byte_count: int = 0
    maximum_file_key: int = 0


def idx_crash_state(idx_path: str
                    ) -> tuple[tuple[int, int] | None, set[int]]:
    """One pass over an .idx for crash recovery (shared by
    verify_idx_against_dat and storage/scrub.recover_volume_files):

    - truncates a partial trailing entry (kill -9 mid-append), or every
      later append would land misaligned and garble the journal;
    - returns ((offset_bytes, size) of the write entry furthest into
      the .dat — the point up to which the index vouches for data —
      or None, and the set of keys whose LAST entry is a tombstone,
      so a tail scan can tell an unjournaled delete marker from one
      the index already knows about).
    """
    if not os.path.exists(idx_path):
        return None, set()
    isize = os.path.getsize(idx_path)
    usable = isize - isize % idx_mod.ENTRY_SIZE
    if usable != isize:
        with open(idx_path, "r+b") as f:
            f.truncate(usable)
    if usable == 0:
        return None, set()
    with open(idx_path, "rb") as f:
        raw = f.read(usable)
    arr = np.frombuffer(raw, dtype=_idx_dtype())
    offs = _units_col(arr) * t.NEEDLE_PADDING_SIZE
    writes = (offs > 0) & (arr["size"].astype(np.int32) > 0)
    furthest = None
    if writes.any():
        i = int(np.argmax(np.where(writes, offs, 0)))
        furthest = (int(offs[i]), int(arr["size"][i]))
    # Keys whose final entry is a delete (keep-LAST semantics).
    keys = arr["key"].astype(np.uint64)
    _uniq, idx_rev = np.unique(keys[::-1], return_index=True)
    last = len(keys) - 1 - idx_rev
    dead_sel = ~writes[last]
    dead = {int(k) for k in keys[last][dead_sel]}
    return furthest, dead


def verify_idx_against_dat(idx_path: str, dat_path: str | None) -> None:
    """Crash-staleness gate run before an .idx is trusted
    (volume_checking.go's CheckVolumeDataIntegrity direction): a
    partial trailing entry is truncated away, and an index whose
    furthest entry points past the .dat's EOF is lying about data that
    no longer exists — regenerate it from the .dat (scanner-based
    `weed fix`) instead of silently trusting it."""
    if not dat_path or not os.path.exists(idx_path) \
            or not os.path.exists(dat_path):
        return
    furthest, _dead = idx_crash_state(idx_path)
    if furthest is None:
        return
    from ..core.needle import get_actual_size
    from .volume_scanner import generate_idx_from_dat, read_super_block
    end = furthest[0] + get_actual_size(
        furthest[1], read_super_block(dat_path).version)
    if end > os.path.getsize(dat_path):
        generate_idx_from_dat(dat_path, idx_path)


class MemoryNeedleMap:
    """NeedleMapper: dict index + write-through append to the .idx file."""

    def __init__(self, idx_file=None):
        self._m: dict[int, tuple[int, int]] = {}
        self.metrics = MapMetrics()
        self._idx_file = idx_file

    @classmethod
    def load(cls, idx_path: str,
             dat_path: str | None = None) -> "MemoryNeedleMap":
        """Rebuild the map from an existing .idx (LoadNewNeedleMap).
        With `dat_path`, the idx tail is first verified against the
        .dat (partial entries truncated, beyond-EOF indexes
        regenerated by the scanner) instead of trusted blindly."""
        verify_idx_against_dat(idx_path, dat_path)
        f = open(idx_path, "a+b")
        f.seek(0)
        nm = cls(idx_file=f)
        for e in idx_mod.iter_index(f):
            nm.metrics.maximum_file_key = max(nm.metrics.maximum_file_key,
                                              e.key)
            if e.offset > 0 and t.size_is_valid(e.size):
                prev = nm._m.get(e.key)
                if prev is not None:
                    nm.metrics.deletion_count += 1
                    nm.metrics.deletion_byte_count += prev[1]
                else:
                    nm.metrics.file_count += 1
                nm.metrics.file_byte_count += e.size
                nm._m[e.key] = (e.offset, e.size)
            else:
                prev = nm._m.pop(e.key, None)
                if prev is not None:
                    nm.metrics.deletion_count += 1
                    nm.metrics.deletion_byte_count += prev[1]
        f.seek(0, os.SEEK_END)
        return nm

    def put(self, key: int, offset: int, size: int) -> None:
        prev = self._m.get(key)
        if prev is not None:
            self.metrics.deletion_count += 1
            self.metrics.deletion_byte_count += prev[1]
        else:
            self.metrics.file_count += 1
        self.metrics.file_byte_count += size
        self.metrics.maximum_file_key = max(self.metrics.maximum_file_key, key)
        self._m[key] = (offset, size)
        if self._idx_file is not None:
            idx_mod.append_entry(self._idx_file, key, offset, size)

    def get(self, key: int) -> tuple[int, int] | None:
        return self._m.get(key)

    def delete(self, key: int) -> int:
        """Returns freed bytes; writes a tombstone idx entry."""
        prev = self._m.pop(key, None)
        if prev is None:
            return 0
        self.metrics.deletion_count += 1
        self.metrics.deletion_byte_count += prev[1]
        if self._idx_file is not None:
            idx_mod.append_entry(self._idx_file, key, 0,
                                 t.TOMBSTONE_FILE_SIZE)
        return prev[1]

    def ordered_offsets(self) -> list[int]:
        """Live-needle .dat offsets in append (= offset) order — the
        probe set for BinarySearchByAppendAtNs (append-only volumes are
        time-ordered by offset)."""
        return sorted(off for off, size in self._m.values()
                      if t.size_is_valid(size))

    def ascending_visit(self, fn) -> None:
        for key in sorted(self._m):
            off, size = self._m[key]
            fn(t.NeedleMapEntry(key, off, size))

    def __len__(self) -> int:
        return len(self._m)

    def __contains__(self, key: int) -> bool:
        return key in self._m

    def content_size(self) -> int:
        return self.metrics.file_byte_count

    def deleted_size(self) -> int:
        return self.metrics.deletion_byte_count

    def flush(self) -> None:
        if self._idx_file is not None:
            self._idx_file.flush()

    def sync(self) -> None:
        """flush + fsync the .idx journal — the durability half of
        Volume.sync (the reference's commitNeedleMap path)."""
        if self._idx_file is not None:
            self._idx_file.flush()
            os.fsync(self._idx_file.fileno())

    def close(self) -> None:
        if self._idx_file is not None:
            self._idx_file.flush()
            self._idx_file.close()
            self._idx_file = None


class CompactNeedleMap:
    """Bounded-memory NeedleMapper (see module docstring).

    Base state: three sorted-by-key numpy arrays holding only LIVE
    entries (raw u32 offsets — 16 bytes/entry total).  Mutations land in
    a dict overflow (tombstones as size=TOMBSTONE) and merge into the
    arrays every OVERFLOW_MERGE updates, so a long-lived writable volume
    stays within the same memory envelope as its .idx file.
    """

    OVERFLOW_MERGE = 16384

    def __init__(self, idx_file=None):
        self._keys = np.empty(0, np.uint64)
        self._offs = np.empty(0, _off_np_dtype())  # /8 units
        self._sizes = np.empty(0, np.int32)
        self._overflow: dict[int, tuple[int, int]] = {}
        self._live = 0
        self.metrics = MapMetrics()
        self._idx_file = idx_file
        # The dict map this replaced was GIL-atomic; sorted-array swaps in
        # _merge() are not.  Vacuum's lock-free get()s and the tail path's
        # ordered_offsets() run concurrently with the write worker, so every
        # public method takes this lock (RLock: put/delete call get).
        self._lock = threading.RLock()

    @classmethod
    def load(cls, idx_path: str,
             dat_path: str | None = None) -> "CompactNeedleMap":
        """Vectorized .idx replay: keep-last per key, drop dead keys.

        Replaces the reference's per-entry walk (needle_map_memory.go)
        with one numpy pass — the load-time analog of batching onto the
        vector unit.  With `dat_path`, a crash-stale idx (partial tail
        entry, entries past the .dat EOF) is repaired/regenerated
        first — see verify_idx_against_dat."""
        verify_idx_against_dat(idx_path, dat_path)
        f = open(idx_path, "a+b")
        f.seek(0)
        raw = f.read()
        f.seek(0, os.SEEK_END)
        nm = cls(idx_file=f)
        usable = len(raw) - len(raw) % idx_mod.ENTRY_SIZE
        arr = np.frombuffer(raw[:usable], dtype=_idx_dtype())
        if len(arr) == 0:
            return nm
        offs = _units_col(arr)
        sizes = arr["size"].astype(np.int32)
        nm.metrics.maximum_file_key = int(arr["key"].astype(np.uint64).max())
        live_sel = _keep_last_live(arr)
        nm._keys = live_sel["key"].astype(np.uint64)
        nm._offs = _units_col(live_sel).astype(_off_np_dtype())
        nm._sizes = live_sel["size"].astype(np.int32)
        nm._live = len(live_sel)
        writes = (offs > 0) & (sizes > 0)
        write_bytes = int(sizes[writes].sum())
        live_bytes = int(nm._sizes.sum())
        nm.metrics.file_count = nm._live
        nm.metrics.file_byte_count = write_bytes
        nm.metrics.deletion_count = int(writes.sum()) - nm._live
        nm.metrics.deletion_byte_count = write_bytes - live_bytes
        return nm

    # -- lookups -------------------------------------------------------------

    def _base_get(self, key: int) -> tuple[int, int] | None:
        i = int(np.searchsorted(self._keys, np.uint64(key)))
        if i < len(self._keys) and int(self._keys[i]) == key:
            return (int(self._offs[i]) * t.NEEDLE_PADDING_SIZE,
                    int(self._sizes[i]))
        return None

    def get(self, key: int) -> tuple[int, int] | None:
        with self._lock:
            hit = self._overflow.get(key)
            if hit is not None:
                return None if hit[1] == t.TOMBSTONE_FILE_SIZE else hit
            return self._base_get(key)

    def __contains__(self, key: int) -> bool:
        return self.get(key) is not None

    def __len__(self) -> int:
        return self._live

    # -- mutations -----------------------------------------------------------

    def put(self, key: int, offset: int, size: int) -> None:
        with self._lock:
            prev = self.get(key)
            if prev is not None:
                self.metrics.deletion_count += 1
                self.metrics.deletion_byte_count += prev[1]
            else:
                self.metrics.file_count += 1
                self._live += 1
            self.metrics.file_byte_count += size
            self.metrics.maximum_file_key = max(
                self.metrics.maximum_file_key, key)
            self._overflow[key] = (offset, size)
            if self._idx_file is not None:
                idx_mod.append_entry(self._idx_file, key, offset, size)
            if len(self._overflow) >= self.OVERFLOW_MERGE:
                self._merge()

    def delete(self, key: int) -> int:
        with self._lock:
            prev = self.get(key)
            if prev is None:
                return 0
            self.metrics.deletion_count += 1
            self.metrics.deletion_byte_count += prev[1]
            self._live -= 1
            self._overflow[key] = (0, t.TOMBSTONE_FILE_SIZE)
            if self._idx_file is not None:
                idx_mod.append_entry(self._idx_file, key, 0,
                                     t.TOMBSTONE_FILE_SIZE)
            if len(self._overflow) >= self.OVERFLOW_MERGE:
                self._merge()
            return prev[1]

    def _merge(self) -> None:
        """Fold the overflow into the sorted base arrays (caller holds
        self._lock)."""
        if not self._overflow:
            return
        items = sorted(self._overflow.items())
        okeys = np.array([k for k, _ in items], np.uint64)
        ooffs = np.array([v[0] // t.NEEDLE_PADDING_SIZE for _, v in items],
                         _off_np_dtype())
        osizes = np.array([v[1] for _, v in items], np.int32)
        keep = ~np.isin(self._keys, okeys, assume_unique=True)
        olive = osizes > 0
        new_keys = np.concatenate([self._keys[keep], okeys[olive]])
        new_offs = np.concatenate([self._offs[keep], ooffs[olive]])
        new_sizes = np.concatenate([self._sizes[keep], osizes[olive]])
        order = np.argsort(new_keys, kind="stable")
        self._keys = new_keys[order]
        self._offs = new_offs[order]
        self._sizes = new_sizes[order]
        self._overflow.clear()

    # -- iteration / stats ---------------------------------------------------

    def ordered_offsets(self):
        """Live-needle .dat offsets in append (= offset) order — the
        probe set for BinarySearchByAppendAtNs."""
        with self._lock:
            self._merge()
            return np.sort(self._offs).astype(np.int64) * \
                t.NEEDLE_PADDING_SIZE

    def ascending_visit(self, fn) -> None:
        with self._lock:
            self._merge()
            keys = self._keys
            offs = self._offs
            sizes = self._sizes
        pad = t.NEEDLE_PADDING_SIZE
        for i in range(len(keys)):
            fn(t.NeedleMapEntry(int(keys[i]), int(offs[i]) * pad,
                                int(sizes[i])))

    def content_size(self) -> int:
        return self.metrics.file_byte_count

    def deleted_size(self) -> int:
        return self.metrics.deletion_byte_count

    def index_memory_bytes(self) -> int:
        """Resident bytes held by the index arrays (diagnostics/tests)."""
        return (self._keys.nbytes + self._offs.nbytes +
                self._sizes.nbytes)

    def flush(self) -> None:
        if self._idx_file is not None:
            self._idx_file.flush()

    def sync(self) -> None:
        if self._idx_file is not None:
            self._idx_file.flush()
            os.fsync(self._idx_file.fileno())

    def close(self) -> None:
        if self._idx_file is not None:
            self._idx_file.flush()
            self._idx_file.close()
            self._idx_file = None


class SortedFileNeedleMap:
    """O(1)-RAM mapper for read-only volumes: every lookup binary-
    searches a by-key-sorted index file (`.sdx`) with preads — nothing
    but metrics lives in memory.  Reference:
    weed/storage/needle_map_sorted_file.go."""

    def __init__(self, sdx_path: str):
        self._f = open(sdx_path, "rb")
        self._path = sdx_path
        size = os.fstat(self._f.fileno()).st_size
        self._n = size // idx_mod.ENTRY_SIZE
        self.metrics = MapMetrics()
        self._live = 0
        # One bounded streaming pass for the counters.
        self._f.seek(0)
        while True:
            chunk = self._f.read(idx_mod.ENTRY_SIZE * 65536)
            if not chunk:
                break
            arr = np.frombuffer(
                chunk[:len(chunk) - len(chunk) % idx_mod.ENTRY_SIZE],
                dtype=_idx_dtype())
            sizes = arr["size"].astype(np.int64)
            live = sizes > 0
            self._live += int(live.sum())
            self.metrics.file_byte_count += int(sizes[live].sum())
            if len(arr):
                self.metrics.maximum_file_key = max(
                    self.metrics.maximum_file_key,
                    int(arr["key"].astype(np.uint64).max()))
        self.metrics.file_count = self._live

    @staticmethod
    def generate(idx_path: str, sdx_path: str) -> None:
        """Sort an .idx into the .sdx this map searches
        (WriteSortedFileFromIdx for volumes).

        One numpy pass over the raw records — 16 bytes/entry transient,
        never a Python dict — so generating on a huge volume's idx stays
        within the memory envelope the mapper itself promises."""
        with open(idx_path, "rb") as f:
            raw = f.read()
        usable = len(raw) - len(raw) % idx_mod.ENTRY_SIZE
        arr = np.frombuffer(raw[:usable], dtype=_idx_dtype())
        payload = _keep_last_live(arr).tobytes() if len(arr) else b""
        tmp = sdx_path + ".tmp"
        with open(tmp, "wb") as out:
            out.write(payload)
            out.flush()
            os.fsync(out.fileno())
        os.replace(tmp, sdx_path)
        # Change-detector sidecar: .idx files are append-only, so the
        # source byte length is an exact staleness signal where mtime
        # granularity is not.
        with open(sdx_path + ".src", "w") as meta:
            meta.write(str(usable))

    @classmethod
    def load(cls, idx_path: str) -> "SortedFileNeedleMap":
        """Open, regenerating the .sdx when missing or stale.  Staleness
        checks the recorded source .idx length (append-only ⇒ exact),
        not mtime, so an append landing within mtime granularity still
        triggers regeneration."""
        sdx = idx_path[:-4] + ".sdx" if idx_path.endswith(".idx") \
            else idx_path + ".sdx"
        stale = not os.path.exists(sdx)
        if not stale and os.path.exists(idx_path):
            try:
                with open(sdx + ".src") as meta:
                    recorded = int(meta.read().strip())
            except (OSError, ValueError):
                recorded = -1
            cur = os.path.getsize(idx_path)
            stale = recorded != cur - cur % idx_mod.ENTRY_SIZE
        if stale:
            cls.generate(idx_path, sdx)
        return cls(sdx)

    def _entry_at(self, i: int) -> t.NeedleMapEntry:
        raw = os.pread(self._f.fileno(), idx_mod.ENTRY_SIZE,
                       i * idx_mod.ENTRY_SIZE)
        return t.NeedleMapEntry.from_bytes(raw)

    def get(self, key: int) -> tuple[int, int] | None:
        lo, hi = 0, self._n - 1
        while lo <= hi:
            mid = (lo + hi) // 2
            e = self._entry_at(mid)
            if e.key == key:
                if e.offset > 0 and t.size_is_valid(e.size):
                    return (e.offset, e.size)
                return None
            if e.key < key:
                lo = mid + 1
            else:
                hi = mid - 1
        return None

    def __contains__(self, key: int) -> bool:
        return self.get(key) is not None

    def __len__(self) -> int:
        return self._live

    def put(self, key: int, offset: int, size: int) -> None:
        raise RuntimeError("sorted-file needle map is read-only")

    def delete(self, key: int) -> int:
        raise RuntimeError("sorted-file needle map is read-only")

    def ordered_offsets(self):
        offs: list[int] = []
        self.ascending_visit(
            lambda e: offs.append(e.offset)
            if t.size_is_valid(e.size) else None)
        return np.sort(np.array(offs, np.int64))

    def ascending_visit(self, fn) -> None:
        self._f.seek(0)
        for e in idx_mod.iter_index(self._f):
            fn(e)

    def content_size(self) -> int:
        return self.metrics.file_byte_count

    def deleted_size(self) -> int:
        return self.metrics.deletion_byte_count

    def flush(self) -> None:
        pass

    def sync(self) -> None:
        pass  # the .sdx is immutable once generated

    def close(self) -> None:
        self._f.close()


NEEDLE_MAP_KINDS = ("compact", "memory", "sorted_file")


def new_needle_map(kind: str, idx_path: str,
                   dat_path: str | None = None):
    """NeedleMapType selection (needle_map.go:12-36).  `dat_path`
    enables the crash-staleness gate (verify_idx_against_dat)."""
    if kind == "compact":
        return CompactNeedleMap.load(idx_path, dat_path)
    if kind == "memory":
        return MemoryNeedleMap.load(idx_path, dat_path)
    if kind == "sorted_file":
        return SortedFileNeedleMap.load(idx_path)
    raise ValueError(f"unknown needle map kind {kind!r}")


class MemDb:
    """Sorted key -> entry map used for .ecx generation and offline tools."""

    def __init__(self):
        self._m: dict[int, tuple[int, int]] = {}

    def set(self, key: int, offset: int, size: int) -> None:
        self._m[key] = (offset, size)

    def delete(self, key: int) -> None:
        self._m.pop(key, None)

    def get(self, key: int) -> tuple[int, int] | None:
        return self._m.get(key)

    def ascending_visit(self, fn) -> None:
        for key in sorted(self._m):
            off, size = self._m[key]
            fn(t.NeedleMapEntry(key, off, size))

    @classmethod
    def from_idx(cls, readable) -> "MemDb":
        """Load .idx applying deletions (readNeedleMap, ec_encoder.go:289)."""
        db = cls()
        for e in idx_mod.iter_index(readable):
            if e.offset > 0 and e.size != t.TOMBSTONE_FILE_SIZE:
                db.set(e.key, e.offset, e.size)
            else:
                db.delete(e.key)
        return db

    def to_sorted_bytes(self) -> bytes:
        """Serialize ascending — the exact .ecx payload."""
        out = io.BytesIO()
        self.ascending_visit(lambda e: out.write(e.to_bytes()))
        return out.getvalue()
