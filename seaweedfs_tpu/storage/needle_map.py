"""In-memory needle maps: needle id -> (offset, size) with live counters.

The reference offers several NeedleMapper implementations (CompactMap,
LevelDB, sorted-file, btree MemDb — weed/storage/needle_map.go:12-36).  In
Python a dict already gives the CompactMap's O(1) behavior without its
section machinery, so `MemoryNeedleMap` is the default store-side mapper
(write-through to the `.idx` file like the reference's baseNeedleMapper),
and `MemDb` is the sorted variant used to build `.ecx` files
(weed/storage/needle_map/memdb.go).
"""

from __future__ import annotations

import io
import os
from dataclasses import dataclass, field

from ..core import idx as idx_mod
from ..core import types as t


@dataclass
class MapMetrics:
    file_count: int = 0
    deletion_count: int = 0
    file_byte_count: int = 0
    deletion_byte_count: int = 0
    maximum_file_key: int = 0


class MemoryNeedleMap:
    """NeedleMapper: dict index + write-through append to the .idx file."""

    def __init__(self, idx_file=None):
        self._m: dict[int, tuple[int, int]] = {}
        self.metrics = MapMetrics()
        self._idx_file = idx_file

    @classmethod
    def load(cls, idx_path: str) -> "MemoryNeedleMap":
        """Rebuild the map from an existing .idx (LoadNewNeedleMap)."""
        f = open(idx_path, "a+b")
        f.seek(0)
        nm = cls(idx_file=f)
        for e in idx_mod.iter_index(f):
            nm.metrics.maximum_file_key = max(nm.metrics.maximum_file_key,
                                              e.key)
            if e.offset > 0 and t.size_is_valid(e.size):
                prev = nm._m.get(e.key)
                if prev is not None:
                    nm.metrics.deletion_count += 1
                    nm.metrics.deletion_byte_count += prev[1]
                else:
                    nm.metrics.file_count += 1
                nm.metrics.file_byte_count += e.size
                nm._m[e.key] = (e.offset, e.size)
            else:
                prev = nm._m.pop(e.key, None)
                if prev is not None:
                    nm.metrics.deletion_count += 1
                    nm.metrics.deletion_byte_count += prev[1]
        f.seek(0, os.SEEK_END)
        return nm

    def put(self, key: int, offset: int, size: int) -> None:
        prev = self._m.get(key)
        if prev is not None:
            self.metrics.deletion_count += 1
            self.metrics.deletion_byte_count += prev[1]
        else:
            self.metrics.file_count += 1
        self.metrics.file_byte_count += size
        self.metrics.maximum_file_key = max(self.metrics.maximum_file_key, key)
        self._m[key] = (offset, size)
        if self._idx_file is not None:
            idx_mod.append_entry(self._idx_file, key, offset, size)

    def get(self, key: int) -> tuple[int, int] | None:
        return self._m.get(key)

    def delete(self, key: int) -> int:
        """Returns freed bytes; writes a tombstone idx entry."""
        prev = self._m.pop(key, None)
        if prev is None:
            return 0
        self.metrics.deletion_count += 1
        self.metrics.deletion_byte_count += prev[1]
        if self._idx_file is not None:
            idx_mod.append_entry(self._idx_file, key, 0,
                                 t.TOMBSTONE_FILE_SIZE)
        return prev[1]

    def ordered_offsets(self) -> list[int]:
        """Live-needle .dat offsets in append (= offset) order — the
        probe set for BinarySearchByAppendAtNs (append-only volumes are
        time-ordered by offset)."""
        return sorted(off for off, size in self._m.values()
                      if t.size_is_valid(size))

    def ascending_visit(self, fn) -> None:
        for key in sorted(self._m):
            off, size = self._m[key]
            fn(t.NeedleMapEntry(key, off, size))

    def __len__(self) -> int:
        return len(self._m)

    def __contains__(self, key: int) -> bool:
        return key in self._m

    def content_size(self) -> int:
        return self.metrics.file_byte_count

    def deleted_size(self) -> int:
        return self.metrics.deletion_byte_count

    def flush(self) -> None:
        if self._idx_file is not None:
            self._idx_file.flush()

    def close(self) -> None:
        if self._idx_file is not None:
            self._idx_file.flush()
            self._idx_file.close()
            self._idx_file = None


class MemDb:
    """Sorted key -> entry map used for .ecx generation and offline tools."""

    def __init__(self):
        self._m: dict[int, tuple[int, int]] = {}

    def set(self, key: int, offset: int, size: int) -> None:
        self._m[key] = (offset, size)

    def delete(self, key: int) -> None:
        self._m.pop(key, None)

    def get(self, key: int) -> tuple[int, int] | None:
        return self._m.get(key)

    def ascending_visit(self, fn) -> None:
        for key in sorted(self._m):
            off, size = self._m[key]
            fn(t.NeedleMapEntry(key, off, size))

    @classmethod
    def from_idx(cls, readable) -> "MemDb":
        """Load .idx applying deletions (readNeedleMap, ec_encoder.go:289)."""
        db = cls()
        for e in idx_mod.iter_index(readable):
            if e.offset > 0 and e.size != t.TOMBSTONE_FILE_SIZE:
                db.set(e.key, e.offset, e.size)
            else:
                db.delete(e.key)
        return db

    def to_sorted_bytes(self) -> bytes:
        """Serialize ascending — the exact .ecx payload."""
        out = io.BytesIO()
        self.ascending_visit(lambda e: out.write(e.to_bytes()))
        return out.getvalue()
