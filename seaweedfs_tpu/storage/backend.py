"""Storage backends: where a volume's `.dat` bytes physically live.

Reference: weed/storage/backend/backend.go:15-47 — `BackendStorageFile`
(ReadAt/WriteAt/Truncate/Sync/GetStat) + `BackendStorage` (NewStorageFile,
CopyFile up, DownloadFile back, DeleteFile) with a factory registry;
disk_file.go is the default, s3_backend/ the remote tier.

Here: DiskFile (local), S3Backend (any S3-compatible endpoint — including
this framework's own gateway — via the shared sig v4 signer), and
LocalDirBackend (a directory posing as remote: tests + second-mount
tiers).  Remote reads go through RemoteFile with an LRU block cache so
needle preads against a tiered volume stay cheap.
"""

from __future__ import annotations

import os
import shutil
import urllib.parse
import urllib.request

REMOTE_BLOCK = 1 << 20  # ranged-GET granularity for remote preads

# Needle-read ranged GETs must be bounded: a WAN-partitioned backend
# has to surface as a fast, retryable error (the volume server maps it
# to 503), never a 60s-per-block hang that wedges every reader queued
# behind the singleflight.
REMOTE_READ_TIMEOUT = 20.0


class BackendStorageFile:
    """Random-access file surface (backend.go BackendStorageFile)."""

    def pread(self, size: int, offset: int) -> bytes:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass


class DiskFile(BackendStorageFile):
    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "rb")

    def pread(self, size: int, offset: int) -> bytes:
        return os.pread(self._f.fileno(), size, offset)

    def size(self) -> int:
        return os.fstat(self._f.fileno()).st_size

    def close(self) -> None:
        self._f.close()


class RemoteFile(BackendStorageFile):
    """Read-only view of a remote object: block-aligned range reads
    through the process-global read-through cache (storage/remote_cache
    — bounded bytes, singleflight per block), plus the per-read
    accounting the promotion policy consumes."""

    def __init__(self, backend: "BackendStorage", key: str,
                 file_size: int, cache_blocks: int = 32):
        # cache_blocks is accepted for signature compatibility; the
        # budget is the process-wide byte bound now.
        self.backend = backend
        self.key = key
        self._size = file_size

    def _block(self, idx: int) -> tuple[bytes, bool]:
        from .remote_cache import CACHE
        lo = idx * REMOTE_BLOCK
        n = min(REMOTE_BLOCK, self._size - lo)
        return CACHE.get_block(self.backend, self.key, idx, lo, n)

    def pread(self, size: int, offset: int) -> bytes:
        from .remote_cache import CACHE
        if offset >= self._size:
            return b""
        size = min(size, self._size - offset)
        out = bytearray()
        pos = offset
        hit_b = miss_b = 0
        while pos < offset + size:
            idx = pos // REMOTE_BLOCK
            blk, hit = self._block(idx)
            lo = pos - idx * REMOTE_BLOCK
            take = min(len(blk) - lo, offset + size - pos)
            out += blk[lo:lo + take]
            pos += take
            if hit:
                hit_b += take
            else:
                miss_b += take
        if hit_b:
            CACHE.record_served(hit_b, hit=True)
        if miss_b:
            CACHE.record_served(miss_b, hit=False)
        CACHE.record_read(self.backend.spec, self.key)
        return bytes(out)

    def size(self) -> int:
        return self._size


class BackendStorage:
    """One remote tier destination (backend.go BackendStorage)."""

    spec: str = ""

    def upload_file(self, key: str, path: str) -> int:
        """Copy a local file up; returns byte size."""
        raise NotImplementedError

    def download_file(self, key: str, path: str) -> int:
        raise NotImplementedError

    def read_range(self, key: str, offset: int, size: int) -> bytes:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def open_file(self, key: str, file_size: int) -> RemoteFile:
        return RemoteFile(self, key, file_size)


class LocalDirBackend(BackendStorage):
    """'local://<dir>': a directory posing as a remote tier."""

    def __init__(self, directory: str):
        self.dir = directory
        self.spec = f"local://{directory}"
        os.makedirs(directory, exist_ok=True)

    def _p(self, key: str) -> str:
        return os.path.join(self.dir, key.replace("/", "_"))

    def upload_file(self, key: str, path: str) -> int:
        shutil.copyfile(path, self._p(key))
        return os.path.getsize(self._p(key))

    def download_file(self, key: str, path: str) -> int:
        shutil.copyfile(self._p(key), path)
        return os.path.getsize(path)

    def read_range(self, key: str, offset: int, size: int) -> bytes:
        with open(self._p(key), "rb") as f:
            return os.pread(f.fileno(), size, offset)

    def delete(self, key: str) -> None:
        try:
            os.remove(self._p(key))
        except FileNotFoundError:
            pass


class S3Backend(BackendStorage):
    """'s3://host:port/bucket[/prefix]': S3-compatible remote tier
    (backend/s3_backend/s3_backend.go) signed with the shared sig v4
    client."""

    def __init__(self, endpoint: str, bucket: str, prefix: str = "",
                 access_key: str = "", secret_key: str = ""):
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        scheme = "s3+https" if self.endpoint.startswith("https") \
            else "s3"
        host = self.endpoint.split("://", 1)[-1]
        self.spec = f"{scheme}://{host}/{bucket}" + \
            (f"/{self.prefix}" if self.prefix else "")

    def _url(self, key: str) -> str:
        k = f"{self.prefix}/{key}" if self.prefix else key
        return f"{self.endpoint}/{self.bucket}/" + \
            urllib.parse.quote(k)

    def _request(self, key: str, method: str, data: bytes = b"",
                 headers: dict | None = None) -> bytes:
        headers = dict(headers or {})
        if self.access_key:
            from ..s3api.sigv4 import sign_request
            headers = sign_request(method, self._url(key), headers,
                                   data, self.access_key,
                                   self.secret_key)
        req = urllib.request.Request(
            self._url(key), data=data if method in ("PUT", "POST")
            else None, method=method, headers=headers)
        with urllib.request.urlopen(req, timeout=600) as resp:
            return resp.read()

    def upload_file(self, key: str, path: str) -> int:
        """Streaming PUT: hash pass then a file-object body, so a 30GB
        .dat never materializes in memory."""
        import hashlib
        size = os.path.getsize(path)
        sha = hashlib.sha256()
        with open(path, "rb") as f:
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                sha.update(chunk)
        headers = {"Content-Length": str(size),
                   "x-amz-content-sha256": sha.hexdigest()}
        if self.access_key:
            from ..s3api.sigv4 import sign_request
            headers = sign_request(
                "PUT", self._url(key), {"Content-Length": str(size)},
                b"", self.access_key, self.secret_key,
                payload_hash=sha.hexdigest())
        with open(path, "rb") as f:
            req = urllib.request.Request(self._url(key), data=f,
                                         method="PUT", headers=headers)
            with urllib.request.urlopen(req, timeout=3600) as resp:
                resp.read()
        return size

    def download_file(self, key: str, path: str) -> int:
        headers = {}
        if self.access_key:
            from ..s3api.sigv4 import sign_request
            headers = sign_request("GET", self._url(key), {}, b"",
                                   self.access_key, self.secret_key)
        req = urllib.request.Request(self._url(key), headers=headers)
        total = 0
        with urllib.request.urlopen(req, timeout=3600) as resp, \
                open(path, "wb") as f:
            while True:
                chunk = resp.read(1 << 20)
                if not chunk:
                    break
                f.write(chunk)
                total += len(chunk)
        return total

    def read_range(self, key: str, offset: int, size: int) -> bytes:
        hdrs = {"Range": f"bytes={offset}-{offset + size - 1}"}
        if self.access_key:
            from ..s3api.sigv4 import sign_request
            hdrs = sign_request("GET", self._url(key), hdrs, b"",
                                self.access_key, self.secret_key)
        req = urllib.request.Request(self._url(key), headers=hdrs)
        with urllib.request.urlopen(
                req, timeout=REMOTE_READ_TIMEOUT) as resp:
            return resp.read()

    def delete(self, key: str) -> None:
        try:
            self._request(key, "DELETE")
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise


def backend_for_spec(spec: str, access_key: str = "",
                     secret_key: str = "") -> BackendStorage:
    """'local:///dir' or 's3://host:port/bucket[/prefix]' -> backend
    (the factory registry, backend.go:48-93)."""
    scheme, _, rest = spec.partition("://")
    if scheme == "local":
        return LocalDirBackend("/" + rest.lstrip("/"))
    if scheme in ("s3", "s3+https"):
        host, _, rest2 = rest.partition("/")
        bucket, _, prefix = rest2.partition("/")
        if not bucket:
            raise ValueError(f"s3 spec needs a bucket: {spec}")
        proto = "https" if scheme == "s3+https" else "http"
        return S3Backend(f"{proto}://{host}", bucket, prefix,
                         access_key, secret_key)
    raise ValueError(f"unknown backend spec: {spec}")
