"""Process-global read-through cache for filer chunk reads.

The per-ChunkStreamer OrderedDict this replaces had the same two
problems `remote_cache.py` solved for tiered volumes: the budget was
per-streamer (every FilerServer, shell command and test that built a
streamer got its own 64MB), and two concurrent readers of the same
cold chunk each paid a volume-server round-trip.  This cache is shared
by every streamer in the process, bounded in BYTES
(`-filer.cache.mb`), and singleflights per file_id: the first reader
fetches (and, for sealed chunks, decrypts — hits never re-pay the AES
pass), everyone else waits on its Event and then reads the cached
bytes.  A hot chunk — the volumes/needles `/debug/hot` names — costs
ONE downstream GET no matter how many requests land on it.

Packed small files (filer/packing.py) share a needle and therefore a
cache entry: one fetch of the pack warms every sibling file.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

from ..stats.sketch import WindowedSketch

# Bounded follower wait, same rationale as remote_cache.py: a wedged
# leader (dead volume server mid-GET) must not wedge every reader of
# the chunk behind it — the loop re-checks and elects a new leader.
SINGLEFLIGHT_WAIT = 30.0


class FilerChunkCache:
    """Bounded-bytes LRU of opened (decrypted) chunk bytes, keyed by
    file_id, with per-chunk singleflight."""

    def __init__(self, max_bytes: int = 64 << 20):
        self._lock = threading.Lock()
        self.max_bytes = max_bytes
        self._chunks: OrderedDict[str, bytes] = OrderedDict()
        self._bytes = 0
        self._inflight: dict[str, threading.Event] = {}
        self.hit_bytes = 0
        self.miss_bytes = 0
        self.evictions = 0
        self.fetch_latency = WindowedSketch(window=300.0)
        # Per-tenant byte cap (tenancy plane, 0 = disabled): one
        # tenant's working set may occupy at most this many cached
        # bytes, so a scan-heavy tenant evicts ITS OWN oldest chunks
        # instead of flushing everyone else's hot set.
        self.tenant_max_bytes = 0
        self._owners: dict[str, str] = {}       # file_id -> tenant
        self._tenant_bytes: dict[str, int] = {}
        self.tenant_evictions = 0

    def configure(self, max_bytes: int) -> None:
        with self._lock:
            self.max_bytes = max(0, int(max_bytes))
            self._evict_locked()

    def configure_tenant_cap(self, max_bytes: int) -> None:
        """-filer.cache.tenant.mb: uniform per-tenant occupancy cap."""
        with self._lock:
            self.tenant_max_bytes = max(0, int(max_bytes))
            for t in list(self._tenant_bytes):
                self._evict_tenant_locked(t, keep="")

    def get_or_fetch(self, file_id: str, fetch,
                     tenant: str = "") -> bytes:
        """Return the chunk bytes, fetching via `fetch()` at most once
        across concurrent callers.  `tenant` attributes the cache
        occupancy of a newly inserted chunk (first fetcher wins)."""
        while True:
            with self._lock:
                data = self._chunks.get(file_id)
                if data is not None:
                    self._chunks.move_to_end(file_id)
                    self.hit_bytes += len(data)
                    from ..stats import metrics as _metrics
                    _metrics.filer_chunk_cache_hit_bytes_total.inc(
                        len(data))
                    return data
                ev = self._inflight.get(file_id)
                if ev is None:
                    ev = threading.Event()
                    self._inflight[file_id] = ev
                    break  # we are the leader
            ev.wait(SINGLEFLIGHT_WAIT)
        try:
            t0 = time.perf_counter()
            data = fetch()
            self.fetch_latency.observe(time.perf_counter() - t0)
        except BaseException:
            with self._lock:
                self._inflight.pop(file_id, None)
            ev.set()
            raise
        with self._lock:
            if file_id not in self._chunks:
                self._chunks[file_id] = data
                self._bytes += len(data)
                if tenant:
                    self._owners[file_id] = tenant
                    self._tenant_bytes[tenant] = \
                        self._tenant_bytes.get(tenant, 0) + len(data)
                    self._evict_tenant_locked(tenant, keep=file_id)
            self._chunks.move_to_end(file_id)
            self.miss_bytes += len(data)
            self._evict_locked()
            self._inflight.pop(file_id, None)
        from ..stats import metrics as _metrics
        _metrics.filer_chunk_cache_miss_bytes_total.inc(len(data))
        ev.set()
        return data

    def _drop_owner_locked(self, file_id: str, nbytes: int) -> None:
        t = self._owners.pop(file_id, "")
        if t:
            left = self._tenant_bytes.get(t, 0) - nbytes
            if left > 0:
                self._tenant_bytes[t] = left
            else:
                self._tenant_bytes.pop(t, None)

    def _evict_locked(self) -> None:
        while self._bytes > self.max_bytes and self._chunks:
            fid, old = self._chunks.popitem(last=False)
            self._bytes -= len(old)
            self._drop_owner_locked(fid, len(old))
            self.evictions += 1

    def _evict_tenant_locked(self, tenant: str, keep: str) -> None:
        """Tenant-first eviction: while `tenant` is over its cap, drop
        ITS oldest chunks (never `keep`, the one just inserted — a
        single over-cap chunk still gets cached once)."""
        if self.tenant_max_bytes <= 0:
            return
        while self._tenant_bytes.get(tenant, 0) > self.tenant_max_bytes:
            victim = next(
                (fid for fid in self._chunks
                 if fid != keep and self._owners.get(fid) == tenant),
                None)
            if victim is None:
                return
            old = self._chunks.pop(victim)
            self._bytes -= len(old)
            self._drop_owner_locked(victim, len(old))
            self.evictions += 1
            self.tenant_evictions += 1

    def invalidate(self, file_id: str) -> None:
        with self._lock:
            old = self._chunks.pop(file_id, None)
            if old is not None:
                self._bytes -= len(old)
                self._drop_owner_locked(file_id, len(old))

    # -- introspection ---------------------------------------------------

    def used_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> dict:
        with self._lock:
            chunks = len(self._chunks)
            used = self._bytes
            hit_b, miss_b = self.hit_bytes, self.miss_bytes
            evictions = self.evictions
            tenant_rows = dict(self._tenant_bytes)
            tenant_cap = self.tenant_max_bytes
            tenant_evictions = self.tenant_evictions

        def _ms(q: float) -> float:
            v = self.fetch_latency.quantile(q)
            return round(v * 1000, 3) if v is not None else 0.0

        return {
            "max_bytes": self.max_bytes,
            "used_bytes": used,
            "chunks": chunks,
            "hit_bytes": hit_b,
            "miss_bytes": miss_b,
            "evictions": evictions,
            "fetch_ms": {"p50": _ms(0.5), "p99": _ms(0.99)},
            "tenant_max_bytes": tenant_cap,
            "tenant_evictions": tenant_evictions,
            "tenants": tenant_rows,
        }

    def reset(self) -> None:
        """Test hook: empty the cache and zero the counters."""
        with self._lock:
            self._chunks.clear()
            self._bytes = 0
            self._inflight.clear()
            self.hit_bytes = 0
            self.miss_bytes = 0
            self.evictions = 0
            self.fetch_latency = WindowedSketch(window=300.0)
            self.tenant_max_bytes = 0
            self._owners.clear()
            self._tenant_bytes.clear()
            self.tenant_evictions = 0


CACHE = FilerChunkCache()
