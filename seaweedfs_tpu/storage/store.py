"""Store: all volumes on one volume server, across disk locations.

Reference: weed/storage/store.go (Store), disk_location.go (DiskLocation).
The store discovers existing volumes at startup, routes needle CRUD by
volume id, assembles heartbeat summaries for the master, and emits delta
events (new/deleted volumes, EC shard mounts) that the cluster layer
streams to the master (store.go:198-268).
"""

from __future__ import annotations

import glob
import os
import re
import threading
from dataclasses import dataclass, field

from ..core.needle import Needle
from ..core.replica_placement import ReplicaPlacement
from ..core.ttl import TTL
from .volume import NotFoundError, Volume, VolumeError

_VOLUME_RE = re.compile(
    r"^(?:(?P<collection>.+)_)?(?P<vid>\d+)\.(?:dat|vif)$")


@dataclass
class VolumeInfo:
    """Heartbeat summary of one volume (master_pb VolumeInformationMessage)."""
    id: int
    collection: str
    size: int
    file_count: int
    delete_count: int
    deleted_byte_count: int
    read_only: bool
    replica_placement: int
    ttl: int
    compact_revision: int
    max_file_key: int = 0
    version: int = 3
    # Unrepaired corrupt needles (open repair tickets, storage/scrub):
    # nonzero degrades the volume on /cluster/healthz.
    corrupt_count: int = 0
    # Newest-write wall time (epoch sec) and tier state, the signals
    # the master's lifecycle daemon plans from (idle/age rules, TTL
    # retirement, don't-re-tier).
    modified_at: int = 0
    tiered: bool = False


class DiskLocation:
    """One data directory holding volumes (and EC shards)."""

    def __init__(self, directory: str, max_volume_count: int = 7):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_volume_count = max_volume_count
        self.volumes: dict[int, Volume] = {}
        self._lock = threading.RLock()

    def load_existing_volumes(self) -> int:
        count = 0
        with self._lock:
            # Tiered volumes FIRST (volume_tier.go maybeLoadVolumeInfo):
            # a .vif marks the remote copy as authoritative, so even a
            # keep_local .dat must not be opened writable — writes to it
            # would silently diverge from (and later lose to) the tier.
            tiered: set[int] = set()
            for path in sorted(glob.glob(os.path.join(self.directory,
                                                      "*.vif"))):
                m = _VOLUME_RE.match(os.path.basename(path))
                if not m:
                    continue
                from .tier import load_vif
                info = load_vif(path[:-4])
                if not info or not info.get("files"):
                    continue  # EC/version metadata, not a tier marker
                vid = int(m.group("vid"))
                tiered.add(vid)
                if vid in self.volumes:
                    continue
                collection = m.group("collection") or ""
                try:
                    from .tier import open_remote_volume
                    self.volumes[vid] = open_remote_volume(
                        self.directory, collection, vid)
                    count += 1
                except Exception:  # noqa: BLE001 — unreachable backend
                    continue       # must not block the store
            for path in sorted(glob.glob(os.path.join(self.directory,
                                                      "*.dat"))):
                m = _VOLUME_RE.match(os.path.basename(path))
                if not m:
                    continue
                vid = int(m.group("vid"))
                if vid in self.volumes or vid in tiered:
                    # A .vif whose backend was unreachable must NOT
                    # fall back to a writable stale local .dat.
                    continue
                collection = m.group("collection") or ""
                try:
                    self.volumes[vid] = Volume(
                        self.directory, collection, vid, create=False)
                    count += 1
                except Exception:  # noqa: BLE001 — one corrupt volume file
                    # (e.g. 0-byte .dat from a crashed create) must not
                    # prevent the rest of the store from loading.
                    continue
        return count

    def close(self) -> None:
        with self._lock:
            for v in self.volumes.values():
                v.close()
            self.volumes.clear()


class Store:
    """Routes needle operations to volumes; the volume server's core."""

    def __init__(self, directories: list[str],
                 max_volume_counts: list[int] | None = None,
                 ip: str = "localhost", port: int = 8080,
                 public_url: str = "",
                 disk_reserve_bytes: int = 0):
        self.ip = ip
        self.port = port
        self.public_url = public_url or f"{ip}:{port}"
        counts = max_volume_counts or [7] * len(directories)
        self.locations = [DiskLocation(d, c)
                          for d, c in zip(directories, counts)]
        for loc in self.locations:
            loc.load_existing_volumes()
        self._lock = threading.RLock()
        # Delta events for the heartbeat stream (master sync).
        self.new_volumes: list[VolumeInfo] = []
        self.deleted_volumes: list[VolumeInfo] = []
        # Free-space reserve (-disk.reserve): volumes on a location
        # whose free bytes fall below this flip readonly BEFORE ENOSPC
        # can tear a write.  low_disk_dirs feeds heartbeats (the master
        # steers assignment away) and the reserve-breached gauge.
        self.disk_reserve_bytes = int(disk_reserve_bytes)
        self.low_disk_dirs: set[str] = set()
        self._reserve_flipped: set[int] = set()

    def check_disk_reserve(self) -> list[int]:
        """Enforce the free-space reserve: flip volumes on a breached
        location readonly (recording them), and flip OUR flips back
        once free space recovers past twice the reserve — the
        hysteresis keeps a disk hovering at the reserve from flapping
        volumes between modes.  Called from the heartbeat path (every
        pulse) and after any ENOSPC.  Returns vids whose mode changed
        in EITHER direction — the caller must full-heartbeat on any
        change, or the master would keep recovered volumes out of its
        writable pool forever."""
        if self.disk_reserve_bytes <= 0:
            # Reserve disabled (possibly at runtime): drop any state a
            # previously-configured reserve left behind, or the node
            # would stay low-disk/readonly forever.
            reset: list[int] = []
            if self.low_disk_dirs or self._reserve_flipped:
                with self._lock:
                    self.low_disk_dirs.clear()
                    for loc in self.locations:
                        for v in list(loc.volumes.values()):
                            if v.vid in self._reserve_flipped and \
                                    v.readonly:
                                v.set_readonly(False)
                                reset.append(v.vid)
                    self._reserve_flipped.clear()
            return reset
        from ..stats.sysstats import disk_status
        flipped: list[int] = []
        with self._lock:
            for loc in self.locations:
                try:
                    free = disk_status(loc.directory)["free"]
                except OSError:
                    continue
                if free < self.disk_reserve_bytes:
                    newly_low = loc.directory not in self.low_disk_dirs
                    self.low_disk_dirs.add(loc.directory)
                    for v in list(loc.volumes.values()):
                        if not v.readonly:
                            v.set_readonly(True)
                            self._reserve_flipped.add(v.vid)
                            flipped.append(v.vid)
                    if newly_low or flipped:
                        from ..events import emit as emit_event
                        emit_event("disk.low",
                                   node=f"{self.ip}:{self.port}",
                                   severity="warn", dir=loc.directory,
                                   free_bytes=free,
                                   reserve_bytes=self.disk_reserve_bytes,
                                   flipped=len(flipped))
                elif loc.directory in self.low_disk_dirs and \
                        free >= 2 * self.disk_reserve_bytes:
                    self.low_disk_dirs.discard(loc.directory)
                    for v in list(loc.volumes.values()):
                        if v.vid in self._reserve_flipped and v.readonly:
                            v.set_readonly(False)
                            self._reserve_flipped.discard(v.vid)
                            flipped.append(v.vid)  # recovered: the
                            # master must re-learn writability too
        return flipped

    # -- volume management --------------------------------------------------

    def find_volume(self, vid: int) -> Volume | None:
        for loc in self.locations:
            v = loc.volumes.get(vid)
            if v is not None:
                return v
        return None

    def has_volume(self, vid: int) -> bool:
        return self.find_volume(vid) is not None

    def free_location(self) -> DiskLocation | None:
        """A disk location with spare volume slots, or None when full."""
        return self._find_free_location()

    def _find_free_location(self) -> DiskLocation | None:
        best, best_free = None, 0
        for loc in self.locations:
            free = loc.max_volume_count - len(loc.volumes)
            if free > best_free:
                best, best_free = loc, free
        return best

    def add_volume(self, vid: int, collection: str = "",
                   replica_placement: str = "000", ttl: str = "",
                   version: int = 3) -> Volume:
        with self._lock:
            if self.has_volume(vid):
                raise VolumeError(f"volume {vid} already exists")
            loc = self._find_free_location()
            if loc is None:
                raise VolumeError("no free disk location")
            v = Volume(loc.directory, collection, vid,
                       replica_placement=ReplicaPlacement.parse(
                           replica_placement),
                       ttl=TTL.parse(ttl), version=version)
            loc.volumes[vid] = v
            self.new_volumes.append(self._volume_info(v))
            return v

    def delete_volume(self, vid: int) -> None:
        with self._lock:
            for loc in self.locations:
                v = loc.volumes.pop(vid, None)
                if v is not None:
                    info = self._volume_info(v)
                    tiered = v.remote_file is not None
                    v.close()
                    base = v.file_name()
                    exts = [".dat", ".idx", ".qrt",
                            ".rlog", ".rwm", ".rap"]
                    if tiered:
                        # Only a tiered volume owns the .vif it mounts
                        # from.  A local volume's sidecar (if any)
                        # belongs to EC shards sharing the base name —
                        # ec.generate's version record must survive
                        # deleting the source replica.
                        exts.append(".vif")
                    for ext in exts:
                        try:
                            os.remove(base + ext)
                        except FileNotFoundError:
                            pass
                    self.deleted_volumes.append(info)
                    return
            raise VolumeError(f"volume {vid} not found")

    def mount_volume(self, vid: int) -> Volume:
        """Load an existing .dat/.idx pair from disk into the store
        (VolumeServer.VolumeMount — used after VolumeCopy pulls files)."""
        with self._lock:
            v = self.find_volume(vid)
            if v is not None:
                return v
            for loc in self.locations:
                # A .vif marks the remote copy authoritative — remount
                # must not reopen a keep_local .dat writable.
                for path in glob.glob(os.path.join(loc.directory,
                                                   "*.vif")):
                    m = _VOLUME_RE.match(os.path.basename(path))
                    if not m or int(m.group("vid")) != vid:
                        continue
                    from .tier import load_vif, open_remote_volume
                    info = load_vif(path[:-4])
                    if not info or not info.get("files"):
                        continue  # EC metadata .vif, not a tier marker
                    v = open_remote_volume(
                        loc.directory, m.group("collection") or "", vid)
                    loc.volumes[vid] = v
                    self.new_volumes.append(self._volume_info(v))
                    return v
                for path in glob.glob(os.path.join(loc.directory, "*.dat")):
                    m = _VOLUME_RE.match(os.path.basename(path))
                    if not m or int(m.group("vid")) != vid:
                        continue
                    v = Volume(loc.directory, m.group("collection") or "",
                               vid, create=False)
                    loc.volumes[vid] = v
                    self.new_volumes.append(self._volume_info(v))
                    return v
            raise VolumeError(f"no volume files for {vid} on this server")

    def unmount_volume(self, vid: int) -> None:
        """Detach a volume without deleting its files (VolumeUnmount)."""
        with self._lock:
            for loc in self.locations:
                v = loc.volumes.pop(vid, None)
                if v is not None:
                    self.deleted_volumes.append(self._volume_info(v))
                    v.close()
                    return
            raise VolumeError(f"volume {vid} not found")

    def configure_volume(self, vid: int, replication: str) -> None:
        """Change a mounted volume's replica placement in its superblock
        (store.ConfigureVolume); the next heartbeat reports the new
        placement so the master's layout re-groups it."""
        v = self.find_volume(vid)
        if v is None:
            raise VolumeError(f"volume {vid} not found")
        v.configure_replication(ReplicaPlacement.parse(replication))
        with self._lock:
            self.new_volumes.append(self._volume_info(v))

    def mark_volume_readonly(self, vid: int, ro: bool = True) -> None:
        v = self.find_volume(vid)
        if v is None:
            raise VolumeError(f"volume {vid} not found")
        v.set_readonly(ro)

    # -- needle CRUD ---------------------------------------------------------

    def write_needle(self, vid: int, n: Needle,
                     fsync: bool = False) -> tuple[int, int]:
        v = self.find_volume(vid)
        if v is None:
            raise VolumeError(f"volume {vid} not found")
        return v.write_needle(n, fsync=fsync)

    def read_needle(self, vid: int, needle_id: int,
                    cookie: int | None = None) -> Needle:
        v = self.find_volume(vid)
        if v is None:
            raise NotFoundError(f"volume {vid} not found")
        return v.read_needle(needle_id, cookie)

    def delete_needle(self, vid: int, needle_id: int) -> int:
        v = self.find_volume(vid)
        if v is None:
            raise VolumeError(f"volume {vid} not found")
        return v.delete_needle(needle_id)

    # -- heartbeat -----------------------------------------------------------

    def _volume_info(self, v: Volume) -> VolumeInfo:
        return VolumeInfo(
            id=v.vid, collection=v.collection, size=v.dat_size(),
            file_count=v.file_count(), delete_count=v.nm.metrics.deletion_count,
            deleted_byte_count=v.deleted_size(), read_only=v.readonly,
            replica_placement=v.super_block.replica_placement.to_byte(),
            ttl=v.super_block.ttl.to_uint32(),
            compact_revision=v.super_block.compaction_revision,
            max_file_key=v.max_file_key(), version=v.version,
            corrupt_count=v.corrupt_count(),
            modified_at=int(getattr(v, "modified_at", 0) or 0),
            tiered=v.remote_file is not None)

    def collect_heartbeat(self) -> dict:
        """Full heartbeat payload (CollectHeartbeat, store.go:198)."""
        volumes = []
        max_file_key = 0
        with self._lock:
            for loc in self.locations:
                for v in loc.volumes.values():
                    volumes.append(self._volume_info(v))
                    max_file_key = max(max_file_key, v.max_file_key())
        return {
            "ip": self.ip,
            "port": self.port,
            "public_url": self.public_url,
            "max_volume_count": sum(l.max_volume_count
                                    for l in self.locations),
            "max_file_key": max_file_key,
            "volumes": volumes,
        }

    def drain_deltas(self) -> tuple[list[VolumeInfo], list[VolumeInfo]]:
        with self._lock:
            new, deleted = self.new_volumes, self.deleted_volumes
            self.new_volumes, self.deleted_volumes = [], []
            return new, deleted

    def close(self) -> None:
        for loc in self.locations:
            loc.close()
