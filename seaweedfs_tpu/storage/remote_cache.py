"""Process-global read-through block cache for tiered volume reads.

A tiered volume's needle reads turn into ranged GETs against the remote
backend (weed/storage/backend/s3_backend does the same proxying).  The
per-RemoteFile OrderedDict this replaces had two problems at fleet
scale: the budget was per-file (1000 tiered volumes × 32 blocks = an
unbounded 32GB), and two concurrent readers of the same cold block each
paid a backend round-trip.  This cache is shared by every RemoteFile in
the process, bounded in BYTES (`-tier.cache.mb`), and singleflights per
block: the first reader fetches, everyone else waits on its Event and
then reads the cached block — a hot tiered needle costs ONE backend
fetch.

The cache also keeps the per-volume read clock the promotion policy
needs: `record_read` timestamps every tiered read per (spec, key), and
`hits_in_window` answers "how many reads in the last W seconds" so the
volume server can schedule a `tier_download` for a tiered volume that
turned hot again.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

from ..fault import registry as _fault
from ..stats.sketch import WindowedSketch

# A follower waiting on another thread's in-flight fetch bounds its wait
# so a wedged leader (WAN partition mid-GET) can never wedge every
# reader of the block behind it.
SINGLEFLIGHT_WAIT = 30.0

# Reads queued behind the most recent PROMOTE_KEEP timestamps per key
# are enough for any plausible hits-in-window policy; older ones are
# outside every window anyway.
_PROMOTE_KEEP = 256


class RemoteBlockCache:
    """Bounded-bytes LRU of remote blocks, keyed (spec, key, block_idx),
    with per-block singleflight."""

    def __init__(self, max_bytes: int = 64 << 20):
        self._lock = threading.Lock()
        self.max_bytes = max_bytes
        self._blocks: OrderedDict[tuple, bytes] = OrderedDict()
        self._bytes = 0
        self._inflight: dict[tuple, threading.Event] = {}
        # Served-byte counters at pread granularity: a re-read of a
        # cached needle counts its full size as hit bytes, which is
        # what "second pass is free" means operationally.
        self.hit_bytes = 0
        self.miss_bytes = 0
        self.evictions = 0
        self.fetch_latency = WindowedSketch(window=300.0)
        self._reads: dict[tuple[str, str], deque] = {}

    def configure(self, max_bytes: int) -> None:
        with self._lock:
            self.max_bytes = max(0, int(max_bytes))
            self._evict_locked()

    # -- block path ------------------------------------------------------

    def get_block(self, backend, key: str, idx: int, lo: int,
                  n: int) -> tuple[bytes, bool]:
        """Return (block bytes, served_from_cache).  Exactly one caller
        fetches a missing block; concurrent callers wait for it."""
        ck = (backend.spec, key, idx)
        while True:
            with self._lock:
                blk = self._blocks.get(ck)
                if blk is not None:
                    self._blocks.move_to_end(ck)
                    return blk, True
                ev = self._inflight.get(ck)
                if ev is None:
                    ev = threading.Event()
                    self._inflight[ck] = ev
                    break  # we are the leader
            # Follower: bounded wait, then re-check.  If the leader
            # failed (event set, block absent) the loop elects a new
            # leader instead of failing everyone on one bad fetch.
            ev.wait(SINGLEFLIGHT_WAIT)
        try:
            if _fault.ARMED:
                _fault.hit("tier.read", key=key, spec=backend.spec)
            t0 = time.perf_counter()
            blk = backend.read_range(key, lo, n)
            self.fetch_latency.observe(time.perf_counter() - t0)
            # Read-through block fetches are remote-tier wire traffic
            # outside the rpc plane: feed the flow ledger directly.
            from ..stats import flows as _flows
            _flows.LEDGER.note("tier.down", "in", len(blk),
                               peer=backend.spec, peer_role="remote")
        except BaseException:
            with self._lock:
                self._inflight.pop(ck, None)
            ev.set()
            raise
        with self._lock:
            self._blocks[ck] = blk
            self._blocks.move_to_end(ck)
            self._bytes += len(blk)
            self._evict_locked()
            self._inflight.pop(ck, None)
        ev.set()
        return blk, False

    def _evict_locked(self) -> None:
        while self._bytes > self.max_bytes and self._blocks:
            _, old = self._blocks.popitem(last=False)
            self._bytes -= len(old)
            self.evictions += 1

    def drop_file(self, spec: str, key: str) -> None:
        """Invalidate every cached block of one remote object (called
        when a volume promotes back to local disk — the remote copy may
        be deleted and must not shadow local reads)."""
        with self._lock:
            stale = [ck for ck in self._blocks
                     if ck[0] == spec and ck[1] == key]
            for ck in stale:
                self._bytes -= len(self._blocks.pop(ck))
            self._reads.pop((spec, key), None)

    # -- accounting ------------------------------------------------------

    def record_served(self, nbytes: int, hit: bool) -> None:
        with self._lock:
            if hit:
                self.hit_bytes += nbytes
            else:
                self.miss_bytes += nbytes
        from ..stats import metrics as _metrics
        if hit:
            _metrics.tier_cache_hit_bytes_total.inc(nbytes)
        else:
            _metrics.tier_cache_miss_bytes_total.inc(nbytes)

    def record_read(self, spec: str, key: str,
                    now: float | None = None) -> None:
        """Timestamp one tiered read of (spec, key) for the promotion
        window."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            dq = self._reads.get((spec, key))
            if dq is None:
                dq = self._reads[(spec, key)] = deque(
                    maxlen=_PROMOTE_KEEP)
            dq.append(now)

    def hits_in_window(self, spec: str, key: str, window: float,
                       now: float | None = None) -> int:
        if now is None:
            now = time.monotonic()
        with self._lock:
            dq = self._reads.get((spec, key))
            if not dq:
                return 0
            return sum(1 for ts in dq if now - ts <= window)

    # -- introspection ---------------------------------------------------

    def used_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> dict:
        with self._lock:
            blocks = len(self._blocks)
            used = self._bytes
            hit_b, miss_b = self.hit_bytes, self.miss_bytes
            evictions = self.evictions

        def _ms(q: float) -> float:
            v = self.fetch_latency.quantile(q)
            return round(v * 1000, 3) if v is not None else 0.0

        return {
            "max_bytes": self.max_bytes,
            "used_bytes": used,
            "blocks": blocks,
            "hit_bytes": hit_b,
            "miss_bytes": miss_b,
            "evictions": evictions,
            "fetch_ms": {"p50": _ms(0.5), "p99": _ms(0.99)},
        }

    def reset(self) -> None:
        """Test hook: empty the cache and zero the counters."""
        with self._lock:
            self._blocks.clear()
            self._bytes = 0
            self._inflight.clear()
            self.hit_bytes = 0
            self.miss_bytes = 0
            self.evictions = 0
            self._reads.clear()
            self.fetch_latency = WindowedSketch(window=300.0)


CACHE = RemoteBlockCache()
