"""Data integrity: crash-safe volume recovery + background scrub.

Three layers, mirroring the reference's volume_checking.go /
command_volume_check_disk.go direction but wired into this repo's
events/healthz/fault surfaces (PR 3):

1. `recover_volume_files` — crash-safe mount.  Run before a volume's
   needle map is opened: validates the superblock, truncates a torn
   trailing record left by a `kill -9` mid-write, appends idx entries
   for complete records the index never learned about (crash between
   the .dat fsync and the .idx append), and regenerates the .idx from
   the .dat when it is missing or references bytes past EOF.  A crash
   can lose unacknowledged in-flight writes, never acknowledged ones,
   and never leaves a volume unmountable or lying.

2. `ScrubDaemon` — rate-limited (`-scrub.mbps`) background sweep on
   the volume server: CRC-verifies every live needle of every normal
   volume and every block of every local EC shard file (against the
   `.ecc` sidecar, ec/integrity.py).  Detection emits
   `needle.corrupt`, bumps `SeaweedFS_scrub_corrupt_total`, and — for
   needles — quarantines (tombstone + repair ticket) so corrupt bytes
   are never served while the volume reports degraded on
   `/cluster/healthz`.

3. Self-healing — the daemon takes repair callbacks from the server:
   a corrupt/unreadable needle is re-fetched from a healthy replica,
   a corrupt shard block is reconstructed through the TPU EC decode
   path (coder.reconstruct over >=10 sibling shard intervals), both
   rewritten in place with `needle.repaired` +
   `SeaweedFS_needle_repairs_total{source=}` emitted.

Facebook's warehouse study (arxiv 1309.0186) puts repair traffic, not
encode, at the center of EC operating cost; routing block repair
through the same batched decode kernel the rebuild pipeline uses keeps
that path cheap (arxiv 1611.09968's efficient-repair direction).
"""

from __future__ import annotations

import os
import threading
import time

from ..core import idx as idx_mod
from ..core import types as t
from ..core.needle import Needle, get_actual_size
from ..events import emit as emit_event
from ..stats.metrics import (needle_repairs_total, scrub_bytes_total,
                             scrub_checked_total, scrub_corrupt_total,
                             scrub_sweeps_total)
from ..trace import root_span
from ..utils import glog
from .volume_scanner import read_super_block, scan_data_tail


# -- crash-safe mount --------------------------------------------------------

def _write_idx_entries(out, entries) -> None:
    for key, offset, size in entries:
        if size > 0:
            idx_mod.append_entry(out, key, offset, size)
        else:
            idx_mod.append_entry(out, key, 0, t.TOMBSTONE_FILE_SIZE)
    out.flush()
    os.fsync(out.fileno())


def recover_volume_files(dat_path: str, idx_path: str, vid: int = 0,
                         node: str = "") -> dict:
    """Crash-safe mount pass (see module docstring).  Returns a report
    dict; raises whatever read_super_block raises for an unmountable
    .dat (0-byte crashed create, garbage superblock) so the store can
    skip it like before.  Emits `volume.recovered` when it changed
    anything on disk."""
    from .needle_map import idx_crash_state
    report = {"dat_truncated": 0, "idx_appended": 0,
              "idx_regenerated": False}
    sb = read_super_block(dat_path)  # validates; raises if unmountable
    version = sb.version
    dat_size = os.path.getsize(dat_path)
    last, dead_keys = idx_crash_state(idx_path)
    idx_missing = not os.path.exists(idx_path) or \
        os.path.getsize(idx_path) == 0

    stale = last is not None and \
        last[0] + get_actual_size(last[1], version) > dat_size
    if stale:
        # The index vouches for bytes the .dat no longer has: it is
        # lying — rebuild it from what the data actually says.
        start = None
    elif last is not None:
        # Index tail is sound: only the region past its furthest entry
        # needs scanning — O(tail), not O(volume), per mount.
        start = last[0] + get_actual_size(last[1], version)
    else:
        start = None
    entries, data_end = scan_data_tail(dat_path, start_offset=start)
    if stale or (idx_missing and entries):
        with open(idx_path, "wb") as out:
            _write_idx_entries(out, entries)
        report["idx_regenerated"] = True
    else:
        # Complete records the index never learned about (crash
        # between the .dat write and the .idx append): journal them.
        # Tombstone MARKERS past the furthest write entry are normal
        # (their idx entries carry offset 0, so they sit beyond `start`
        # on every mount) — only journal ones the index doesn't
        # already record as deleted, or every restart after a delete
        # would append a duplicate and report a phantom recovery.
        fresh = [(key, off, size) for key, off, size in entries
                 if size > 0 or key not in dead_keys]
        if fresh:
            with open(idx_path, "ab") as out:
                _write_idx_entries(out, fresh)
            report["idx_appended"] = len(fresh)

    if data_end < dat_size:
        # Torn trailing record from a crash mid-write: truncate so the
        # append grid stays clean and the next write lands aligned.
        with open(dat_path, "r+b") as f:
            f.truncate(data_end)
        report["dat_truncated"] = dat_size - data_end

    if report["dat_truncated"] or report["idx_appended"] or \
            report["idx_regenerated"]:
        glog.warningf("volume %d recovered: %s", vid, report)
        emit_event("volume.recovered", node=node, severity="warn",
                   vid=vid, **report)
    return report


# -- rate limiting -----------------------------------------------------------

class RateLimiter:
    """Token-bucket byte throttle for the scrub's disk reads
    (`-scrub.mbps`): a background sweep must never starve foreground
    traffic of disk bandwidth.  mbps <= 0 disables."""

    def __init__(self, mbps: float = 32.0):
        self.rate = mbps * 1e6
        self._allow_at = time.monotonic()
        self._lock = threading.Lock()

    def take(self, nbytes: int) -> None:
        if self.rate <= 0 or nbytes <= 0:
            return
        with self._lock:
            now = time.monotonic()
            self._allow_at = max(self._allow_at, now) + nbytes / self.rate
            wait = self._allow_at - now
        if wait > 0:
            time.sleep(min(wait, 5.0))


# -- the scrub daemon --------------------------------------------------------

class ScrubDaemon:
    """Per-volume-server integrity sweep + self-healing dispatcher.

    `repair_needle(volume, key) -> truthy` and
    `repair_ec_block(ev, sid, offset, size, block_index, want_crc)
    -> bool` come from the cluster layer (they need master lookups /
    shard fan-out); without them the daemon detects and quarantines
    but cannot heal.
    """

    def __init__(self, store, ec_volumes: dict, node: str = "",
                 mbps: float = 32.0, interval: float = 3600.0,
                 repair_needle=None, repair_ec_block=None,
                 on_change=None):
        self.store = store
        self.ec_volumes = ec_volumes
        self.node = node
        self.limiter = RateLimiter(mbps)
        self.interval = interval
        self.repair_needle = repair_needle
        self.repair_ec_block = repair_ec_block
        self.on_change = on_change
        # vid -> {(shard_id, block_index), ...} of detected-but-
        # unrepaired EC corruption; feeds the heartbeat so the master's
        # healthz reports the volume degraded until healed.  Guarded by
        # _ec_corrupt_lock: the heartbeat and /admin/scrub/status
        # threads iterate it while a sweep mutates it.
        self.ec_corrupt: dict[int, set[tuple[int, int]]] = {}
        self._ec_corrupt_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._sweep_lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self.interval <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="scrub")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.scrub_all(repair=True)
            except Exception as e:  # noqa: BLE001 — sweep must survive
                glog.warningf("scrub sweep failed: %s", e)

    # -- sweeps --------------------------------------------------------------

    def ec_corrupt_counts(self) -> dict[int, int]:
        with self._ec_corrupt_lock:
            return {vid: len(blocks)
                    for vid, blocks in self.ec_corrupt.items()
                    if blocks}

    def ec_corrupt_snapshot(self) -> dict[int, list[tuple[int, int]]]:
        with self._ec_corrupt_lock:
            return {vid: sorted(blocks)
                    for vid, blocks in self.ec_corrupt.items()
                    if blocks}

    def _ec_mark(self, vid: int, sid: int, block: int,
                 corrupt: bool) -> None:
        with self._ec_corrupt_lock:
            blocks = self.ec_corrupt.setdefault(vid, set())
            if corrupt:
                blocks.add((sid, block))
            else:
                blocks.discard((sid, block))

    def scrub_all(self, repair: bool = False,
                  vid: int | None = None) -> dict:
        """One sweep over every (or one) volume and EC volume.  Safe to
        call concurrently with traffic; serialized against itself."""
        with self._sweep_lock, root_span("scrub.sweep", "scrub",
                                         repair=repair):
            reports = []
            for loc in self.store.locations:
                for v in list(loc.volumes.values()):
                    if vid is not None and v.vid != vid:
                        continue
                    if v.remote_file is not None:
                        continue  # tiered: the backend owns integrity
                    reports.append(self.scrub_volume(v, repair=repair))
            for evid, ev in sorted(self.ec_volumes.items()):
                if vid is not None and evid != vid:
                    continue
                reports.append(self.scrub_ec_volume(ev, repair=repair))
            scrub_sweeps_total.inc()
            out = {"volumes": reports,
                   "corrupt": sum(r["corrupt"] for r in reports),
                   "repaired": sum(r["repaired"] for r in reports),
                   "quarantined": sum(r.get("quarantined", 0)
                                      for r in reports)}
            if self.on_change is not None and \
                    (out["corrupt"] or out["repaired"]):
                try:
                    self.on_change()
                except Exception:  # noqa: BLE001 — advisory only
                    pass
            return out

    # -- normal volumes ------------------------------------------------------

    def _verify_needle(self, v, entry) -> str | None:
        """CRC-verify one live needle record in place.  Returns an
        error string, or None when the bytes are sound.  Re-validates
        the map entry after a failure so a concurrent overwrite or
        vacuum swap is never misread as bit-rot."""
        total = get_actual_size(entry.size, v.version)
        err = None
        try:
            blob = v.pread(total, entry.offset)
            if len(blob) < total:
                err = "short read (record truncated)"
            else:
                n = Needle.parse_header(blob)
                if n.id != entry.key or n.size != entry.size:
                    err = (f"header mismatch: disk has "
                           f"{n.id:x}/{n.size}, index says "
                           f"{entry.key:x}/{entry.size}")
                else:
                    Needle.from_bytes(blob, v.version, check_crc=True)
        except OSError as e:
            err = f"read error: {e}"
        except ValueError as e:
            err = str(e)
        if err is not None:
            cur = v.nm.get(entry.key)
            if cur is None or cur != (entry.offset, entry.size):
                return None  # raced a delete/overwrite/vacuum: skip
        return err

    def scrub_volume(self, v, repair: bool = False) -> dict:
        emit_event("scrub.start", node=self.node, vid=v.vid,
                   kind="volume")
        t0 = time.perf_counter()
        entries: list = []
        v.nm.ascending_visit(
            lambda e: entries.append(e) if t.size_is_valid(e.size)
            else None)
        checked = corrupt = repaired = quarantined = 0
        nbytes = 0
        for entry in entries:
            total = get_actual_size(entry.size, v.version)
            self.limiter.take(total)
            err = self._verify_needle(v, entry)
            checked += 1
            nbytes += total
            scrub_checked_total.inc(kind="needle")
            scrub_bytes_total.inc(total)
            if err is None:
                continue
            corrupt += 1
            scrub_corrupt_total.inc(kind="needle")
            emit_event("needle.corrupt", node=self.node,
                       severity="error", vid=v.vid,
                       key=f"{entry.key:x}", kind="needle", error=err)
            fixed = False
            if repair and self.repair_needle is not None:
                try:
                    fixed = bool(self.repair_needle(v, entry.key))
                except Exception:  # noqa: BLE001 — repair must not
                    fixed = False  # kill the sweep
            if fixed:
                repaired += 1
            elif "read error" not in err:
                # CRC-proven corruption: stop serving the bad bytes.
                # A pure read error may be transient — never tombstone
                # a needle whose bytes might be fine.
                if v.quarantine_needle(entry.key, node=self.node):
                    quarantined += 1
        if repair and self.repair_needle is not None:
            # Second chance for previously-quarantined needles: the
            # repair ticket survives the tombstone precisely so a
            # replica that was down last sweep can heal us now.
            for key in list(v.repair_tickets):
                try:
                    if self.repair_needle(v, key):
                        repaired += 1
                except Exception:  # noqa: BLE001
                    pass
        v.last_scrub = time.time()
        report = {"id": v.vid, "kind": "volume", "checked": checked,
                  "corrupt": corrupt, "repaired": repaired,
                  "quarantined": quarantined,
                  "tickets": len(v.repair_tickets), "bytes": nbytes}
        emit_event("scrub.finish", node=self.node, vid=v.vid,
                   kind="volume",
                   severity="warn" if corrupt > repaired else "info",
                   seconds=round(time.perf_counter() - t0, 6), **{
                       k: report[k] for k in
                       ("checked", "corrupt", "repaired", "bytes")})
        return report

    # -- EC volumes ----------------------------------------------------------

    def scrub_ec_volume(self, ev, repair: bool = False) -> dict:
        from ..ec.integrity import (ShardChecksums, ecc_lock,
                                    file_block_crcs)
        emit_event("scrub.start", node=self.node, vid=ev.vid, kind="ec")
        t0 = time.perf_counter()
        ecc = ShardChecksums.load(ev.base_file_name)
        checked = corrupt = repaired = 0
        nbytes = 0
        tofu: dict[int, list[int]] = {}
        for sid in sorted(ev.shards):
            shard = ev.shards[sid]
            crcs = ecc.get(sid)
            if crcs is None:
                # Trust-on-first-scrub: a shard that arrived without a
                # checksum record (copied/received) is fingerprinted
                # now; divergence is detectable from the next sweep on.
                tofu[sid] = file_block_crcs(
                    shard.path, block=ecc.block, limiter=self.limiter)
                continue
            bad = ecc.verify_file(sid, shard.path,
                                  limiter=self.limiter)
            checked += len(crcs)
            nbytes += os.path.getsize(shard.path)
            scrub_checked_total.inc(len(crcs), kind="shard_block")
            scrub_bytes_total.inc(os.path.getsize(shard.path))
            for b in bad:
                corrupt += 1
                scrub_corrupt_total.inc(kind="shard_block")
                emit_event("needle.corrupt", node=self.node,
                           severity="error", vid=ev.vid,
                           kind="shard_block", shard=sid, block=b)
                fixed = False
                if repair and self.repair_ec_block is not None and \
                        b < len(crcs):
                    off = b * ecc.block
                    size = min(ecc.block, shard.size - off)
                    try:
                        # The callback rewrites the block ONLY when
                        # the reconstruction reproduces the recorded
                        # checksum — anything else (a second corrupt
                        # source shard) is a failed repair that must
                        # not touch the original bytes.
                        fixed = bool(self.repair_ec_block(
                            ev, sid, off, size, b, crcs[b]))
                    except Exception:  # noqa: BLE001
                        fixed = False
                if fixed:
                    repaired += 1
                self._ec_mark(ev.vid, sid, b, corrupt=not fixed)
        if tofu:
            # Re-load under the sidecar lock: a shard received mid-
            # sweep must not have its fresh record clobbered by this
            # sweep's stale view.
            with ecc_lock(ev.base_file_name):
                cur = ShardChecksums.load(ev.base_file_name)
                changed = False
                for sid, crcs in tofu.items():
                    if cur.get(sid) is None:
                        cur.set_shard(sid, crcs)
                        changed = True
                if changed:
                    cur.save()
        unrepaired = len(self.ec_corrupt_snapshot().get(ev.vid, []))
        report = {"id": ev.vid, "kind": "ec", "checked": checked,
                  "corrupt": corrupt, "repaired": repaired,
                  "unrepaired": unrepaired, "bytes": nbytes}
        emit_event("scrub.finish", node=self.node, vid=ev.vid,
                   kind="ec",
                   severity="warn" if unrepaired else "info",
                   seconds=round(time.perf_counter() - t0, 6), **{
                       k: report[k] for k in
                       ("checked", "corrupt", "repaired", "bytes")})
        return report
