"""Sequential `.dat` scanner — powers vacuum, `fix` (idx regeneration),
export, and integrity checking (reference: storage/volume_backup.go:247-262
VolumeFileScanner4GenIdx, volume_checking.go)."""

from __future__ import annotations

import os
from typing import Iterator

from ..core import types as t
from ..core.needle import Needle, needle_body_length
from ..core.super_block import SUPER_BLOCK_SIZE, SuperBlock


def scan_volume_file(dat_path: str, check_crc: bool = False,
                     start_offset: int | None = None,
                     ) -> Iterator[tuple[Needle, int, int]]:
    """Yield (needle, offset, total_record_size) for every record in a .dat.

    Tombstone markers (size == 0 records) are yielded too — callers decide.
    Stops cleanly at EOF or a truncated trailing record.
    """
    with open(dat_path, "rb") as f:
        sb = SuperBlock.from_bytes(f.read(SUPER_BLOCK_SIZE + 64 * 1024))
        version = sb.version
        offset = start_offset if start_offset is not None else sb.block_size()
        size = os.fstat(f.fileno()).st_size
        while offset + t.NEEDLE_HEADER_SIZE <= size:
            header = os.pread(f.fileno(), t.NEEDLE_HEADER_SIZE, offset)
            if len(header) < t.NEEDLE_HEADER_SIZE:
                return
            n = Needle.parse_header(header)
            if n.size < 0:
                return  # corrupt size: stop like the reference scanner
            body_len = needle_body_length(n.size, version)
            total = t.NEEDLE_HEADER_SIZE + body_len
            if offset + total > size:
                return  # truncated tail
            blob = header + os.pread(f.fileno(), body_len, offset +
                                     t.NEEDLE_HEADER_SIZE)
            needle = Needle.from_bytes(blob, version, check_crc=check_crc)
            yield needle, offset, total
            offset += total


def scan_data_tail(dat_path: str, start_offset: int | None = None,
                   check_crc: bool = False,
                   ) -> tuple[list[tuple[int, int, int]], int]:
    """Tolerant tail scan for crash recovery (storage/scrub.py):
    returns ([(needle_id, offset, size), ...], data_end) for every
    COMPLETE, parseable record from `start_offset` on, stopping — but
    not raising — at the first truncated or malformed record.
    `data_end` is the byte offset just past the last good record: a
    .dat longer than that carries a torn tail to truncate."""
    sb = read_super_block(dat_path)
    start = start_offset if start_offset is not None else sb.block_size()
    entries: list[tuple[int, int, int]] = []
    data_end = start
    gen = scan_volume_file(dat_path, check_crc=check_crc,
                           start_offset=start)
    while True:
        try:
            needle, offset, total = next(gen)
        except StopIteration:
            break
        except (ValueError, OSError):
            break  # malformed record: everything past it is garbage
        entries.append((needle.id, offset, needle.size))
        data_end = offset + total
    return entries, data_end


def read_super_block(dat_path: str) -> SuperBlock:
    with open(dat_path, "rb") as f:
        return SuperBlock.from_bytes(f.read(SUPER_BLOCK_SIZE + 64 * 1024))


def generate_idx_from_dat(dat_path: str, idx_path: str) -> int:
    """`weed fix`: rebuild the .idx by scanning the .dat. Returns #entries."""
    from ..core import idx as idx_mod
    count = 0
    with open(idx_path, "wb") as out:
        for needle, offset, _total in scan_volume_file(dat_path):
            if needle.size > 0:
                idx_mod.append_entry(out, needle.id, offset, needle.size)
            else:
                idx_mod.append_entry(out, needle.id, 0,
                                     t.TOMBSTONE_FILE_SIZE)
            count += 1
    return count
