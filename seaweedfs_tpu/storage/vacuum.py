"""Vacuum (compaction): reclaim deleted space by copying live needles.

Reference flow (weed/storage/volume_vacuum.go): Compact writes live records
into `.cpd`/`.cpx` staging files; CommitCompact replays any records appended
after the snapshot (makeupDiff), then atomically renames staging over the
live files and reloads.  The superblock compaction revision increments so
replicas can detect divergence.
"""

from __future__ import annotations

import os

from ..core import idx as idx_mod
from ..core import types as t
from ..core.needle import Needle
from ..core.super_block import SuperBlock
from .volume import Volume
from .volume_scanner import scan_volume_file


def compact(volume: Volume) -> int:
    """Phase 1: copy live needles to .cpd/.cpx. Returns snapshot dat size.

    The volume stays writable; records appended after the returned offset
    are replayed by commit_compact.
    """
    base = volume.file_name()
    volume.sync()
    snapshot_size = volume.dat_size()

    sb = SuperBlock(
        version=volume.super_block.version,
        replica_placement=volume.super_block.replica_placement,
        ttl=volume.super_block.ttl,
        compaction_revision=volume.super_block.compaction_revision + 1,
        extra=volume.super_block.extra)

    with open(base + ".cpd", "wb") as cpd, open(base + ".cpx", "wb") as cpx:
        cpd.write(sb.to_bytes())
        new_offset = cpd.tell()
        for needle, offset, total in scan_volume_file(base + ".dat"):
            if offset >= snapshot_size:
                break
            if needle.size <= 0:
                continue
            live = volume.nm.get(needle.id)
            if live is None or live[0] != offset:
                continue  # deleted or superseded
            blob = needle.to_bytes(volume.version)
            cpd.write(blob)
            idx_mod.append_entry(cpx, needle.id, new_offset, needle.size)
            new_offset += len(blob)
    return snapshot_size


def commit_compact(volume: Volume, snapshot_size: int) -> None:
    """Phase 2: replay post-snapshot appends, swap files, reload the map.

    Holds the volume's file lock in write mode for the whole swap so
    lock-free readers can never pread a closed fd or stale offsets.
    """
    base = volume.file_name()
    with volume._file_lock.write(), volume._lock:
        volume.sync()
        # makeupDiff: replay records appended after the snapshot.
        with open(base + ".cpd", "r+b") as cpd, \
                open(base + ".cpx", "ab") as cpx:
            cpd.seek(0, os.SEEK_END)
            new_offset = cpd.tell()
            for needle, _off, _total in scan_volume_file(
                    base + ".dat", start_offset=snapshot_size):
                if needle.size > 0:
                    blob = needle.to_bytes(volume.version)
                    cpd.write(blob)
                    idx_mod.append_entry(cpx, needle.id, new_offset,
                                         needle.size)
                    new_offset += len(blob)
                else:  # tombstone marker: propagate the delete
                    idx_mod.append_entry(cpx, needle.id, 0,
                                         t.TOMBSTONE_FILE_SIZE)
        # Swap.
        volume._dat.close()
        volume.nm.close()
        os.replace(base + ".cpd", base + ".dat")
        os.replace(base + ".cpx", base + ".idx")
        # Reload in place (same map kind the volume was opened with).
        from .needle_map import new_needle_map
        volume._dat = open(base + ".dat", "r+b")
        volume.super_block = SuperBlock.from_bytes(volume._dat.read(64 * 1024))
        volume.nm = new_needle_map(
            getattr(volume, "needle_map_kind", "compact"), base + ".idx")
        volume._dat.seek(0, os.SEEK_END)
        volume._append_at = volume._dat.tell()


def vacuum(volume: Volume) -> None:
    """Compact + commit in one step (single-process convenience)."""
    snapshot = compact(volume)
    commit_compact(volume, snapshot)
