"""Vacuum (compaction): reclaim deleted space by copying live needles.

Reference flow (weed/storage/volume_vacuum.go): Compact writes live records
into `.cpd`/`.cpx` staging files; CommitCompact replays any records appended
after the snapshot (makeupDiff), then atomically renames staging over the
live files and reloads.  The superblock compaction revision increments so
replicas can detect divergence.

Staging state (`volume.vacuum_staged`) and its guard
(`volume.vacuum_lock`) live on the Volume itself, mirroring the
reference's Volume-struct fields: two Compacts from different
*in-process* planes (gRPC facade and JSON admin in the same server)
serialize instead of interleaving writes to the same `.cpd`/`.cpx`,
and a Commit consumes whichever plane's snapshot is staged.  Like the
reference, nothing guards against a separate process (`weed compact`)
operating on a volume a live server has mounted.
"""

from __future__ import annotations

import os

from ..core import idx as idx_mod
from ..core import types as t
from ..core.needle import Needle
from ..core.super_block import SuperBlock
from . import expiry as _expiry
from .volume import Volume
from .volume_scanner import scan_volume_file


class VacuumError(Exception):
    pass


def compact(volume: Volume) -> int:
    """Phase 1: copy live needles to .cpd/.cpx. Returns snapshot dat size.

    The volume stays writable; records appended after the returned offset
    are replayed by commit_compact.  Re-running compact replaces any
    previously staged (uncommitted) snapshot, like the reference.
    """
    base = volume.file_name()
    with volume.vacuum_lock:
        # Invalidate any previously staged snapshot *before* truncating
        # the staging files: if this compact fails midway, a commit of
        # the stale snapshot would swap a partial .cpd over the live
        # .dat.
        volume.vacuum_staged = None
        volume.sync()
        snapshot_size = volume.dat_size()

        sb = SuperBlock(
            version=volume.super_block.version,
            replica_placement=volume.super_block.replica_placement,
            ttl=volume.super_block.ttl,
            compaction_revision=volume.super_block.compaction_revision + 1,
            extra=volume.super_block.extra)

        expired_count = 0
        expired_bytes = 0
        with open(base + ".cpd", "wb") as cpd, \
                open(base + ".cpx", "wb") as cpx:
            cpd.write(sb.to_bytes())
            new_offset = cpd.tell()
            for needle, offset, total in scan_volume_file(base + ".dat"):
                if offset >= snapshot_size:
                    break
                if needle.size <= 0:
                    continue
                live = volume.nm.get(needle.id)
                if live is None or live[0] != offset:
                    continue  # deleted or superseded
                # TTL-expired == dead: the read path already 404s these
                # (volume.read_needle), so dropping the record is the
                # reclaim step, not a behavior change.  The map entry
                # vanishes with the .cpx swap.
                if _expiry.needle_expired(needle, volume.super_block.ttl):
                    expired_count += 1
                    expired_bytes += total
                    continue
                blob = needle.to_bytes(volume.version)
                cpd.write(blob)
                idx_mod.append_entry(cpx, needle.id, new_offset, needle.size)
                new_offset += len(blob)
        volume.vacuum_staged = snapshot_size
        volume.vacuum_expired_count = expired_count
        volume.vacuum_expired_bytes = expired_bytes
    return snapshot_size


def commit_compact(volume: Volume, snapshot_size: int | None = None) -> None:
    """Phase 2: replay post-snapshot appends, swap files, reload the map.

    With no explicit `snapshot_size`, commits the snapshot staged on the
    volume by the last compact(); raises VacuumError if none is staged.
    Holds the volume's vacuum lock for the whole replay+swap so a
    concurrent compact cannot truncate the `.cpd` mid-commit, and the
    file lock in write mode so lock-free readers can never pread a
    closed fd or stale offsets.
    """
    base = volume.file_name()
    with volume.vacuum_lock:
        if snapshot_size is None:
            snapshot_size = volume.vacuum_staged
        if snapshot_size is None:
            raise VacuumError("no compact staged for this volume")
        with volume._file_lock.write(), volume._lock:
            volume.sync()
            # makeupDiff: replay records appended after the snapshot.
            with open(base + ".cpd", "r+b") as cpd, \
                    open(base + ".cpx", "ab") as cpx:
                cpd.seek(0, os.SEEK_END)
                new_offset = cpd.tell()
                for needle, _off, _total in scan_volume_file(
                        base + ".dat", start_offset=snapshot_size):
                    if needle.size > 0:
                        blob = needle.to_bytes(volume.version)
                        cpd.write(blob)
                        idx_mod.append_entry(cpx, needle.id, new_offset,
                                             needle.size)
                        new_offset += len(blob)
                    else:  # tombstone marker: propagate the delete
                        idx_mod.append_entry(cpx, needle.id, 0,
                                             t.TOMBSTONE_FILE_SIZE)
            # Swap.
            volume._dat.close()
            volume.nm.close()
            os.replace(base + ".cpd", base + ".dat")
            os.replace(base + ".cpx", base + ".idx")
            # Reload in place (same map kind the volume was opened with).
            from .needle_map import new_needle_map
            volume._dat = open(base + ".dat", "r+b")
            volume.super_block = SuperBlock.from_bytes(
                volume._dat.read(64 * 1024))
            volume.nm = new_needle_map(
                getattr(volume, "needle_map_kind", "compact"),
                base + ".idx")
            volume._dat.seek(0, os.SEEK_END)
            volume._append_at = volume._dat.tell()
            # The replication change log is compacted with the volume:
            # the acked prefix can never need re-shipping, and the
            # appended vacuum record keeps the seq chain alive (and
            # documents the rewrite to the standby).
            if volume.rlog is not None:
                volume.rlog.compact()
        volume.vacuum_staged = None


def cleanup_compact(volume: Volume) -> None:
    """Abandon a staged compact: drop the snapshot and remove the
    `.cpd`/`.cpx` staging files (VacuumVolumeCleanup)."""
    base = volume.file_name()
    with volume.vacuum_lock:
        volume.vacuum_staged = None
        for ext in (".cpd", ".cpx"):
            try:
                os.remove(base + ext)
            except FileNotFoundError:
                pass


def vacuum(volume: Volume) -> None:
    """Compact + commit in one step (single-process convenience).

    Holds the (reentrant) vacuum lock across both phases so concurrent
    vacuum() calls fully serialize instead of one consuming the
    other's staged snapshot between its phases.  Journaled as a
    volume.vacuum event with the reclaimed bytes and garbage ratios.
    """
    import time as _time

    from ..events import emit as emit_event
    with volume.vacuum_lock:
        before_bytes = volume.dat_size()
        before_ratio = volume.garbage_ratio()
        t0 = _time.perf_counter()
        compact(volume)
        commit_compact(volume)
        expired_count = getattr(volume, "vacuum_expired_count", 0)
        expired_bytes = getattr(volume, "vacuum_expired_bytes", 0)
        if expired_bytes:
            from ..stats import metrics as _metrics
            _metrics.ttl_expired_bytes_total.inc(expired_bytes,
                                                 via="vacuum")
        emit_event("volume.vacuum", vid=volume.vid,
                   seconds=round(_time.perf_counter() - t0, 6),
                   reclaimed_bytes=before_bytes - volume.dat_size(),
                   expired_needles=expired_count,
                   expired_bytes=expired_bytes,
                   garbage_before=round(before_ratio, 4),
                   garbage_after=round(volume.garbage_ratio(), 4))
