"""TTL expiry decisions, with an injectable clock.

Every "is this expired?" question in the system routes through here so
they all agree: the needle read path (404 with an expiry reason),
vacuum's live filter (expired == dead, reclaim the bytes), the volume
server's sweeper (a fully-expired TTL volume is deleted whole, like
weed/topology's volume-ttl vacuum), and the master's layout steering
(stop assigning writes to a near-expiry volume so it can drain and
die).

The TTL wire codec's minimum unit is one minute (core/ttl.py), so
tests can't wait out a real expiry; `set_clock` lets them advance time
instead.  Production never calls it.
"""

from __future__ import annotations

import time

from ..core.ttl import TTL

_clock = time.time


def now() -> float:
    return _clock()


def set_clock(fn) -> None:
    """Test hook: replace the expiry wall clock (pass `time.time` or
    call `reset_clock` to restore)."""
    global _clock
    _clock = fn


def reset_clock() -> None:
    global _clock
    _clock = time.time


def needle_ttl_sec(needle, volume_ttl: TTL | None) -> int:
    """Effective TTL for one needle in seconds (0 = never expires).
    A per-needle TTL wins; otherwise the volume superblock's applies —
    the reference stamps the assign-time ?ttl on both."""
    if needle.has_ttl() and needle.ttl.minutes() > 0:
        return needle.ttl.minutes() * 60
    if volume_ttl is not None and volume_ttl.minutes() > 0:
        return volume_ttl.minutes() * 60
    return 0


def needle_expired(needle, volume_ttl: TTL | None = None,
                   at: float | None = None) -> bool:
    ttl_sec = needle_ttl_sec(needle, volume_ttl)
    if ttl_sec <= 0 or not needle.has_last_modified_date():
        return False
    if at is None:
        at = now()
    return at > needle.last_modified + ttl_sec


def volume_expired(ttl: TTL | None, modified_at: float,
                   grace: float = 0.0, at: float | None = None) -> bool:
    """A TTL volume whose NEWEST write is past expiry (plus grace) holds
    only dead needles and can be retired whole."""
    if ttl is None or ttl.minutes() <= 0 or modified_at <= 0:
        return False
    if at is None:
        at = now()
    return at > modified_at + ttl.minutes() * 60 + grace


def volume_near_expiry(ttl: TTL | None, modified_at: float,
                       fraction: float = 0.5,
                       at: float | None = None) -> bool:
    """Past `fraction` of the TTL since the newest write: the master
    stops steering new writes here so the volume drains toward whole-
    volume retirement instead of being kept alive forever."""
    if ttl is None or ttl.minutes() <= 0 or modified_at <= 0:
        return False
    if at is None:
        at = now()
    return at > modified_at + ttl.minutes() * 60 * fraction
