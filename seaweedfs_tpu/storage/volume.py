"""Volume engine: one append-only `.dat` + `.idx` pair.

Mirrors the reference's Volume behavior (weed/storage/volume.go,
volume_read_write.go) with its key design points kept:

- append-only writes, 8-byte aligned records, offsets stored /8;
- an async batched write worker: requests queue up and are written +
  fsynced as one group (reference batches <=128 requests / 4MB then one
  sync — volume_read_write.go:297-370);
- O(1) reads: one map lookup then one pread;
- deletes append a tombstone needle and a tombstone idx entry;
- vacuum (volume_vacuum.py) copies live needles to `.cpd/.cpx` then
  atomically swaps, bumping the superblock compaction revision.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass

from ..core import types as t
from ..core.needle import (CURRENT_VERSION, Needle, get_actual_size)
from ..core.replica_placement import ReplicaPlacement
from ..core.super_block import SUPER_BLOCK_SIZE, SuperBlock
from ..core.ttl import TTL
from ..fault import registry as _fault
from ..stats import contention as _contention
from ..stats import phases as _phases
from ..utils.rwlock import RWLock
from . import expiry as _expiry
from .needle_map import new_needle_map

MAX_BATCH_REQUESTS = 128
MAX_BATCH_BYTES = 4 * 1024 * 1024


class VolumeError(Exception):
    pass


class NotFoundError(VolumeError):
    pass


class CorruptNeedleError(VolumeError):
    """The record's stored CRC disagrees with its data bytes: bit-rot
    or a torn write.  Distinct from VolumeError so the read path can
    route it to self-healing repair instead of a plain 4xx."""


class DiskFullError(VolumeError):
    """An append hit ENOSPC.  The partially-written record was rolled
    back (truncated — no torn tail for crash recovery to find) and the
    volume flipped readonly; the volume server re-checks its disk
    reserve and heartbeats the state so the master steers assignment
    away."""


class TierReadError(VolumeError):
    """A remote-tier ranged read failed (WAN partition, backend down,
    timeout).  Distinct from CorruptNeedleError/OSError so the read
    path answers a bounded, retryable 503 instead of routing into
    degraded-read repair — the local bytes are gone by design, not
    rotten."""


@dataclass
class _WriteReq:
    needle: Needle
    done: threading.Event
    offset: int = 0
    size: int = 0
    error: Exception | None = None
    # journal=False marks mutations that must NOT land in the
    # replication change log: the standby's apply path (or the mirror
    # would ship its own inputs back) and quarantine tombstones (which
    # must never propagate as user deletes — PR 4's repair rule).
    journal: bool = True


def _parse_needle_extras(tail: bytes) -> dict:
    """Parse the post-data record tail (flags + optional extras, no
    checksum) for the response-header metadata the zero-copy GET path
    serves: name, mime, last-modified.  Mirrors the field order of
    Needle._read_body_v2."""
    from ..core.needle import (FLAG_HAS_LAST_MODIFIED_DATE,
                               FLAG_HAS_MIME, FLAG_HAS_NAME,
                               LAST_MODIFIED_BYTES_LENGTH)
    flags = tail[0]
    i = 1
    name = mime = b""
    last_modified = 0
    if flags & FLAG_HAS_NAME and i < len(tail):
        n = tail[i]
        name = tail[i + 1:i + 1 + n]
        i += 1 + n
    if flags & FLAG_HAS_MIME and i < len(tail):
        n = tail[i]
        mime = tail[i + 1:i + 1 + n]
        i += 1 + n
    if flags & FLAG_HAS_LAST_MODIFIED_DATE and \
            i + LAST_MODIFIED_BYTES_LENGTH <= len(tail):
        last_modified = int.from_bytes(
            tail[i:i + LAST_MODIFIED_BYTES_LENGTH], "big")
    return {"name": name, "mime": mime,
            "last_modified": last_modified}


class NeedleSlice:
    """A byte range of a volume's .dat holding one needle's payload,
    produced by Volume.read_needle_slice after cookie+CRC checks.

    File-like enough for the HTTP responder: read(n) serves chunks via
    os.pread (the TLS / fallback path) and sendfile_to(sock) moves the
    whole remainder kernel-side with os.sendfile.  OWNS a dup'd fd of
    the .dat rather than holding the volume's file lock: a slow client
    must never block deletes/fsync-writes/vacuum on the volume, and if
    vacuum swaps the file mid-transfer the dup keeps the old inode
    alive — the client finishes reading a consistent pre-compact
    snapshot."""

    __slots__ = ("fd", "offset", "size", "_pos", "_closed", "etag",
                 "name", "mime", "last_modified")

    def __init__(self, fd: int, offset: int, size: int,
                 etag: str = "", name: bytes = b"", mime: bytes = b"",
                 last_modified: int = 0):
        self.fd = fd  # dup'd; closed by close()
        self.offset = offset
        self.size = size
        self._pos = 0
        self._closed = False
        # Response-header metadata (checksum etag + record extras).
        self.etag = etag
        self.name = name
        self.mime = mime
        self.last_modified = last_modified

    def read(self, n: int = -1) -> bytes:
        remaining = self.size - self._pos
        if remaining <= 0:
            return b""
        want = remaining if n < 0 else min(n, remaining)
        data = os.pread(self.fd, want, self.offset + self._pos)
        if not data:
            raise VolumeError("needle slice truncated mid-read")
        self._pos += len(data)
        return data

    def sendfile_to(self, sock, note=None) -> None:
        """Zero-copy the remaining payload into a plaintext socket.
        `note(n)` receives each syscall-returned byte total — these
        bytes never transit userspace, so the wire-flow ledger
        (stats/flows.py) counts them here or not at all."""
        sock_fd = sock.fileno()
        end = self.offset + self.size
        off = self.offset + self._pos
        while off < end:
            sent = os.sendfile(sock_fd, self.fd, off,
                               min(end - off, 8 << 20))
            if sent == 0:
                raise ConnectionError("peer closed during sendfile")
            off += sent
            if note is not None:
                note(sent)
        self._pos = self.size

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                os.close(self.fd)
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):  # backstop; close() is the contract
        self.close()


class Volume:
    """A single volume. Thread-safe; writes go through the batch worker."""

    def __init__(self, dir_: str, collection: str, vid: int,
                 replica_placement: ReplicaPlacement | None = None,
                 ttl: TTL | None = None, create: bool = True,
                 version: int = CURRENT_VERSION, use_worker: bool = True,
                 remote_file=None, needle_map_kind: str = "compact"):
        self.dir = dir_
        self.collection = collection
        self.vid = vid
        self.readonly = False
        # Metered (stats/contention.py): the append lock is THE
        # serialization point of the write path — its wait histogram
        # is where a write convoy becomes visible, and waits land in
        # the blocked request's `lock` phase.
        self._lock = _contention.MeteredLock("volume.write",
                                             threading.RLock())
        # Readers-writer discipline like the reference's dataFileAccessLock:
        # concurrent preads; exclusive for write batches and the vacuum
        # file swap.  Write-side waits/holds are metered as
        # "volume.file" (read side stays free).
        self._file_lock = RWLock(name="volume.file")
        # Vacuum staging state lives on the Volume (volume_vacuum.go
        # keeps it on the Volume struct) so the in-process planes —
        # gRPC facade and JSON admin — serialize on the same guard and
        # a Commit can find the snapshot whichever plane staged it.
        self.vacuum_lock = threading.RLock()
        self.vacuum_staged: int | None = None
        # Self-healing state: quarantined-needle repair tickets
        # (key -> quarantine unix time) and the last scrub sweep time
        # (storage/scrub.py).  len(repair_tickets) is the volume's
        # corrupt_count in heartbeats and /cluster/healthz.  Tickets
        # persist in a `.qrt` sidecar: a restart must neither forget
        # that quarantined data awaits repair (healthz would lie
        # healthy) nor let its tombstone masquerade as a user delete.
        self.repair_tickets: dict[int, float] = self._load_tickets()
        self.last_scrub = 0.0
        # Replication change log (replication/rlog.py): None until
        # mirroring is configured for this volume.  Auto-reopened below
        # when the sidecar already exists, so a restarted primary keeps
        # journaling without waiting for the shipper to re-enable it.
        self.rlog = None
        base = self.file_name()
        # Tiered volume: the .dat lives on a remote BackendStorage
        # (storage/volume_tier.go); reads proxy through remote_file,
        # writes are forbidden, the .idx stays local.
        self.remote_file = remote_file
        if remote_file is not None:
            self._dat = None
            self.readonly = True
            use_worker = False
            self.super_block = SuperBlock.from_bytes(
                remote_file.pread(SUPER_BLOCK_SIZE + 64 * 1024, 0))
            self.needle_map_kind = needle_map_kind
            self.nm = new_needle_map(needle_map_kind, base + ".idx")
            self._append_at = remote_file.size()
            self.last_modified = time.time()
            # Newest-write wall time; open_remote_volume restores the
            # real value from the .vif (a tiered volume is readonly, so
            # it can't advance).
            self.modified_at = 0.0
            self._closed = False
            self._use_worker = False
            self._queue = queue.Queue(maxsize=1)
            self._worker = None
            return
        exists = os.path.exists(base + ".dat")
        if not exists and not create:
            raise VolumeError(f"volume file {base}.dat not found")
        if exists:
            # Crash-safe mount (storage/scrub.py): validate the
            # superblock, truncate a torn trailing record, and repair/
            # regenerate the .idx BEFORE anything trusts either file —
            # a kill -9 mid-write must never leave this volume
            # unmountable or lying about what it holds.
            from .scrub import recover_volume_files
            recover_volume_files(base + ".dat", base + ".idx", vid=vid)
            self._dat = open(base + ".dat", "r+b")
            self.super_block = SuperBlock.from_bytes(
                self._dat.read(SUPER_BLOCK_SIZE + 64 * 1024))
        else:
            self._dat = open(base + ".dat", "w+b")
            self.super_block = SuperBlock(
                version=version,
                replica_placement=replica_placement or ReplicaPlacement(),
                ttl=ttl or TTL())
            self._dat.write(self.super_block.to_bytes())
            self._dat.flush()
        self.needle_map_kind = needle_map_kind
        # No dat_path here: recover_volume_files above already ran the
        # strictly-stronger crash pass (verify_idx_against_dat is the
        # gate for mappers loaded OUTSIDE a Volume) — passing it would
        # just re-read the whole .idx a second time per mount.
        self.nm = new_needle_map(needle_map_kind, base + ".idx")
        if needle_map_kind == "sorted_file":
            self.readonly = True  # the .sdx map cannot journal updates
        self._dat.seek(0, os.SEEK_END)
        self._append_at = self._dat.tell()
        self.last_modified = time.time()
        # Newest-write wall time, the TTL-expiry anchor: seeded from
        # the .dat mtime across restarts (close enough — the mtime IS
        # the last append), advanced by every committed write.
        self.modified_at = os.path.getmtime(base + ".dat") if exists \
            else 0.0
        if os.path.exists(base + ".rlog"):
            self.enable_rlog()

        self._closed = False
        self._use_worker = use_worker
        self._queue: queue.Queue[_WriteReq | None] = queue.Queue(maxsize=1024)
        self._worker = None
        if use_worker:
            self._worker = threading.Thread(
                target=self._worker_loop, name=f"vol{vid}-writer", daemon=True)
            self._worker.start()

    # -- naming ------------------------------------------------------------

    def file_name(self) -> str:
        name = f"{self.collection}_{self.vid}" if self.collection else \
            str(self.vid)
        return os.path.join(self.dir, name)

    @property
    def version(self) -> int:
        return self.super_block.version

    def enable_rlog(self):
        """Switch on the durable replication change log for this
        volume (idempotent).  From here every committed write/delete
        journals a fixed-size record into the `.rlog` sidecar at the
        same commit point as the needle itself, so the shipper can
        resume exactly after a kill -9.  Standby volumes never call
        this — their mutations arrive FROM a mirror and shipping them
        back would loop."""
        with self._lock:
            if self.rlog is None:
                # Lazy import: storage must not pull the replication
                # package (and its filer-client deps) at module import.
                from ..replication.rlog import ReplicationLog
                self.rlog = ReplicationLog(self.file_name())
        return self.rlog

    # -- write path --------------------------------------------------------

    def _worker_loop(self) -> None:
        """Batch pending requests, write them, fsync once per batch."""
        while True:
            req = self._queue.get()
            if req is None:
                return
            batch = [req]
            bytes_est = len(req.needle.data)
            while (len(batch) < MAX_BATCH_REQUESTS and
                   bytes_est < MAX_BATCH_BYTES):
                try:
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._drain_batch(batch)
                    return
                batch.append(nxt)
                bytes_est += len(nxt.needle.data)
            self._drain_batch(batch)

    def _drain_batch(self, batch: list[_WriteReq]) -> None:
        """Write all records, fsync once, then publish map entries.

        Publication order matters: needle-map entries become visible only
        after the data is durable on the .dat fd, so a concurrent
        read_needle (lock-free os.pread) can never observe a mapped offset
        whose bytes haven't reached the OS yet.
        """
        try:
            with self._file_lock.write(), self._lock:
                written: list[_WriteReq] = []
                for req in batch:
                    try:
                        off, size = self._write_record_locked(req.needle)
                        req.offset, req.size = off, size
                        written.append(req)
                    except Exception as e:  # noqa: BLE001 — to the waiter
                        req.error = e
                try:
                    self._dat.flush()
                    os.fsync(self._dat.fileno())
                    for req in written:
                        self.nm.put(req.needle.id, req.offset,
                                    req.needle.size)
                    # Durable writes are durable in BOTH files: an idx
                    # entry lost to a crash would orphan the fsynced
                    # data (recovery re-journals it, but an fsync ack
                    # should never depend on recovery).
                    self.nm.sync()
                    if self.rlog is not None:
                        # Change-log records land AFTER the data is
                        # durable and BEFORE the waiters are released:
                        # a crash here loses only un-acked writes, and
                        # every acked write has its log record.
                        for req in written:
                            if req.journal:
                                self.rlog.append(self.rlog.OP_WRITE,
                                                 req.needle.id,
                                                 req.needle.cookie,
                                                 req.needle.size)
                        self.rlog.sync()
                except Exception as e:  # noqa: BLE001
                    for req in batch:
                        req.error = req.error or e
                self.last_modified = time.time()
        except Exception as e:  # noqa: BLE001 — never strand the waiters
            for req in batch:
                req.error = req.error or e
        finally:
            for req in batch:
                req.done.set()

    def _write_record_locked(self, n: Needle) -> tuple[int, int]:
        """Append the record bytes (no map publication, no sync)."""
        if self.readonly:
            raise VolumeError(f"volume {self.vid} is read only")
        self.modified_at = _expiry.now()
        offset = self._append_at
        if offset % t.NEEDLE_PADDING_SIZE != 0:
            # Self-heal like the reference: realign to the padding grid.
            offset += t.NEEDLE_PADDING_SIZE - (offset % t.NEEDLE_PADDING_SIZE)
            self._dat.truncate(offset)
        if offset >= t.MAX_POSSIBLE_VOLUME_SIZE:
            raise VolumeError(f"volume {self.vid} exceeds max size")
        if n.append_at_ns == 0:
            n.append_at_ns = time.time_ns()
        blob = n.to_bytes(self.version)
        if _fault.ARMED and n.data:
            # volume.corrupt: deterministic bit-rot injection — the
            # write SUCCEEDS but a data bit flips on its way to disk
            # (the stored checksum was already computed from the true
            # bytes, so the damage is CRC-detectable like real rot).
            try:
                _fault.hit("volume.corrupt", vid=self.vid,
                           key=f"{n.id:x}")
            except _fault.FaultInjected:
                buf = bytearray(blob)
                buf[t.NEEDLE_HEADER_SIZE + 4] ^= 0xFF  # first data byte
                blob = bytes(buf)
        self._dat.seek(offset)
        try:
            if _fault.ARMED and "disk.full" in _fault.ARMED:
                # Injected ENOSPC mid-record: half the blob lands (a
                # real torn write) before the fault fires, so the
                # rollback below has something real to clean up.
                half = max(1, len(blob) // 2)
                self._dat.write(blob[:half])
                self._dat.flush()
                _fault.hit("disk.full", vid=self.vid, key=f"{n.id:x}")
                self._dat.write(blob[half:])
            else:
                self._dat.write(blob)
        except OSError as e:
            # Roll the partial record back NOW (truncate to the
            # pre-append offset): the .dat keeps no torn tail, so the
            # volume stays mountable as-is instead of leaning on crash
            # recovery at the next mount.  If the truncate itself fails
            # the torn-tail machinery (scrub.recover_volume_files)
            # still catches it on remount.
            try:
                self._dat.truncate(offset)
                self._dat.flush()
            except OSError:
                pass
            self._append_at = offset
            import errno as _errno
            if isinstance(e, _fault.FaultInjected) or \
                    e.errno == _errno.ENOSPC:
                # Out of space: stop admitting writes to this volume
                # before the next append can tear again.
                self.readonly = True
                from ..events import emit as emit_event
                emit_event("disk.full", severity="error", vid=self.vid,
                           rolled_back_bytes=len(blob),
                           key=f"{n.id:x}")
                raise DiskFullError(
                    f"volume {self.vid}: disk full (ENOSPC); partial "
                    f"record rolled back, volume now readonly") from e
            raise
        self._append_at = offset + len(blob)
        return offset, n.size

    def write_needle(self, n: Needle, fsync: bool = False,
                     journal: bool = True) -> tuple[int, int]:
        """Append an object. Returns (offset, stored size).

        Like the reference, writes reach the OS page cache (flush) but
        are NOT fsynced by default — durability rides replication, and
        `?fsync=true` opts a request in per-call
        (topology/store_replicate.go:37-44, writeNeedle2's fsync
        branch).  fsync=True requests ride the batch worker so
        concurrent durable writers share one fsync per ≤128-request
        batch.  Map entries publish only after flush, so a lock-free
        pread can never observe a mapped offset whose bytes haven't
        reached the OS.
        """
        if self._closed:
            raise VolumeError(f"volume {self.vid} is closed")
        if not fsync or not self._use_worker:
            # Same lock discipline as the batch worker: the file lock
            # in write mode excludes vacuum's and tiering's fd swaps
            # (which synchronize on _file_lock.write() only), _lock
            # orders appends.
            with self._file_lock.write(), self._lock:
                with _phases.phase("disk"):
                    off, size = self._write_record_locked(n)
                    self._dat.flush()
                    if fsync:
                        os.fsync(self._dat.fileno())
                    self.nm.put(n.id, off, n.size)
                    if fsync:
                        self.nm.sync()
                    else:
                        self.nm.flush()
                    if journal and self.rlog is not None:
                        self.rlog.append(self.rlog.OP_WRITE, n.id,
                                         n.cookie, n.size)
                        if fsync:
                            self.rlog.sync()
                self.last_modified = time.time()
                return off, size
        req = _WriteReq(needle=n, done=threading.Event(),
                        journal=journal)
        self._queue.put(req)
        if self._closed:
            # close() may already have drained the queue; fail fast instead
            # of waiting on a worker that will never run.
            req.error = req.error or VolumeError(
                f"volume {self.vid} is closed")
            req.done.set()
        # The batch worker appends + fsyncs on its own thread; this
        # handler's wall time spent waiting on it IS the request's
        # disk time (write + shared group fsync).
        with _phases.phase("disk"):
            req.done.wait()
        if req.error:
            raise req.error
        return req.offset, req.size

    def delete_needle(self, needle_id: int, journal: bool = True) -> int:
        """Tombstone an object. Returns bytes freed (0 if absent).

        Appends a zero-data needle (so the .dat replays the delete) and a
        tombstone idx entry, mirroring doDeleteRequest
        (volume_read_write.go).  journal=False suppresses the
        replication change-log record: quarantine tombstones (and the
        standby's own apply path) must never propagate as user deletes.
        """
        with self._file_lock.write(), self._lock:
            if self.readonly:
                raise VolumeError(f"volume {self.vid} is read only")
            entry = self.nm.get(needle_id)
            if entry is None:
                return 0
            marker = Needle(cookie=0, id=needle_id, data=b"")
            marker.append_at_ns = time.time_ns()
            offset = self._append_at
            blob = marker.to_bytes(self.version)
            self._dat.seek(offset)
            self._dat.write(blob)
            self._append_at = offset + len(blob)
            self._dat.flush()
            # Publish the tombstone only after the marker bytes are flushed.
            freed = self.nm.delete(needle_id)
            self.nm.flush()
            if journal and self.rlog is not None:
                self.rlog.append(self.rlog.OP_DELETE, needle_id, 0, 0)
            self.last_modified = time.time()
            return freed

    # -- self-healing (storage/scrub.py drives these) ------------------------

    def _tickets_path(self) -> str:
        return self.file_name() + ".qrt"

    def _load_tickets(self) -> dict[int, float]:
        import json
        try:
            with open(self._tickets_path()) as f:
                return {int(k, 16): float(ts)
                        for k, ts in json.load(f).items()}
        except (OSError, ValueError):
            return {}

    def _save_tickets(self) -> None:
        """Persist the open repair tickets (best effort — a failed save
        costs re-detection by the next scrub, never data)."""
        import json
        path = self._tickets_path()
        try:
            if not self.repair_tickets:
                try:
                    os.remove(path)
                except FileNotFoundError:
                    pass
                return
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump({f"{k:x}": ts
                           for k, ts in self.repair_tickets.items()}, f)
            os.replace(tmp, path)
        except OSError:
            pass

    def corrupt_count(self) -> int:
        """Unrepaired corrupt needles (open repair tickets) — reported
        in heartbeats; any nonzero count degrades /cluster/healthz."""
        return len(self.repair_tickets)

    def quarantine_needle(self, key: int, node: str = "") -> bool:
        """Stop serving a corrupt needle's bytes: tombstone it and keep
        a repair ticket so a later sweep (or a degraded read) can still
        re-fetch it from a healthy replica.  Returns True if the needle
        was newly quarantined."""
        if key in self.repair_tickets:
            return False
        if self.nm.get(key) is None:
            return False
        try:
            # journal=False: a quarantine tombstone is NOT a user
            # delete — shipping it would delete the standby's healthy
            # copy of data this cluster merely failed to keep.
            self.delete_needle(key, journal=False)
        except VolumeError:
            pass  # readonly volume: the ticket still flags it degraded
        self.repair_tickets[key] = time.time()
        self._save_tickets()
        from ..events import emit as emit_event
        emit_event("volume.quarantine", node=node, severity="warn",
                   vid=self.vid, key=f"{key:x}")
        return True

    def repair_needle(self, n: Needle, fsync: bool = True) -> tuple[int, int]:
        """Rewrite a healthy copy of a needle in place (append + map
        publish), closing its repair ticket.  Runs even on a readonly
        volume: repair restores what the volume already promised to
        hold, it does not admit new data."""
        with self._file_lock.write(), self._lock:
            ro, self.readonly = self.readonly, False
            try:
                off, size = self._write_record_locked(n)
                self._dat.flush()
                if fsync:
                    os.fsync(self._dat.fileno())
                self.nm.put(n.id, off, n.size)
                if fsync:
                    self.nm.sync()  # both files durable, like write
                else:
                    self.nm.flush()
                if self.rlog is not None:
                    # A repair is journaled as a WRITE: the standby
                    # either already holds these bytes (same id+cookie,
                    # idempotent) or its copy is what this repair
                    # restored — re-shipping converges both cases.
                    self.rlog.append(self.rlog.OP_WRITE, n.id,
                                     n.cookie, n.size)
                self.last_modified = time.time()
            finally:
                self.readonly = ro
        if self.repair_tickets.pop(n.id, None) is not None:
            self._save_tickets()
        return off, size

    def read_needle_blob(self, needle_id: int) -> bytes:
        """Raw CRC-verified record bytes (header..padding) of one live
        needle — what a sibling replica pulls to heal its copy.  Raises
        CorruptNeedleError when this copy is rotten too."""
        entry = self.nm.get(needle_id)
        if entry is None or not t.size_is_valid(entry[1]):
            raise NotFoundError(f"needle {needle_id:x} not found")
        total = get_actual_size(entry[1], self.version)
        blob = self.pread(total, entry[0])
        if len(blob) < total:
            raise CorruptNeedleError(
                f"needle {needle_id:x}: record truncated")
        try:
            Needle.from_bytes(blob, self.version)  # CRC gate
        except ValueError as e:
            raise CorruptNeedleError(
                f"needle {needle_id:x}: {e}") from None
        return blob

    # -- read path ---------------------------------------------------------

    def read_needle(self, needle_id: int, cookie: int | None = None) -> Needle:
        """One map lookup + one pread (the O(1) design point).

        Takes the file lock in read mode so vacuum's fd swap can't close
        the fd mid-pread; readers run concurrently with each other.
        """
        with self._file_lock.read():
            entry = self.nm.get(needle_id)
            if entry is None:
                raise NotFoundError(f"needle {needle_id:x} not found")
            offset, size = entry
            if not t.size_is_valid(size):
                raise NotFoundError(f"needle {needle_id:x} deleted")
            total = get_actual_size(size, self.version)
            if _fault.ARMED:
                # disk.read: an armed fail is an OSError here — the
                # exact failure mode of a dying sector.
                _fault.hit("disk.read", vid=self.vid,
                           key=f"{needle_id:x}")
            # Inline disk-phase accounting (not the phases.phase ctx):
            # this is THE hot read path — the context manager's object
            # allocation and two method calls are measurable at
            # thousands of reads/sec, the inline form is not.
            _led = _phases.active()
            _t = time.perf_counter() if _led is not None else 0.0
            if self.remote_file is not None:
                # Any remote failure — FaultInjected (an OSError!),
                # URLError, timeout — becomes TierReadError so the
                # server maps it to a retryable 503 instead of routing
                # it into degraded-read repair.
                try:
                    blob = self.remote_file.pread(total, offset)
                except Exception as e:
                    raise TierReadError(
                        f"volume {self.vid}: remote read failed: "
                        f"{e}") from e
            else:
                blob = os.pread(self._dat.fileno(), total, offset)
            if _led is not None:
                _led.arr[_phases.IDX_DISK] += \
                    time.perf_counter() - _t
        try:
            n = Needle.from_bytes(blob, self.version)
        except ValueError as e:
            raise CorruptNeedleError(
                f"needle {needle_id:x}: {e}") from None
        if cookie is not None and n.cookie != cookie:
            raise VolumeError(
                f"cookie mismatch for needle {needle_id:x}")
        # Expiry honors the per-needle TTL first, then the volume
        # superblock's (the assign-time ?ttl) — storage/expiry.py is
        # the single decision point.
        if _expiry.needle_expired(n, self.super_block.ttl):
            raise NotFoundError(f"needle {needle_id:x} expired")
        return n

    def pread(self, size: int, offset: int) -> bytes:
        """Raw .dat range read under the read lock (local or remote) —
        the tail/backup scanners' and the scrubber's access path."""
        with self._file_lock.read():
            if _fault.ARMED:
                _fault.hit("disk.read", vid=self.vid)
            with _phases.phase("disk"):
                if self.remote_file is not None:
                    try:
                        return self.remote_file.pread(size, offset)
                    except Exception as e:
                        raise TierReadError(
                            f"volume {self.vid}: remote read "
                            f"failed: {e}") from e
                return os.pread(self._dat.fileno(), size, offset)

    def read_needle_slice(self, needle_id: int,
                          cookie: int | None = None,
                          min_size: int = 0) -> "NeedleSlice | None":
        """Zero-copy read: locate a needle, verify cookie + CRC by
        streaming preads, and return a NeedleSlice over the raw data
        bytes in the .dat — never materializing the payload as one
        Python object.  The slice rides a dup'd fd, so no volume lock
        is held during CRC or the client transfer: a vacuum swap
        mid-read just leaves the reader on the old inode's consistent
        bytes (the GET handler streams the slice with os.sendfile).

        Returns None when the record needs the full parse path: v1
        layout, remote-tiered volume, empty body, a body smaller than
        `min_size`, or flags the read pipeline must interpret
        (compressed / TTL).  Raises like read_needle for absent or
        deleted needles so callers map errors identically.
        (Reference parity: volume_server_handlers_read.go reads then
        verifies the CRC before writing data out — same check, no
        userspace copy of the payload.)
        """
        from ..core import crc as crc_mod
        from ..core.needle import (FLAG_HAS_TTL, FLAG_IS_COMPRESSED,
                                   VERSION1)
        if self.remote_file is not None or self.version == VERSION1:
            return None
        with self._file_lock.read():
            entry = self.nm.get(needle_id)
            if entry is None:
                raise NotFoundError(f"needle {needle_id:x} not found")
            offset, size = entry
            if not t.size_is_valid(size):
                raise NotFoundError(f"needle {needle_id:x} deleted")
            if size < max(min_size, 5):  # data_size(4)+flags(1) floor
                return None
            fd = os.dup(self._dat.fileno())
        try:
            head = os.pread(fd, t.NEEDLE_HEADER_SIZE + 4, offset)
            if len(head) < t.NEEDLE_HEADER_SIZE + 4:
                raise VolumeError(f"needle {needle_id:x} truncated")
            disk_cookie = t.get_uint32(head, 0)
            disk_size = t.get_uint32(head, 12)
            data_size = t.get_uint32(head, 16)
            if cookie is not None and disk_cookie != cookie:
                raise VolumeError(
                    f"cookie mismatch for needle {needle_id:x}")
            if disk_size != size or data_size + 5 > size \
                    or data_size < min_size:
                os.close(fd)
                return None  # unusual record: take the full parse path
            data_off = offset + t.NEEDLE_HEADER_SIZE + 4
            # Everything after the data bytes up to the checksum:
            # flags(1) + optional name/mime/last-modified extras —
            # bounded by `size`, typically a handful of bytes.
            tail = os.pread(fd, size - 4 - data_size,
                            data_off + data_size)
            if not tail or tail[0] & (FLAG_IS_COMPRESSED
                                      | FLAG_HAS_TTL):
                os.close(fd)
                return None  # needs decode / expiry logic
            meta = _parse_needle_extras(tail)
            stored = t.get_uint32(os.pread(
                fd, 4, offset + t.NEEDLE_HEADER_SIZE + size))
            crc = 0
            pos, remaining = data_off, data_size
            # Attributed to `disk`: the streaming CRC pass is the read
            # path's per-byte payload verification — its cost scales
            # with the bytes pread, not with handler logic.
            with _phases.phase("disk"):
                while remaining:
                    chunk = os.pread(fd, min(remaining, 4 << 20), pos)
                    if not chunk:
                        raise VolumeError(
                            f"needle {needle_id:x} truncated")
                    crc = crc_mod.crc32c(chunk, crc)
                    pos += len(chunk)
                    remaining -= len(chunk)
            if crc_mod.masked_value(crc) != stored:
                raise CorruptNeedleError(
                    f"CRC error on needle {needle_id:x}")
            return NeedleSlice(fd, data_off, data_size,
                               etag=f"{stored:08x}", **meta)
        except BaseException:
            os.close(fd)
            raise

    # -- stats / lifecycle --------------------------------------------------

    def content_size(self) -> int:
        return self.nm.content_size()

    def deleted_size(self) -> int:
        return self.nm.deleted_size()

    def file_count(self) -> int:
        return len(self.nm)

    def dat_size(self) -> int:
        with self._lock:
            return self._append_at

    def garbage_ratio(self) -> float:
        total = self.dat_size()
        if total <= SUPER_BLOCK_SIZE:
            return 0.0
        return self.nm.deleted_size() / total

    def max_file_key(self) -> int:
        return self.nm.metrics.maximum_file_key

    def set_readonly(self, ro: bool = True) -> None:
        with self._lock:
            self.readonly = ro

    def configure_replication(self, rp: ReplicaPlacement) -> None:
        """Rewrite the superblock's replica-placement byte in place
        (VolumeConfigure RPC, server/volume_grpc_admin.go:104): the
        volume's intended copy count changes; actual replica repair is
        volume.fix.replication's job afterward."""
        with self._lock:
            if self._dat is None:
                raise VolumeError(
                    f"volume {self.vid} is tiered to remote storage; "
                    f"its superblock cannot be reconfigured in place")
            self.super_block.replica_placement = rp
            pos = self._dat.tell()
            self._dat.seek(0)
            self._dat.write(self.super_block.to_bytes())
            self._dat.flush()
            os.fsync(self._dat.fileno())
            self._dat.seek(pos)

    def sync(self) -> None:
        with self._lock:
            if self._dat is not None:
                self._dat.flush()
                os.fsync(self._dat.fileno())
            # The .idx is fsynced alongside the .dat: a sync() caller
            # (EC generate, volume copy, tiering) must get a pair of
            # files that agree after a crash, not data without index.
            self.nm.sync()

    def close(self) -> None:
        self._closed = True
        if self._worker is not None and self._worker.is_alive():
            self._queue.put(None)
            self._worker.join(timeout=5)
            self._worker = None
        # Fail any request that raced past the shutdown sentinel.
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not None:
                req.error = VolumeError(f"volume {self.vid} is closed")
                req.done.set()
        with self._lock:
            try:
                if self._dat is not None:
                    self._dat.flush()
                    self._dat.close()
                elif self.remote_file is not None:
                    self.remote_file.close()
            except ValueError:
                pass
            self.nm.close()
            if self.rlog is not None:
                self.rlog.close()
