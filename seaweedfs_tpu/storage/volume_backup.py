"""Incremental volume backup / tail.

Reference: weed/storage/volume_backup.go — `BinarySearchByAppendAtNs`
(:170) locates the cut offset of the first needle appended after a
timestamp by binary-searching the `.idx` entries (each probe reads that
needle's appendAtNs from the `.dat`), and `IncrementalBackup` (:65)
streams everything after the cut to a following copy.  The volume
server exposes this as the VolumeTail RPCs; `weed backup` consumes it.

The delta wire format is simply the raw `.dat` byte range after the cut
offset: appends are strictly time-ordered in an append-only volume, and
tombstones are needles too, so replaying the range reproduces state.
"""

from __future__ import annotations

import os

from ..core import types as t
from ..core.needle import Needle, needle_body_length
from .volume import Volume, VolumeError
from .volume_scanner import scan_volume_file


def _append_at_ns_at(volume: Volume, offset: int) -> int:
    """appendAtNs of the needle record starting at `offset`."""
    header = volume.pread(t.NEEDLE_HEADER_SIZE, offset)
    n = Needle.parse_header(header)
    body_len = needle_body_length(n.size, volume.version)
    blob = header + volume.pread(body_len,
                                 offset + t.NEEDLE_HEADER_SIZE)
    return Needle.from_bytes(blob, volume.version).append_at_ns


def _record_total(volume: Volume, offset: int) -> int:
    header = volume.pread(t.NEEDLE_HEADER_SIZE, offset)
    n = Needle.parse_header(header)
    return t.NEEDLE_HEADER_SIZE + needle_body_length(n.size,
                                                     volume.version)


def binary_search_by_append_at_ns(volume: Volume,
                                  since_ns: int) -> int:
    """Smallest .dat offset whose record (live OR tombstone) has
    append_at_ns > since_ns (BinarySearchByAppendAtNs); returns the
    volume's end offset when nothing is newer.

    Live-needle offsets (time-ordered in an append-only volume) drive
    the binary search; the gap before the found entry — which holds
    tombstones and overwritten needles invisible to the live map — is
    then walked forward so a delete is never cut out of the delta
    (deleted needles must not resurrect in backups)."""
    entries = volume.nm.ordered_offsets()
    lo, hi = 0, len(entries)
    while lo < hi:
        mid = (lo + hi) // 2
        if _append_at_ns_at(volume, entries[mid]) > since_ns:
            hi = mid
        else:
            lo = mid + 1
    # Scan from the end of the previous live record (or the volume
    # head) across the non-live gap.
    if lo == 0:
        scan_from = volume.super_block.block_size()
    else:
        prev = entries[lo - 1]
        scan_from = prev + _record_total(volume, prev)
    end = volume.dat_size()
    offset = scan_from
    while offset + t.NEEDLE_HEADER_SIZE <= end:
        if _append_at_ns_at(volume, offset) > since_ns:
            return offset
        offset += _record_total(volume, offset)
    return end


def read_incremental(volume: Volume, since_ns: int,
                     max_bytes: int = 64 * 1024 * 1024) -> bytes:
    """Raw .dat bytes for every record appended after since_ns (capped;
    callers loop with the last returned needle's timestamp)."""
    start = binary_search_by_append_at_ns(volume, since_ns)
    end = min(volume.dat_size(), start + max_bytes)
    if start >= end:
        return b""
    # Never split a trailing record: walk records within the window.
    out_end = start
    offset = start
    while offset + t.NEEDLE_HEADER_SIZE <= end:
        header = volume.pread(t.NEEDLE_HEADER_SIZE, offset)
        n = Needle.parse_header(header)
        total = t.NEEDLE_HEADER_SIZE + needle_body_length(
            n.size, volume.version)
        if offset + total > end:
            break
        offset += total
        out_end = offset
    return volume.pread(out_end - start, start)


def last_append_in_blob(delta: bytes, version: int) -> int:
    """Newest appendAtNs inside a delta blob (resume cursor)."""
    last = 0
    offset = 0
    while offset + t.NEEDLE_HEADER_SIZE <= len(delta):
        header = delta[offset:offset + t.NEEDLE_HEADER_SIZE]
        n = Needle.parse_header(header)
        total = t.NEEDLE_HEADER_SIZE + needle_body_length(
            n.size, version)
        if offset + total > len(delta):
            break
        needle = Needle.from_bytes(delta[offset:offset + total],
                                   version)
        last = max(last, needle.append_at_ns)
        offset += total
    return last


def last_append_at_ns(dat_path: str,
                      idx_path: str | None = None) -> int:
    """Newest appendAtNs in a local .dat — the backup's resume point.

    O(1) fast path (the reference derives the cursor from the idx
    tail): read .idx entries from the end, pread the first live one's
    needle.  A tombstone-only tail or missing .idx falls back to a full
    .dat scan."""
    from ..core import idx as idx_mod
    idx_path = idx_path or dat_path[:-4] + ".idx"
    try:
        from .volume_scanner import read_super_block
        version = read_super_block(dat_path).version
        entry_size = idx_mod.ENTRY_SIZE
        size = os.path.getsize(idx_path)
        with open(idx_path, "rb") as idx, open(dat_path, "rb") as dat:
            pos = size - (size % entry_size)
            # Walk back a bounded number of entries looking for a live
            # one (tombstones carry offset 0, no dat record to probe).
            for _ in range(64):
                pos -= entry_size
                if pos < 0:
                    break
                idx.seek(pos)
                e = t.NeedleMapEntry.from_bytes(idx.read(entry_size), 0)
                if e.offset <= 0 or not t.size_is_valid(e.size):
                    continue
                # Walk from the last live needle to EOF: trailing
                # tombstones are newer, and missing them would make
                # every incremental run re-fetch them.
                dat_size = os.fstat(dat.fileno()).st_size
                last = 0
                offset = e.offset
                while offset + t.NEEDLE_HEADER_SIZE <= dat_size:
                    header = os.pread(dat.fileno(),
                                      t.NEEDLE_HEADER_SIZE, offset)
                    n = Needle.parse_header(header)
                    body_len = needle_body_length(n.size, version)
                    if offset + t.NEEDLE_HEADER_SIZE + body_len > \
                            dat_size:
                        break
                    blob = header + os.pread(
                        dat.fileno(), body_len,
                        offset + t.NEEDLE_HEADER_SIZE)
                    last = max(last, Needle.from_bytes(
                        blob, version).append_at_ns)
                    offset += t.NEEDLE_HEADER_SIZE + body_len
                return last
    except (OSError, ValueError):
        pass
    last = 0
    for needle, _off, _total in scan_volume_file(dat_path):
        if needle.append_at_ns > last:
            last = needle.append_at_ns
    return last


def apply_incremental(dat_path: str, idx_path: str,
                      delta: bytes, version: int) -> int:
    """Append a delta blob to a local backup copy, updating the .idx
    (IncrementalBackup's receiving half).  Returns needles applied."""
    from ..core import idx as idx_mod
    applied = 0
    with open(dat_path, "r+b") as dat, open(idx_path, "ab") as idx:
        dat.seek(0, os.SEEK_END)
        base = dat.tell()
        dat.write(delta)
        dat.flush()
        offset = 0
        while offset + t.NEEDLE_HEADER_SIZE <= len(delta):
            header = delta[offset:offset + t.NEEDLE_HEADER_SIZE]
            n = Needle.parse_header(header)
            total = t.NEEDLE_HEADER_SIZE + needle_body_length(
                n.size, version)
            if offset + total > len(delta):
                raise VolumeError("truncated incremental delta")
            if n.size > 0:
                idx_mod.append_entry(idx, n.id, base + offset, n.size)
            else:  # tombstone
                idx_mod.append_entry(idx, n.id, 0,
                                     t.TOMBSTONE_FILE_SIZE)
            offset += total
            applied += 1
        idx.flush()
    return applied
