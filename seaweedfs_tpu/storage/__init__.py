"""Volume engine: append-only blob storage with O(1) reads."""
