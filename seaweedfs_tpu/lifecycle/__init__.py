"""Data-lifecycle plane: declarative per-collection rules (policy.py)
and the master-coordinated daemon that enforces them (daemon.py) —
cold volumes tier to a remote backend, TTL data actually expires, hot
tiered volumes promote back to local disk."""

from .daemon import LifecycleDaemon
from .policy import (Policy, PolicyError, Rule, load_rules,
                     parse_duration, parse_rules_text)

__all__ = ["LifecycleDaemon", "Policy", "PolicyError", "Rule",
           "load_rules", "parse_duration", "parse_rules_text"]
