"""The lifecycle daemon: the master-side loop that enforces policy.

Each scan walks the heartbeat topology, joins it with the per-node
`/debug/hot` read sketches (the PR 7 hot-key tracker measures exactly
the coldness signal an idle rule needs: a volume absent from the read
top-k gained no reads since the last scan), and acts:

- `tier` rules: a cold single-copy volume is flipped readonly on its
  holder, then `/admin/tier_upload` moves its .dat to the rule's
  backend — over the low-priority lane (the admission controller sheds
  background work first), behind a scrub-style byte throttle, with
  retry/breaker protection so a flapping holder degrades the scan, not
  the master.
- `expire` rules: the collection's TTL volumes are vacuumed so expired
  needles (dead to vacuum since this PR) physically vanish; the
  holder-side sweeper (volume_server._lifecycle_tick) retires volumes
  whose NEWEST write is past expiry whole.

Leader-only under raft: a deposed master's daemon idles, exactly like
the vacuum/sweep loops.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

from ..cluster import resilience, rpc
from ..events import emit as emit_event
from ..fault import registry as _fault
from ..stats import metrics as _metrics
from ..storage.scrub import RateLimiter
from .policy import Policy


class LifecycleDaemon:
    """Policy enforcement loop owned by the master (leader-only)."""

    def __init__(self, master, policy: Policy,
                 interval: float = 60.0, mbps: float = 32.0):
        self.master = master
        self.policy = policy
        self.interval = interval
        self.limiter = RateLimiter(mbps)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Per-node read totals from the previous scan: an idle decision
        # needs a baseline, so a node's first scan only observes.
        self._read_totals: dict[str, dict[int, int]] = {}
        self.scans = 0
        self.last_scan = 0.0
        self.actions = {"tier_ok": 0, "tier_error": 0, "expire_ok": 0,
                        "expire_error": 0}
        self.recent: deque = deque(maxlen=32)
        self._policy_retry = resilience.RetryPolicy(
            max_attempts=3, per_attempt_timeout=120.0,
            total_deadline=300.0)

    # -- lifecycle of the loop itself ------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="lifecycle", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            if not self.master.is_leader():
                continue
            try:
                self.scan_once()
            except Exception:  # noqa: BLE001 — a scan must not kill the loop
                pass

    # -- one scan ---------------------------------------------------------

    def scan_once(self) -> dict:
        """Walk the topology, apply every rule once.  Returns a summary
        (also driven directly by tests and `cluster.lifecycle run`)."""
        out = {"tiered": [], "vacuumed": [], "errors": []}
        if not self.policy.rules:
            return out
        topo = self.master.topo
        # Holder map first: tiering is single-copy only (the remote
        # object would be shared state under two holders' feet).
        holders: dict[int, list] = {}
        for dn in list(topo.leaves()):
            for vid in dn.volumes:
                holders.setdefault(vid, []).append(dn)
        for dn in list(topo.leaves()):
            url = dn.url()
            baseline = self._read_totals.get(url)
            reads = self._node_read_totals(url)
            if reads is not None:
                self._read_totals[url] = reads
            for vid, vinfo in sorted(dn.volumes.items()):
                self._consider(dn, vid, vinfo, holders, baseline,
                               reads, out)
        self.scans += 1
        self.last_scan = time.time()
        return out

    def _node_read_totals(self, url: str) -> dict[int, int] | None:
        """Per-volume cumulative read counts from the node's /debug/hot
        sketch (None: node unreachable — no idle decisions for it)."""
        try:
            snap = rpc.call(f"http://{url}/debug/hot", "GET",
                            timeout=5.0, headers=rpc.PRIORITY_LOW)
            top = snap["dimensions"]["volume"]["read"]["top"]
            return {int(e["key"]): int(e["count"]) for e in top}
        except Exception:  # noqa: BLE001
            return None

    def _consider(self, dn, vid: int, vinfo, holders, baseline,
                  reads, out: dict) -> None:
        collection = getattr(vinfo, "collection", "")
        if getattr(vinfo, "tiered", False):
            return
        expire = self.policy.expire_rule_for(collection)
        if expire is not None and getattr(vinfo, "ttl", 0):
            self._vacuum_one(dn, vid, out)
        rule = self.policy.tier_rule_for(collection)
        if rule is None or len(holders.get(vid, ())) != 1:
            return
        now = time.time()
        modified_at = getattr(vinfo, "modified_at", 0)
        if rule.min_age:
            if not modified_at or now - modified_at < rule.min_age:
                return
        if rule.fullness:
            limit = self.master.topo.volume_size_limit
            if getattr(vinfo, "size", 0) < rule.fullness * limit:
                return
        if rule.idle_for:
            if not modified_at or now - modified_at < rule.idle_for:
                return
            # No read-count baseline yet (first sight of this node):
            # observe this scan, act the next.
            if baseline is None or reads is None:
                return
            if reads.get(vid, 0) - baseline.get(vid, 0) > 0:
                return  # gained reads since the last scan: not cold
        self._tier_one(dn, vid, vinfo, rule, out)

    # -- actions ----------------------------------------------------------

    def _tier_one(self, dn, vid: int, vinfo, rule, out: dict) -> None:
        url = dn.url()
        breaker = resilience.breaker_for(url)
        size = getattr(vinfo, "size", 0)

        def step(path: str, payload: dict):
            def send(attempt: int, timeout: float):
                if not breaker.allow():
                    raise resilience.BreakerOpen(url)
                try:
                    if _fault.ARMED:
                        # The holder may sit across a WAN from the
                        # backend AND the master; the ship-path shaping
                        # points model both legs here.
                        _fault.hit("wan.delay", peer=url, vid=vid)
                        _fault.hit("wan.partition", peer=url, vid=vid)
                    r = rpc.call(f"http://{url}{path}", "POST",
                                 json.dumps(payload).encode(),
                                 timeout=timeout,
                                 headers=rpc.PRIORITY_LOW)
                except Exception as e:  # noqa: BLE001 — classified by retry
                    status = getattr(e, "status", None)
                    if status is None or status >= 500:
                        breaker.record_failure()
                    raise
                breaker.record_success()
                return r

            # Idempotent by construction: readonly is a flag write and
            # a tier_upload re-send either re-uploads (overwrite) or
            # 400s on the already-remote volume, never duplicates data.
            return self._policy_retry.run(send, idempotent=True)

        try:
            step("/admin/readonly", {"volume": vid, "readonly": True})
            self.limiter.take(size)
            step("/admin/tier_upload", {"volume": vid,
                                        "dest": rule.dest})
        except Exception as e:  # noqa: BLE001 — scan continues
            self.actions["tier_error"] += 1
            _metrics.lifecycle_actions_total.inc(action="tier",
                                                 outcome="error")
            out["errors"].append({"volume": vid, "node": url,
                                  "error": str(e)})
            self._note("tier_error", vid, url, error=str(e))
            return
        self.actions["tier_ok"] += 1
        _metrics.lifecycle_actions_total.inc(action="tier",
                                             outcome="ok")
        out["tiered"].append(vid)
        emit_event("lifecycle.tier", vid=vid, node=url,
                   dest=rule.dest, bytes=size,
                   collection=getattr(vinfo, "collection", ""))
        self._note("tier", vid, url, dest=rule.dest)

    def _vacuum_one(self, dn, vid: int, out: dict) -> None:
        url = dn.url()
        try:
            rpc.call(f"http://{url}/admin/vacuum", "POST",
                     json.dumps({"volume": vid}).encode(),
                     timeout=120.0, headers=rpc.PRIORITY_LOW)
        except Exception as e:  # noqa: BLE001
            self.actions["expire_error"] += 1
            _metrics.lifecycle_actions_total.inc(action="expire",
                                                 outcome="error")
            out["errors"].append({"volume": vid, "node": url,
                                  "error": str(e)})
            return
        self.actions["expire_ok"] += 1
        _metrics.lifecycle_actions_total.inc(action="expire",
                                             outcome="ok")
        out["vacuumed"].append(vid)

    def _note(self, kind: str, vid: int, node: str, **extra) -> None:
        self.recent.append({"at": round(time.time(), 3), "kind": kind,
                            "volume": vid, "node": node, **extra})

    # -- introspection -----------------------------------------------------

    def status(self) -> dict:
        return {
            "enabled": bool(self.policy.rules),
            "rules": self.policy.to_dict()["rules"],
            "interval": self.interval,
            "scans": self.scans,
            "last_scan_age": (round(time.time() - self.last_scan, 3)
                              if self.last_scan else None),
            "actions": dict(self.actions),
            "recent": list(self.recent),
        }
