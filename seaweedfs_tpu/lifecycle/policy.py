"""Declarative lifecycle rules: which collections tier where, and what
expires.

Two formats, one model.  The line grammar (the `-lifecycle.rules`
default) is one rule per line:

    # collection  action  [key=value ...]
    logs    tier   dest=local:///cold  idle=10m
    pics    tier   dest=s3://minio:9000/frozen  age=30d  fullness=0.8
    scratch expire
    *       expire

and the same rules in TOML (a `.toml` path switches parsers):

    [[rule]]
    collection = "logs"
    action = "tier"
    dest = "local:///cold"
    idle = "10m"

`tier` conditions (idle / age / fullness) AND together; at least one is
required — an unconditional tier rule would tier a volume the moment
it rolls readonly.  `expire` needs no conditions: it opts the
collection's TTL volumes into vacuum-driven reclaim (the TTL itself
rides the assign-time `?ttl`, stamped in the volume superblock and on
each needle).

Collections match exactly; `*` matches any.  The FIRST matching rule
per action wins, so specific lines go above the wildcard.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

_DURATION_RE = re.compile(r"^(\d+(?:\.\d+)?)\s*([smhdw]?)$")

_UNIT_SECONDS = {"": 1.0, "s": 1.0, "m": 60.0, "h": 3600.0,
                 "d": 86400.0, "w": 604800.0}


class PolicyError(ValueError):
    pass


def parse_duration(text: str) -> float:
    """'90s' / '10m' / '2h' / '30d' / bare seconds -> seconds.  Finer
    grained than core/ttl.py's wire codec on purpose: rule thresholds
    are scan-time comparisons, not stored per needle."""
    m = _DURATION_RE.match(str(text).strip())
    if not m:
        raise PolicyError(f"bad duration: {text!r}")
    return float(m.group(1)) * _UNIT_SECONDS[m.group(2)]


@dataclass(frozen=True)
class Rule:
    collection: str          # exact name, or "*"
    action: str              # "tier" | "expire"
    dest: str = ""           # tier: backend spec (backend_for_spec)
    idle_for: float = 0.0    # tier: seconds with no reads AND no writes
    min_age: float = 0.0     # tier: seconds since the newest write
    fullness: float = 0.0    # tier: fraction of the volume size limit

    def matches(self, collection: str) -> bool:
        return self.collection == "*" or self.collection == collection

    def to_dict(self) -> dict:
        d = {"collection": self.collection, "action": self.action}
        if self.dest:
            d["dest"] = self.dest
        if self.idle_for:
            d["idle_for"] = self.idle_for
        if self.min_age:
            d["min_age"] = self.min_age
        if self.fullness:
            d["fullness"] = self.fullness
        return d


def _build_rule(collection: str, action: str, kv: dict) -> Rule:
    if action not in ("tier", "expire"):
        raise PolicyError(f"unknown lifecycle action {action!r} "
                          f"(want tier|expire)")
    known = {"dest", "idle", "age", "fullness"}
    bad = set(kv) - known
    if bad:
        raise PolicyError(f"unknown rule keys {sorted(bad)}")
    dest = str(kv.get("dest", ""))
    idle_for = parse_duration(kv["idle"]) if "idle" in kv else 0.0
    min_age = parse_duration(kv["age"]) if "age" in kv else 0.0
    fullness = float(kv.get("fullness", 0.0))
    if action == "tier":
        if not dest:
            raise PolicyError("tier rule needs dest=<backend spec>")
        if not (idle_for or min_age or fullness):
            raise PolicyError(
                "tier rule needs at least one of idle=/age=/fullness=")
        if fullness and not 0.0 < fullness <= 1.0:
            raise PolicyError(f"fullness must be in (0, 1]: {fullness}")
    elif kv:
        raise PolicyError("expire rule takes no conditions "
                          f"(got {sorted(kv)})")
    return Rule(collection=collection, action=action, dest=dest,
                idle_for=idle_for, min_age=min_age, fullness=fullness)


def parse_rules_text(text: str) -> "Policy":
    rules = []
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split()
        if len(parts) < 2:
            raise PolicyError(f"line {lineno}: want "
                              f"'<collection> <action> [k=v ...]'")
        collection, action = parts[0], parts[1]
        kv = {}
        for tok in parts[2:]:
            k, eq, v = tok.partition("=")
            if not eq:
                raise PolicyError(f"line {lineno}: bad token {tok!r}")
            kv[k] = v
        try:
            rules.append(_build_rule(collection, action, kv))
        except PolicyError as e:
            raise PolicyError(f"line {lineno}: {e}") from None
    return Policy(rules)


def parse_rules_toml(text: str) -> "Policy":
    try:
        import tomllib
    except ModuleNotFoundError:  # stdlib tomllib is 3.11+
        try:
            import tomli as tomllib  # type: ignore[no-redef]
        except ModuleNotFoundError:
            raise PolicyError(
                "TOML rules need Python 3.11+ (stdlib tomllib) or the "
                "tomli package; use the line grammar instead") from None
    try:
        doc = tomllib.loads(text)
    except tomllib.TOMLDecodeError as e:
        raise PolicyError(f"bad TOML: {e}") from None
    rules = []
    for i, entry in enumerate(doc.get("rule", [])):
        if not isinstance(entry, dict):
            raise PolicyError(f"rule #{i}: want a table")
        kv = {k: v for k, v in entry.items()
              if k not in ("collection", "action")}
        try:
            rules.append(_build_rule(str(entry.get("collection", "*")),
                                     str(entry.get("action", "")), kv))
        except PolicyError as e:
            raise PolicyError(f"rule #{i}: {e}") from None
    return Policy(rules)


def load_rules(path: str) -> "Policy":
    with open(path) as f:
        text = f.read()
    if path.endswith(".toml"):
        return parse_rules_toml(text)
    return parse_rules_text(text)


class Policy:
    """An ordered rule list; first match per action wins."""

    def __init__(self, rules: list[Rule] | None = None):
        self.rules = list(rules or [])

    def tier_rule_for(self, collection: str) -> Rule | None:
        for r in self.rules:
            if r.action == "tier" and r.matches(collection):
                return r
        return None

    def expire_rule_for(self, collection: str) -> Rule | None:
        for r in self.rules:
            if r.action == "expire" and r.matches(collection):
                return r
        return None

    def to_dict(self) -> dict:
        return {"rules": [r.to_dict() for r in self.rules]}

    def __len__(self) -> int:
        return len(self.rules)
