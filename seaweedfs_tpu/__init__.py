"""seaweedfs_tpu — a TPU-native distributed object store.

A ground-up rebuild of the capabilities of SeaweedFS (reference:
/root/reference, pure Go) designed TPU-first:

- the Reed-Solomon GF(2^8) erasure-coding hot path is a bit-sliced matmul on
  the TPU MXU (``ops/``: numpy oracle, XLA coder, Pallas kernel);
- multi-volume encode/rebuild scales over a ``jax.sharding.Mesh`` with XLA
  collectives (``parallel/``);
- the storage/cluster framework (needle formats, volume engine, topology,
  master/volume servers, filer, gateways) keeps the reference's on-disk and
  wire shapes so existing tools and operators carry over (``core/``,
  ``storage/``, ``ec/``, ``topology/``, ``cluster/``, ``shell/``).
"""

__version__ = "0.1.0"
