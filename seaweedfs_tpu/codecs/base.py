"""Codec-as-data: an erasure codec is a generator matrix, not code.

ROADMAP item 2's refactor unlock.  Every codec here is a *value* — a
name, shard counts, locality groups, and a systematic (total x data)
generator matrix over GF(2^8) — and every byte-crunching backend
(numpy oracle, C++ AVX2, XLA, the Pallas MXU kernel) consumes that
value through the exact same GF(2) bit-matmul primitive
(`ops/coder_pallas.apply_bitmatrix_pallas` takes the matrix as an
argument).  Adding a codec therefore never touches a kernel: it is a
new matrix plus metadata in the registry below.

Two codecs ship:

- `rs`  — RS(10,4), the reference-compatible default.  Matrices come
  from the klauspost Vandermonde construction (`ops/gf256.py`), so
  shard bytes stay bit-identical with the reference's `.ec00`-`.ec13`.
- `lrc` — LRC(10,2,2) (codecs/lrc.py): 10 data shards in two local
  groups of 5, one XOR local parity per group, two global Cauchy
  parities.  Single-shard repair reads 5 shards instead of 10 — the
  Facebook warehouse study (arxiv 1309.0186) measured repair traffic
  as the top cluster-network consumer, and local reconstruction codes
  (arxiv 1412.3022) shrink exactly that.

Decoding is a generic GF(2^8) solve: express each wanted shard's
generator row as a combination of survivor rows (Gaussian elimination
with a caller-supplied read-preference order), so the SAME solver
serves RS's any-k-of-n decode, LRC's 5-read local repair, and LRC's
global fallback — the read set falls out of the algebra.
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass

import numpy as np

from ..ops import gf256

DEFAULT_CODEC = "rs"

# Per-codec decode/bit-matrix cache bound (mirrors the coder-level
# lru_cache(maxsize=256)): keys are (present, wanted, prefer) tuples,
# and on a long-degraded cluster the partial-survivor key space would
# otherwise grow without limit on these process-global singletons.
# Matrices are cheap to re-derive, so overflow just clears.
_CACHE_CAP = 1024


@dataclass(frozen=True)
class LocalGroup:
    """One locality group: the data shards it spans plus its dedicated
    local parity shard (the XOR of the members)."""

    data: tuple[int, ...]
    parity: int

    @property
    def members(self) -> tuple[int, ...]:
        return self.data + (self.parity,)


@dataclass(frozen=True)
class RepairRead:
    """The planned read set for rebuilding one missing shard."""

    sid: int
    reads: tuple[int, ...]
    local: bool  # True when the reads stay inside one locality group


class Codec:
    """An erasure codec as data.

    matrix: (total x data) systematic generator over GF(2^8) — top
    `data_shards` rows are the identity.  `locality` lists the local
    groups (empty for plain MDS codes like RS).  `tolerance` is the
    number of simultaneous shard losses the codec ALWAYS survives
    (some patterns beyond it may still decode — e.g. LRC(10,2,2)
    survives one loss per local group plus both globals = 4).
    """

    def __init__(self, name: str, matrix: np.ndarray, data_shards: int,
                 locality: tuple[LocalGroup, ...] = (),
                 tolerance: int | None = None,
                 matrix_kind: str = "vandermonde"):
        total = matrix.shape[0]
        if matrix.shape[1] != data_shards or total <= data_shards:
            raise ValueError(
                f"codec {name!r}: generator must be (total x {data_shards}) "
                f"with total > data, got {matrix.shape}")
        if not np.array_equal(matrix[:data_shards],
                              gf256.mat_identity(data_shards)):
            raise ValueError(f"codec {name!r}: generator not systematic")
        self.name = name
        self.data_shards = data_shards
        self.total_shards = total
        self.parity_shards = total - data_shards
        self.locality = locality
        self.matrix_kind = matrix_kind
        self.tolerance = (total - data_shards if tolerance is None
                          else tolerance)
        m = np.ascontiguousarray(matrix, dtype=np.uint8)
        m.setflags(write=False)
        self.matrix = m
        self._group_of: dict[int, LocalGroup] = {}
        for g in locality:
            for sid in g.members:
                self._group_of[sid] = g
        self._decode_cache: dict[tuple, tuple] = {}
        self._bit_cache: dict[tuple, tuple] = {}
        self._cache_lock = threading.Lock()

    # RS codecs keep the exact klauspost decode path (identical `used`
    # selection, identical error strings) — the generic solver is for
    # codecs whose minimal read set is NOT "any data_shards survivors".
    @property
    def is_rs(self) -> bool:
        return not self.locality

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return (f"Codec({self.name!r}, k={self.data_shards}, "
                f"m={self.parity_shards}, groups={len(self.locality)})")

    # -- structure ----------------------------------------------------------

    def local_group(self, sid: int) -> LocalGroup | None:
        return self._group_of.get(sid)

    def shard_ids(self) -> list[int]:
        return list(range(self.total_shards))

    def min_repair_reads(self, sid: int) -> int:
        """Shards a single-shard repair reads when everything else
        survives — the headline repair-bandwidth number."""
        g = self._group_of.get(sid)
        if g is not None:
            return len(g.members) - 1
        return self.data_shards

    # -- matrices -----------------------------------------------------------

    def parity_matrix(self) -> np.ndarray:
        """(parity x data) rows that map data shards to parity shards."""
        return self.matrix[self.data_shards:]

    def parity_bitmatrix(self) -> np.ndarray:
        """GF(2)-lowered (8*parity x 8*data) parity matrix."""
        from ..ops import rs_bitmatrix
        if self.is_rs:
            return rs_bitmatrix.parity_bitmatrix(
                self.data_shards, self.total_shards, self.matrix_kind)
        key = ("parity",)
        with self._cache_lock:
            hit = self._bit_cache.get(key)
        if hit is None:
            b = rs_bitmatrix.expand_bitmatrix(self.parity_matrix())
            b.setflags(write=False)
            with self._cache_lock:
                hit = self._bit_cache.setdefault(key, b)
        return hit  # the parity key is a singleton; no bound needed

    def decode_matrix(self, present: tuple[int, ...],
                      wanted: tuple[int, ...],
                      prefer: tuple[int, ...] = ()
                      ) -> tuple[np.ndarray, tuple[int, ...]]:
        """GF(2^8) matrix rebuilding `wanted` shards from survivors.

        Returns (mat, used): `used` is the minimal read set the solve
        settled on (survivors in `prefer`-first order are tried as
        pivots first), mat is (len(wanted) x len(used)) with
        wanted_shards = mat @ stacked(used shards).  Raises ValueError
        when the erasure pattern is undecodable.
        """
        present = tuple(sorted(set(present)))
        wanted = tuple(wanted)
        prefer = tuple(prefer)
        if self.is_rs:
            mat, used = gf256.decode_matrix(
                self.data_shards, self.total_shards, list(present),
                wanted=list(wanted), kind=self.matrix_kind)
            return mat, tuple(used)
        key = (present, wanted, prefer)
        with self._cache_lock:
            hit = self._decode_cache.get(key)
        if hit is None:
            bad = [s for s in present + wanted
                   if not 0 <= s < self.total_shards]
            if bad:
                raise ValueError(
                    f"shard ids {bad} out of range [0, {self.total_shards})")
            mat, used = solve_decode(self.matrix, present, wanted, prefer)
            mat.setflags(write=False)
            with self._cache_lock:
                if len(self._decode_cache) >= _CACHE_CAP:
                    self._decode_cache.clear()
                hit = self._decode_cache.setdefault(key, (mat, used))
        return hit

    def decode_bitmatrix(self, present: tuple[int, ...],
                         wanted: tuple[int, ...],
                         prefer: tuple[int, ...] = ()
                         ) -> tuple[np.ndarray, tuple[int, ...]]:
        """GF(2)-lowered decode matrix: (8*wanted x 8*used), used."""
        from ..ops import rs_bitmatrix
        if self.is_rs:
            return rs_bitmatrix.decode_bitmatrix(
                self.data_shards, self.total_shards, tuple(present),
                tuple(wanted), self.matrix_kind)
        key = (tuple(sorted(set(present))), tuple(wanted), tuple(prefer))
        with self._cache_lock:
            hit = self._bit_cache.get(key)
        if hit is None:
            mat, used = self.decode_matrix(*key)
            b = rs_bitmatrix.expand_bitmatrix(mat)
            b.setflags(write=False)
            with self._cache_lock:
                if len(self._bit_cache) >= _CACHE_CAP:
                    self._bit_cache.clear()
                hit = self._bit_cache.setdefault(key, (b, used))
        return hit

    # -- repair planning ----------------------------------------------------

    def repair_plan(self, present, missing) -> list[RepairRead]:
        """Per-missing-shard minimal read sets: local group first,
        global fallback — the repair-bandwidth-optimal plan the
        cluster rebuild and the degraded-read ladder both follow.
        Raises ValueError when any missing shard is undecodable."""
        present = tuple(sorted(set(present)))
        plans = []
        for sid in missing:
            g = self._group_of.get(sid)
            prefer = tuple(m for m in g.members if m != sid) if g else ()
            _mat, used = self.decode_matrix(present, (sid,), prefer)
            local = g is not None and set(used) <= set(g.members)
            plans.append(RepairRead(sid, used, local))
        return plans


def solve_decode(gen: np.ndarray, present: tuple[int, ...],
                 wanted: tuple[int, ...], prefer: tuple[int, ...] = ()
                 ) -> tuple[np.ndarray, tuple[int, ...]]:
    """Express each `wanted` generator row as a GF(2^8) combination of
    `present` rows (Gauss-Jordan on gen[present].T with survivor
    columns tried in prefer-first order).  The unique solution over
    the pivot columns IS the minimal-read decode: survivors the
    algebra doesn't need get zero coefficients and are dropped.
    """
    order = [s for s in prefer if s in present] + \
            [s for s in sorted(present) if s not in prefer]
    k = gen.shape[1]
    t = gf256.mul_table()
    a = gen[order].T.astype(np.uint8).copy()          # (k, survivors)
    b = gen[list(wanted)].T.astype(np.uint8).copy()   # (k, wanted)
    ncols = a.shape[1]
    pivots: list[int] = []
    row = 0
    for c in range(ncols):
        if row >= k:
            break
        pivot = -1
        for r in range(row, k):
            if a[r, c]:
                pivot = r
                break
        if pivot < 0:
            continue
        if pivot != row:
            a[[row, pivot]] = a[[pivot, row]]
            b[[row, pivot]] = b[[pivot, row]]
        inv = gf256.gf_inv(int(a[row, c]))
        a[row] = t[inv, a[row]]
        b[row] = t[inv, b[row]]
        for r in range(k):
            if r != row and a[r, c]:
                f = int(a[r, c])
                a[r] ^= t[f, a[row]]
                b[r] ^= t[f, b[row]]
        pivots.append(c)
        row += 1
    # Non-pivot rows are all-zero in `a`; a nonzero target there means
    # the wanted shard is outside the survivors' span: undecodable.
    for r in range(row, k):
        if b[r].any():
            unsolved = [w for i, w in enumerate(wanted) if b[r, i]]
            raise ValueError(
                f"shards {unsolved} unrecoverable from survivors "
                f"{sorted(present)}: erasure pattern exceeds the code")
    x = np.zeros((ncols, len(wanted)), dtype=np.uint8)
    for i, c in enumerate(pivots):
        x[c] = b[i]
    used_cols = [c for c in pivots if x[c].any()]
    used = tuple(order[c] for c in used_cols)
    mat = np.ascontiguousarray(x[used_cols].T)
    return mat, used


# -- registry ---------------------------------------------------------------

_REGISTRY: dict[str, Codec] = {}
_REGISTRY_LOCK = threading.Lock()


def register_codec(codec: Codec) -> Codec:
    with _REGISTRY_LOCK:
        _REGISTRY[codec.name] = codec
    return codec


def codec_names() -> list[str]:
    with _REGISTRY_LOCK:
        return sorted(_REGISTRY)


def get_codec(name: str | Codec | None) -> Codec:
    """Resolve a codec by name (None -> the default `rs`)."""
    if isinstance(name, Codec):
        return name
    if not name:
        name = DEFAULT_CODEC
    with _REGISTRY_LOCK:
        codec = _REGISTRY.get(name)
    if codec is None:
        raise ValueError(
            f"unknown erasure codec {name!r}; registered: {codec_names()}")
    return codec


@functools.lru_cache(maxsize=None)
def rs_codec(data_shards: int = 10, parity_shards: int = 4,
             matrix_kind: str = "vandermonde") -> Codec:
    """Ad-hoc RS codec for parameterized schemes (RS(16,4), RS(8,3));
    the registered `rs` is exactly rs_codec(10, 4, "vandermonde")."""
    total = data_shards + parity_shards
    if matrix_kind == "vandermonde":
        matrix = gf256.build_systematic_matrix(data_shards, total)
    elif matrix_kind == "cauchy":
        matrix = gf256.build_cauchy_matrix(data_shards, total)
    else:
        raise ValueError(f"unknown matrix kind {matrix_kind!r}")
    name = "rs" if (data_shards, parity_shards,
                    matrix_kind) == (10, 4, "vandermonde") \
        else f"rs{data_shards}_{parity_shards}_{matrix_kind}"
    return Codec(name, np.asarray(matrix), data_shards,
                 tolerance=parity_shards, matrix_kind=matrix_kind)


register_codec(rs_codec())
