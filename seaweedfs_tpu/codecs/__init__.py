"""Pluggable erasure codecs (see base.py for the design).

    from seaweedfs_tpu import codecs
    codec = codecs.get_codec("lrc")
    codec.repair_plan(present=set(range(14)) - {3}, missing=[3])
    # -> [RepairRead(sid=3, reads=(0, 1, 2, 4, 10), local=True)]
"""

from .base import (DEFAULT_CODEC, Codec, LocalGroup, RepairRead,
                   codec_names, get_codec, register_codec, rs_codec,
                   solve_decode)
from .lrc import LRC_10_2_2  # noqa: F401 — import registers "lrc"

__all__ = [
    "DEFAULT_CODEC", "Codec", "LocalGroup", "RepairRead",
    "codec_names", "get_codec", "register_codec", "rs_codec",
    "solve_decode", "LRC_10_2_2",
]
