"""LRC(10,2,2): locally repairable code with 5-read single-shard repair.

Layout (total 14 shards — same shard-file count and extensions as
RS(10,4), so every placement/heartbeat/scrub surface carries it
unchanged):

    shards 0-4   data, local group A
    shards 5-9   data, local group B
    shard  10    local parity of group A  (XOR of shards 0-4)
    shard  11    local parity of group B  (XOR of shards 5-9)
    shards 12-13 global parities          (Cauchy rows over all data)

Repair cost: a lost shard inside a group is the XOR of the 5 other
group members — 5 reads instead of RS's 10 (arxiv 1412.3022's local
reconstruction property).  A lost global parity re-encodes from the
10 data shards.

Tolerance: ANY 3 simultaneous losses decode (same-group losses fall
back to the global parities, whose 2x10 Cauchy rows have every minor
nonsingular — the arxiv 1611.09968 Cauchy MDS construction; the
property test verifies all C(14,3)=364 patterns exhaustively against
the numpy oracle), and the structured pattern of one loss per local
group plus BOTH globals (4 losses) also decodes.  Patterns the code
cannot express (e.g. 4 data shards of one group) raise cleanly from
the generic solver in base.py.

Trade: RS(10,4) survives any 4 losses at 10-read repair; LRC(10,2,2)
guarantees any 3 (and favorable 4s) at 5-read repair with the same
1.4x storage overhead.  At production scale rebuild bandwidth
dominates (arxiv 1309.0186), which is why this codec exists.
"""

from __future__ import annotations

import numpy as np

from ..ops import gf256
from .base import Codec, LocalGroup, register_codec

# Shard-id layout constants (documented above).
GROUP_A = LocalGroup(data=(0, 1, 2, 3, 4), parity=10)
GROUP_B = LocalGroup(data=(5, 6, 7, 8, 9), parity=11)
GLOBALS = (12, 13)


def lrc_matrix(data_shards: int = 10,
               groups: tuple[LocalGroup, ...] = (GROUP_A, GROUP_B),
               global_rows: tuple[int, ...] = GLOBALS) -> np.ndarray:
    """Systematic LRC generator: identity, XOR local-parity rows, then
    Cauchy global rows m[r, c] = 1/(r ^ c) — r >= total-2 > c keeps
    r ^ c nonzero, and Cauchy minors are all nonsingular, which is
    what makes two same-group losses globally decodable."""
    total = data_shards + len(groups) + len(global_rows)
    m = np.zeros((total, data_shards), dtype=np.uint8)
    m[:data_shards] = gf256.mat_identity(data_shards)
    for g in groups:
        m[g.parity, list(g.data)] = 1
    for r in global_rows:
        for c in range(data_shards):
            m[r, c] = gf256.gf_inv(r ^ c)
    return m


LRC_10_2_2 = register_codec(Codec(
    "lrc", lrc_matrix(), data_shards=10,
    locality=(GROUP_A, GROUP_B), tolerance=3))
