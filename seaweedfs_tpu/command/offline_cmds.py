"""Offline volume-file subcommands: fix / compact / export
(reference: weed/command/fix.go, compact.go, export.go).

These operate directly on `.dat`/`.idx` files with no servers running —
the same administrative escape hatches the reference ships.
`backup` (incremental pull from a live volume server) lives in
offline_backup.py.
"""

from __future__ import annotations

import os
import sys
import tarfile
import time

from . import Command, Flags, register


def _volume_base(flags: Flags) -> str:
    d = flags.get("dir", ".")
    collection = flags.get("collection", "")
    vid = flags.get_int("volumeId", -1)
    if vid < 0:
        print("-volumeId is required", file=sys.stderr)
        raise SystemExit(2)
    name = f"{collection}_{vid}" if collection else str(vid)
    return os.path.join(d, name)


def run_fix(flags: Flags, args: list[str]) -> int:
    """Regenerate .idx from .dat (command/fix.go)."""
    from ..storage.volume_scanner import generate_idx_from_dat
    base = _volume_base(flags)
    count = generate_idx_from_dat(base + ".dat", base + ".idx")
    print(f"wrote {base}.idx ({count} entries)")
    return 0


def run_compact(flags: Flags, args: list[str]) -> int:
    """Offline vacuum: copy live needles into fresh .dat/.idx
    (command/compact.go)."""
    from ..storage.vacuum import commit_compact, compact
    from ..storage.volume import Volume
    base = _volume_base(flags)
    vol = Volume(flags.get("dir", "."), flags.get("collection", ""),
                 flags.get_int("volumeId"))
    try:
        before = vol.dat_size()
        snapshot = compact(vol)
        commit_compact(vol, snapshot)
        print(f"compacted {base}.dat: {before} -> {vol.dat_size()} bytes")
    finally:
        vol.close()
    return 0


def run_export(flags: Flags, args: list[str]) -> int:
    """Export live needles as a .tar, or list them with -fileNameFormat=
    none (command/export.go)."""
    from ..storage.volume_scanner import scan_volume_file
    base = _volume_base(flags)
    out_path = flags.get("o", "")
    newer_than = flags.get("newer", "")
    newer_ns = 0
    if newer_than:
        newer_ns = int(time.mktime(
            time.strptime(newer_than, "%Y-%m-%d %H:%M:%S"))) * 10**9
    tar = tarfile.open(out_path, "w") if out_path else None
    count = 0
    # Append order is authoritative: the newest record per id wins, and a
    # tombstone (size<=0) erases any earlier version (same liveness rule
    # `weed fix` uses to rebuild the .idx).
    latest: dict[int, tuple] = {}
    for needle, offset, total in scan_volume_file(base + ".dat"):
        if needle.size <= 0:
            latest.pop(needle.id, None)
        else:
            latest[needle.id] = (needle, offset, total)
    try:
        for needle, offset, _total in latest.values():
            if newer_ns and needle.append_at_ns < newer_ns:
                continue
            name = (needle.name.decode("utf-8", "replace")
                    if needle.name else f"{needle.id:x}")
            if needle.is_compressed() and not name.endswith(".gz"):
                # gzip-stored needle: export the stored bytes honestly
                # (command/export.go appends .gz the same way)
                name += ".gz"
            if tar is not None:
                info = tarfile.TarInfo(name=name)
                info.size = len(needle.data)
                info.mtime = (needle.append_at_ns // 10**9) or \
                    int(time.time())
                import io
                tar.addfile(info, io.BytesIO(needle.data))
            else:
                print(f"{needle.id:x}\t{name}\t{len(needle.data)}\t"
                      f"offset={offset}")
            count += 1
    finally:
        if tar is not None:
            tar.close()
    dest = out_path or "stdout"
    print(f"exported {count} files from {base}.dat to {dest}",
          file=sys.stderr)
    return 0


register(Command("fix", "fix -dir=/data -volumeId=3 [-collection=c]",
                 "rebuild the .idx by scanning the .dat", run_fix))
register(Command("compact", "compact -dir=/data -volumeId=3",
                 "offline vacuum of one volume", run_compact))
register(Command("export",
                 "export -dir=/data -volumeId=3 -o=vol.tar [-newer='...']",
                 "export live needles to tar / listing", run_export))
