"""CLI command registry + dispatcher (reference: weed/command/command.go:10-32,
weed/weed.go:38-80).

Every subcommand registers a `Command(name, usage, help, run)`; `main`
dispatches `weed <name> [flags]`.  Commands accept Go-style single-dash
flags (`-port 9333` or `-port=9333`) like the reference so existing muscle
memory and scripts carry over.
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass, field
from typing import Callable

from ..utils import glog


@dataclass
class Command:
    name: str
    usage: str
    short: str
    run: Callable[["Flags", list[str]], int]
    flag_defs: dict[str, tuple[str, str]] = field(default_factory=dict)
    # flag -> (default, help); all flags parse as strings, converted by use


class Flags:
    """Parsed `-key value` / `-key=value` flags with typed getters."""

    def __init__(self, values: dict[str, str]):
        self._v = values

    def get(self, key: str, default: str = "") -> str:
        return self._v.get(key, default)

    def get_int(self, key: str, default: int = 0) -> int:
        val = self._v.get(key)
        return int(val) if val not in (None, "") else default

    def get_float(self, key: str, default: float = 0.0) -> float:
        val = self._v.get(key)
        return float(val) if val not in (None, "") else default

    def get_bool(self, key: str, default: bool = False) -> bool:
        val = self._v.get(key)
        if val is None:
            return default
        return val.lower() in ("", "1", "true", "yes", "on")

    def __contains__(self, key: str) -> bool:
        return key in self._v


def parse_flags(args: list[str]) -> tuple[Flags, list[str]]:
    flags: dict[str, str] = {}
    rest: list[str] = []
    i = 0
    while i < len(args):
        a = args[i]
        if a == "--":
            rest.extend(args[i + 1:])
            break
        if a.startswith("-") and len(a) > 1 and not a[1].isdigit():
            key = a.lstrip("-")
            if "=" in key:
                key, val = key.split("=", 1)
                flags[key] = val
            elif i + 1 < len(args) and not args[i + 1].startswith("-"):
                flags[key] = args[i + 1]
                i += 1
            else:
                flags[key] = ""  # bare boolean flag
        else:
            rest.append(a)
        i += 1
    return Flags(flags), rest


COMMANDS: dict[str, Command] = {}


def register(cmd: Command) -> None:
    COMMANDS[cmd.name] = cmd


def _load_all() -> None:
    # Import for registration side effects.
    from . import benchmark_cmd  # noqa: F401
    from . import client_cmds  # noqa: F401
    from . import mount_cmd  # noqa: F401
    from . import offline_cmds  # noqa: F401
    from . import replication_cmds  # noqa: F401
    from . import servers  # noqa: F401


def usage() -> str:
    _load_all()
    lines = ["usage: weed <command> [flags] [args]", "", "commands:"]
    for name in sorted(COMMANDS):
        lines.append(f"  {name:<18} {COMMANDS[name].short}")
    lines += [
        "",
        "global flags (any command):",
        "  -v <level>            glog verbosity (glog.v(n) gates; "
        "env WEED_V)",
        "  -events.file <path>   append cluster events as JSONL "
        "(journal persistence)",
        "  -events.file.max_mb <mb> / -events.file.keep <n>   rotate "
        "the JSONL sink by size, keeping n rotated files",
        "  -events.buffer <n>    event ring capacity (default 2048); "
        "-events=false unmounts /debug/events + /cluster/events",
        "  -debug.traces / -debug.faults   mount /debug/traces and "
        "/debug/faults",
        "  -pprof                mount /debug/pprof + start the "
        "always-on continuous profiler",
        "  -pprof.hz / -pprof.window       sampler rate (default 19) "
        "and ring-window seconds (default 60)",
        "  -lock.meter=false / -phases=false   disarm lock-contention "
        "metering / the request phase ledger",
    ]
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    _load_all()
    if not argv or argv[0] in ("-h", "-help", "--help", "help"):
        print(usage())
        return 0
    name, args = argv[0], argv[1:]
    cmd = COMMANDS.get(name)
    if cmd is None:
        print(f"unknown command {name!r}\n\n{usage()}", file=sys.stderr)
        return 2
    flags, rest = parse_flags(args)
    # Global -v <level> wires glog verbosity on every command (server
    # roles included) so `glog.v(n)` gates actually fire; without the
    # flag the WEED_V env still applies (setup's None path) instead of
    # being clobbered to 0.
    glog.setup(verbosity=flags.get_int("v", 0) if "v" in flags
               else None)
    # Offset width flavor: the reference's 5BytesOffset BUILD tag
    # (storage/types/offset_5bytes.go) as a process-wide config —
    # `-offsetBytes=5` on any command, or WEED_OFFSET_BYTES=5.
    offset_bytes = flags.get_int(
        "offsetBytes", int(os.environ.get("WEED_OFFSET_BYTES", "4")))
    if offset_bytes != 4:
        from ..core.types import set_offset_flavor
        set_offset_flavor(offset_bytes)
    # -cpuprofile/-memprofile on any subcommand (grace.SetupProfiling):
    # begin profiling now, dump at process exit.
    if flags.get("cpuprofile") or flags.get("memprofile"):
        from ..utils.pprof import setup_profiling
        setup_profiling(flags.get("cpuprofile", ""),
                        flags.get("memprofile", ""))
    # Distributed-tracing knobs, process-wide on any server command
    # (trace/tracer.py reads these env vars dynamically; flags just set
    # them before servers construct):  -debug.traces mounts the
    # /debug/traces endpoint (operator opt-in, like pprof);
    # -trace.sample / -trace.slowMs tune head sampling and the
    # always-sample slow threshold; -trace=false disables recording.
    if flags.get_bool("debug.traces", False):
        os.environ["SEAWEEDFS_TPU_TRACES"] = "1"
    if "trace" in flags and not flags.get_bool("trace", True):
        os.environ["SEAWEEDFS_TPU_TRACE"] = "0"
    if flags.get("trace.sample"):
        os.environ["SEAWEEDFS_TPU_TRACE_SAMPLE"] = flags.get("trace.sample")
    if flags.get("trace.slowMs"):
        os.environ["SEAWEEDFS_TPU_TRACE_SLOW_MS"] = flags.get("trace.slowMs")
    # Time-attribution plane knobs (utils/pprof.py, stats/contention,
    # stats/phases read these when servers construct): -pprof mounts
    # the /debug/pprof surface AND starts the always-on continuous
    # profiler; -pprof.hz / -pprof.window tune its sample rate and
    # ring-window size; -pprof.continuous=false keeps the routes but
    # not the sampler; -lock.meter=false and -phases=false disarm
    # lock metering / the per-request phase ledger (the overhead-bench
    # toggles — both default on).
    if flags.get_bool("pprof", False):
        os.environ["SEAWEEDFS_TPU_PPROF"] = "1"
    if flags.get("pprof.hz"):
        os.environ["SEAWEEDFS_TPU_PPROF_HZ"] = flags.get("pprof.hz")
    if flags.get("pprof.window"):
        os.environ["SEAWEEDFS_TPU_PPROF_WINDOW"] = \
            flags.get("pprof.window")
    if "pprof.continuous" in flags and \
            not flags.get_bool("pprof.continuous", True):
        os.environ["SEAWEEDFS_TPU_PPROF_CONTINUOUS"] = "0"
    if "lock.meter" in flags and not flags.get_bool("lock.meter", True):
        os.environ["SEAWEEDFS_TPU_LOCK_METER"] = "0"
        from ..stats import contention
        contention.ENABLED = False
    if "phases" in flags and not flags.get_bool("phases", True):
        os.environ["SEAWEEDFS_TPU_PHASES"] = "0"
        from ..stats import phases
        phases.ENABLED = False
    # Fault-injection / resilience knobs (fault/registry.py and
    # cluster/resilience.py read these env vars when the first server
    # constructs — after this block):  -faults "point=spec;..." arms
    # fault points at boot AND mounts /debug/faults; -debug.faults
    # mounts the endpoint unarmed (runtime arming via fault.set);
    # -faults.seed replays a probabilistic chaos run;
    # -breaker.threshold / -breaker.cooldown tune the per-host circuit
    # breaker in the rpc client pool (threshold 0 disables it).
    if flags.get("faults"):
        os.environ["SEAWEEDFS_TPU_FAULTS"] = flags.get("faults")
    elif flags.get_bool("debug.faults", False):
        os.environ["SEAWEEDFS_TPU_FAULTS"] = ""
    if flags.get("faults.seed"):
        os.environ["SEAWEEDFS_TPU_FAULTS_SEED"] = \
            flags.get("faults.seed")
    if flags.get("breaker.threshold"):
        os.environ["SEAWEEDFS_TPU_BREAKER_THRESHOLD"] = \
            flags.get("breaker.threshold")
    if flags.get("breaker.cooldown"):
        os.environ["SEAWEEDFS_TPU_BREAKER_COOLDOWN"] = \
            flags.get("breaker.cooldown")
    # Event-journal knobs (events/journal.py reads these when servers
    # construct):  -events.file appends every event as a JSONL line
    # (durable timeline beyond the in-memory ring); -events.buffer
    # sizes the ring; -events=false is the kill switch that also
    # unmounts /debug/events.
    if flags.get("events.file"):
        os.environ["SEAWEEDFS_TPU_EVENTS_FILE"] = \
            flags.get("events.file")
    if flags.get("events.buffer"):
        os.environ["SEAWEEDFS_TPU_EVENTS_BUFFER"] = \
            flags.get("events.buffer")
    # -events.file.max_mb / -events.file.keep: size-based rotation of
    # the JSONL sink (path -> path.1 -> ... -> path.N, keep N).
    if flags.get("events.file.max_mb"):
        os.environ["SEAWEEDFS_TPU_EVENTS_FILE_MAX_MB"] = \
            flags.get("events.file.max_mb")
    if flags.get("events.file.keep"):
        os.environ["SEAWEEDFS_TPU_EVENTS_FILE_KEEP"] = \
            flags.get("events.file.keep")
    if "events" in flags and not flags.get_bool("events", True):
        os.environ["SEAWEEDFS_TPU_EVENTS"] = "0"
    # Device roofline kill switch (stats/roofline.py reads it at
    # import and via set_armed): -roofline=false disarms per-kernel
    # work accounting and the pipeline occupancy recorder — the
    # disarmed path is a single flag check per kernel call.
    if "roofline" in flags and not flags.get_bool("roofline", True):
        os.environ["SEAWEEDFS_TPU_ROOFLINE"] = "0"
        from ..stats import roofline
        roofline.set_armed(False)
    # Wire-flow budget knobs (stats/flows.py reads these lazily):
    # -flows.budget declares per-purpose bandwidth ceilings
    # ("repair.fetch=50MB/s,rlog.ship=10MB/s" — 1024-based units,
    # "/s" optional); a sustained breach emits a flows.budget event
    # and a /cluster/healthz warning.  -flows.sustain sets how many
    # seconds over the ceiling count as sustained (default 2).
    if flags.get("flows.budget"):
        os.environ["SEAWEEDFS_TPU_FLOWS_BUDGET"] = \
            flags.get("flows.budget")
    if flags.get("flows.sustain"):
        os.environ["SEAWEEDFS_TPU_FLOWS_SUSTAIN"] = \
            flags.get("flows.sustain")
    # Every cluster-dialing command — servers AND clients (upload,
    # shell, mount, …) — goes through the TLS plane when security.toml
    # configures [grpc.client], matching the reference where each
    # command's gRPC dials go through security.LoadClientTLS.  A broken
    # security.toml fails closed with a message; exempt are the
    # commands needed to repair it and the offline local-file tools
    # that never dial the cluster.
    if name not in ("scaffold", "version", "fix", "compact", "export"):
        from ..utils.security import (install_cluster_tls,
                                      security_configuration)
        try:
            install_cluster_tls(security_configuration())
        except Exception as e:  # noqa: BLE001 — bad TOML / cert paths
            print(f"security.toml: {e}\n(fix it, or regenerate with "
                  f"`weed scaffold -config=security`)", file=sys.stderr)
            return 2
    try:
        return cmd.run(flags, rest)
    except KeyboardInterrupt:
        return 130
