"""weed benchmark: write/read load generator with latency stats.

Reference: weed/command/benchmark.go:26-141 (write then random read
via assign+upload against a live master, concurrency workers,
latency percentiles printed by printStats :434, synthetic payloads
:523).

The reference's goroutine workers share one multi-core Go process;
Python threads share the GIL, so `-procs=K` (default 4 when c >= 8)
forks K worker processes each running c/K client threads — the same
aggregate concurrency with real CPU parallelism.  `-procs=1` keeps
everything in-process (used by tests).
"""

from __future__ import annotations

import random
import threading
import time

from . import Command, Flags, register


class _Stats:
    def __init__(self) -> None:
        self.latencies_ms: list[float] = []
        self.bytes = 0
        self.errors = 0
        self.lock = threading.Lock()

    def add(self, seconds: float, nbytes: int) -> None:
        with self.lock:
            self.latencies_ms.append(seconds * 1000.0)
            self.bytes += nbytes

    def error(self) -> None:
        with self.lock:
            self.errors += 1

    def report(self, title: str, wall: float,
               cpu: dict | None = None) -> dict:
        lat = sorted(self.latencies_ms)
        n = len(lat)

        def pct(p: float) -> float:
            return lat[min(n - 1, int(n * p))] if n else 0.0
        out = {
            "title": title, "requests": n, "errors": self.errors,
            "seconds": round(wall, 3),
            "req_per_sec": round(n / wall, 2) if wall else 0.0,
            "mb_per_sec": round(self.bytes / wall / 1e6, 2)
            if wall else 0.0,
            "latency_ms": {
                "avg": round(sum(lat) / n, 2) if n else 0.0,
                "p50": round(pct(0.50), 2), "p90": round(pct(0.90), 2),
                "p99": round(pct(0.99), 2),
                "max": round(lat[-1], 2) if n else 0.0,
            },
        }
        if cpu is not None:
            total = cpu.get("client_s", 0.0) + cpu.get("server_s", 0.0)
            out["cpu"] = {
                "client_s": round(cpu.get("client_s", 0.0), 3),
                "server_s": round(cpu.get("server_s", 0.0), 3),
                "total_s": round(total, 3),
                "req_per_core_sec": round(n / total, 1)
                if total > 0 else 0.0,
                "cpu_us_per_req": round(total / n * 1e6, 1)
                if n else 0.0,
            }
        print(f"\n--- {title} ---")
        print(f"requests      {n}  (errors {self.errors})")
        print(f"time          {out['seconds']} s")
        print(f"throughput    {out['req_per_sec']} req/s, "
              f"{out['mb_per_sec']} MB/s")
        lm = out["latency_ms"]
        print(f"latency ms    avg {lm['avg']}  p50 {lm['p50']}  "
              f"p90 {lm['p90']}  p99 {lm['p99']}  max {lm['max']}")
        if cpu is not None and out.get("cpu"):
            c = out["cpu"]
            print(f"cpu           client {c['client_s']}s + servers "
                  f"{c['server_s']}s = {c['total_s']}s  ->  "
                  f"{c['req_per_core_sec']} req/core-sec  "
                  f"({c['cpu_us_per_req']} us CPU/req)")
        return out


def _mp_worker(outq, barrier, master: str, phase: str, count: int,
               size: int, collection: str, nthreads: int,
               fids_in: list[str], seed: int) -> None:
    """One forked load process: nthreads client threads, own stats."""
    from ..cluster.client import WeedClient
    client = WeedClient(master)
    payload = random.Random(7).randbytes(size)
    stats = _Stats()
    fids: list[str] = []
    fid_lock = threading.Lock()

    def w_write(c: int) -> None:
        for _ in range(c):
            t0 = time.perf_counter()
            try:
                fid = client.upload_data(payload, collection=collection)
            except Exception:  # noqa: BLE001 — count, keep loading
                stats.error()
                continue
            stats.add(time.perf_counter() - t0, size)
            with fid_lock:
                fids.append(fid)

    def w_read(c: int, rng: random.Random) -> None:
        for _ in range(c):
            fid = rng.choice(fids_in)
            t0 = time.perf_counter()
            try:
                data = client.download(fid)
            except Exception:  # noqa: BLE001
                stats.error()
                continue
            stats.add(time.perf_counter() - t0, len(data))

    per = count // nthreads
    counts = [per + (1 if i < count % nthreads else 0)
              for i in range(nthreads)]
    if phase == "write":
        threads = [threading.Thread(target=w_write, args=(c,), daemon=True)
                   for c in counts if c]
    else:
        threads = [threading.Thread(
            target=w_read, args=(c, random.Random(seed * 1000 + i)),
            daemon=True) for i, c in enumerate(counts) if c]
    barrier.wait()
    import resource
    ru0 = resource.getrusage(resource.RUSAGE_SELF)
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ru1 = resource.getrusage(resource.RUSAGE_SELF)
    outq.put({"lat": stats.latencies_ms, "bytes": stats.bytes,
              "errors": stats.errors, "fids": fids,
              "wall": time.perf_counter() - t0,
              "cpu": (ru1.ru_utime + ru1.ru_stime)
              - (ru0.ru_utime + ru0.ru_stime)})


def _server_cpus(master: str) -> dict[int, float]:
    """pid -> cpu_seconds for every reachable server process (master +
    volume servers from /vol/list).  Keyed by pid so co-located roles
    (weed server all-in-one, in-process tests) are never double-counted.
    The per-request CPU breakdown is what makes the reference's
    multi-core req/s comparable to a 1-core run (BASELINE.md)."""
    from ..cluster import rpc
    out: dict[int, float] = {}
    try:
        st = rpc.call(f"{master}/cluster/status")
        if "pid" in st:
            out[st["pid"]] = st["cpu_seconds"]
    except Exception:  # noqa: BLE001 — cpu sampling is best-effort
        pass
    try:
        vl = rpc.call(f"{master}/vol/list")
        urls = {n["url"]
                for dc in vl.get("topology", {}).get("data_centers", [])
                for rack in dc.get("racks", [])
                for n in rack.get("nodes", [])}
        for u in urls:
            try:
                st = rpc.call(f"http://{u}/admin/status")
                if "pid" in st:
                    out[st["pid"]] = st["cpu_seconds"]
            except Exception:  # noqa: BLE001
                pass
    except Exception:  # noqa: BLE001
        pass
    return out


def _cpu_delta(before: dict[int, float],
               after: dict[int, float]) -> float:
    return sum(after[pid] - before[pid]
               for pid in after if pid in before)


def run_benchmark(flags: Flags, args: list[str],
                  reports: list | None = None) -> int:
    from ..cluster.client import WeedClient
    master = flags.get("master", "127.0.0.1:9333")
    master = master if master.startswith("http") else f"http://{master}"
    n = flags.get_int("n", 1024)
    size = flags.get_int("size", 1024)
    concurrency = flags.get_int("c", 16)
    procs = flags.get_int("procs", 4 if concurrency >= 8 else 1)
    do_write = flags.get("write", "true").lower() != "false"
    do_read = flags.get("read", "true").lower() != "false"
    sample_cpu = flags.get("cpu", "true").lower() != "false"
    collection = flags.get("collection", "")
    if procs > 1:
        return _run_benchmark_mp(master, n, size, concurrency, procs,
                                 do_write, do_read, collection, reports,
                                 sample_cpu)
    client = WeedClient(master)
    payload = random.Random(7).randbytes(size)
    fids: list[str] = []
    fid_lock = threading.Lock()

    def worker_write(count: int, stats: _Stats) -> None:
        for _ in range(count):
            t0 = time.perf_counter()
            try:
                fid = client.upload_data(payload,
                                         collection=collection)
            except Exception:  # noqa: BLE001 — count, keep loading
                stats.error()
                continue
            stats.add(time.perf_counter() - t0, size)
            with fid_lock:
                fids.append(fid)

    def worker_read(count: int, stats: _Stats,
                    local_rng: random.Random) -> None:
        for _ in range(count):
            with fid_lock:
                fid = local_rng.choice(fids)
            t0 = time.perf_counter()
            try:
                data = client.download(fid)
            except Exception:  # noqa: BLE001
                stats.error()
                continue
            stats.add(time.perf_counter() - t0, len(data))

    def run_phase(fn, title: str, extra_args=()) -> None:
        import resource
        stats = _Stats()
        per = n // concurrency
        counts = [per + (1 if i < n % concurrency else 0)
                  for i in range(concurrency)]
        threads = [threading.Thread(
            target=fn, args=(c, stats, *extra_args), daemon=True)
            for c in counts if c]
        import os
        srv0 = _server_cpus(master) if sample_cpu else {}
        ru0 = resource.getrusage(resource.RUSAGE_SELF)
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        cpu = None
        if sample_cpu:
            ru1 = resource.getrusage(resource.RUSAGE_SELF)
            srv1 = _server_cpus(master)
            client_cpu = (ru1.ru_utime + ru1.ru_stime) \
                - (ru0.ru_utime + ru0.ru_stime)
            me = os.getpid()
            if me in srv1:
                # In-process servers (tests): their CPU is already
                # inside the client rusage; don't count twice.
                srv0.pop(me, None)
                srv1.pop(me, None)
            cpu = {"client_s": client_cpu,
                   "server_s": _cpu_delta(srv0, srv1)}
        out = stats.report(title, wall, cpu)
        if reports is not None:
            reports.append(out)

    print(f"benchmarking {master}: n={n} size={size}B "
          f"concurrency={concurrency}")
    if do_write:
        run_phase(lambda c, s: worker_write(c, s), "write")
    if do_read:
        if not fids:
            print("nothing to read (write phase skipped/failed)")
            return 1
        run_phase(lambda c, s: worker_read(c, s, random.Random()),
                  "random read")
    return 0


def _run_benchmark_mp(master: str, n: int, size: int, concurrency: int,
                      procs: int, do_write: bool, do_read: bool,
                      collection: str, reports: list | None,
                      sample_cpu: bool = True) -> int:
    """Spawn `procs` load processes per phase and merge their stats."""
    import multiprocessing as mp
    ctx = mp.get_context("spawn")  # safe even if the parent touched jax
    nthreads = max(1, concurrency // procs)

    def run_phase(phase: str, fids_in: list[str]) -> list[str]:
        outq = ctx.Queue()
        per = n // procs
        counts = [c for c in
                  (per + (1 if i < n % procs else 0)
                   for i in range(procs)) if c]
        # Barrier parties must match the workers actually spawned, or a
        # small -n with zero-count slots would deadlock everyone.
        barrier = ctx.Barrier(len(counts) + 1)
        workers = [ctx.Process(
            target=_mp_worker,
            args=(outq, barrier, master, phase, c, size, collection,
                  nthreads, fids_in, i), daemon=True)
            for i, c in enumerate(counts)]
        for w in workers:
            w.start()
        barrier.wait()  # everyone imported and connected; go
        srv0 = _server_cpus(master) if sample_cpu else {}
        t0 = time.perf_counter()
        stats = _Stats()
        fids: list[str] = []
        client_cpu = 0.0
        for _ in workers:
            out = outq.get()
            stats.latencies_ms.extend(out["lat"])
            stats.bytes += out["bytes"]
            stats.errors += out["errors"]
            fids.extend(out["fids"])
            client_cpu += out.get("cpu", 0.0)
        wall = time.perf_counter() - t0
        for w in workers:
            w.join()
        cpu = None
        if sample_cpu:
            cpu = {"client_s": client_cpu,
                   "server_s": _cpu_delta(srv0, _server_cpus(master))}
        title = "write" if phase == "write" else "random read"
        rep = stats.report(f"{title} ({procs} procs x "
                           f"{nthreads} threads)", wall, cpu)
        if reports is not None:
            reports.append(rep)
        return fids

    print(f"benchmarking {master}: n={n} size={size}B "
          f"concurrency={concurrency} procs={procs}")
    fids: list[str] = []
    if do_write:
        fids = run_phase("write", [])
    if do_read:
        if not fids:
            print("nothing to read (write phase skipped/failed)")
            return 1
        run_phase("read", fids)
    return 0


register(Command(
    "benchmark",
    "benchmark -master=host:9333 -n=1024 -size=1024 -c=16 -procs=4",
    "write/read load test against a cluster", run_benchmark))
