"""weed benchmark: write/read load generator with latency stats.

Reference: weed/command/benchmark.go:26-141 (write then random read
via assign+upload against a live master, concurrency workers,
latency percentiles printed by printStats :434, synthetic payloads
:523).

The reference's goroutine workers share one multi-core Go process;
Python threads share the GIL, so `-procs=K` (default 4 when c >= 8)
forks K worker processes each running c/K client threads — the same
aggregate concurrency with real CPU parallelism.  `-procs=1` keeps
everything in-process (used by tests).
"""

from __future__ import annotations

import random
import threading
import time

from . import Command, Flags, register


class _Stats:
    def __init__(self) -> None:
        self.latencies_ms: list[float] = []
        self.bytes = 0
        self.errors = 0
        self.lock = threading.Lock()

    def add(self, seconds: float, nbytes: int) -> None:
        with self.lock:
            self.latencies_ms.append(seconds * 1000.0)
            self.bytes += nbytes

    def error(self) -> None:
        with self.lock:
            self.errors += 1

    def report(self, title: str, wall: float) -> dict:
        lat = sorted(self.latencies_ms)
        n = len(lat)

        def pct(p: float) -> float:
            return lat[min(n - 1, int(n * p))] if n else 0.0
        out = {
            "title": title, "requests": n, "errors": self.errors,
            "seconds": round(wall, 3),
            "req_per_sec": round(n / wall, 2) if wall else 0.0,
            "mb_per_sec": round(self.bytes / wall / 1e6, 2)
            if wall else 0.0,
            "latency_ms": {
                "avg": round(sum(lat) / n, 2) if n else 0.0,
                "p50": round(pct(0.50), 2), "p90": round(pct(0.90), 2),
                "p99": round(pct(0.99), 2),
                "max": round(lat[-1], 2) if n else 0.0,
            },
        }
        print(f"\n--- {title} ---")
        print(f"requests      {n}  (errors {self.errors})")
        print(f"time          {out['seconds']} s")
        print(f"throughput    {out['req_per_sec']} req/s, "
              f"{out['mb_per_sec']} MB/s")
        lm = out["latency_ms"]
        print(f"latency ms    avg {lm['avg']}  p50 {lm['p50']}  "
              f"p90 {lm['p90']}  p99 {lm['p99']}  max {lm['max']}")
        return out


def _mp_worker(outq, barrier, master: str, phase: str, count: int,
               size: int, collection: str, nthreads: int,
               fids_in: list[str], seed: int) -> None:
    """One forked load process: nthreads client threads, own stats."""
    from ..cluster.client import WeedClient
    client = WeedClient(master)
    payload = random.Random(7).randbytes(size)
    stats = _Stats()
    fids: list[str] = []
    fid_lock = threading.Lock()

    def w_write(c: int) -> None:
        for _ in range(c):
            t0 = time.perf_counter()
            try:
                fid = client.upload_data(payload, collection=collection)
            except Exception:  # noqa: BLE001 — count, keep loading
                stats.error()
                continue
            stats.add(time.perf_counter() - t0, size)
            with fid_lock:
                fids.append(fid)

    def w_read(c: int, rng: random.Random) -> None:
        for _ in range(c):
            fid = rng.choice(fids_in)
            t0 = time.perf_counter()
            try:
                data = client.download(fid)
            except Exception:  # noqa: BLE001
                stats.error()
                continue
            stats.add(time.perf_counter() - t0, len(data))

    per = count // nthreads
    counts = [per + (1 if i < count % nthreads else 0)
              for i in range(nthreads)]
    if phase == "write":
        threads = [threading.Thread(target=w_write, args=(c,), daemon=True)
                   for c in counts if c]
    else:
        threads = [threading.Thread(
            target=w_read, args=(c, random.Random(seed * 1000 + i)),
            daemon=True) for i, c in enumerate(counts) if c]
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    outq.put({"lat": stats.latencies_ms, "bytes": stats.bytes,
              "errors": stats.errors, "fids": fids,
              "wall": time.perf_counter() - t0})


def run_benchmark(flags: Flags, args: list[str],
                  reports: list | None = None) -> int:
    from ..cluster.client import WeedClient
    master = flags.get("master", "127.0.0.1:9333")
    master = master if master.startswith("http") else f"http://{master}"
    n = flags.get_int("n", 1024)
    size = flags.get_int("size", 1024)
    concurrency = flags.get_int("c", 16)
    procs = flags.get_int("procs", 4 if concurrency >= 8 else 1)
    do_write = flags.get("write", "true").lower() != "false"
    do_read = flags.get("read", "true").lower() != "false"
    collection = flags.get("collection", "")
    if procs > 1:
        return _run_benchmark_mp(master, n, size, concurrency, procs,
                                 do_write, do_read, collection, reports)
    client = WeedClient(master)
    payload = random.Random(7).randbytes(size)
    fids: list[str] = []
    fid_lock = threading.Lock()

    def worker_write(count: int, stats: _Stats) -> None:
        for _ in range(count):
            t0 = time.perf_counter()
            try:
                fid = client.upload_data(payload,
                                         collection=collection)
            except Exception:  # noqa: BLE001 — count, keep loading
                stats.error()
                continue
            stats.add(time.perf_counter() - t0, size)
            with fid_lock:
                fids.append(fid)

    def worker_read(count: int, stats: _Stats,
                    local_rng: random.Random) -> None:
        for _ in range(count):
            with fid_lock:
                fid = local_rng.choice(fids)
            t0 = time.perf_counter()
            try:
                data = client.download(fid)
            except Exception:  # noqa: BLE001
                stats.error()
                continue
            stats.add(time.perf_counter() - t0, len(data))

    def run_phase(fn, title: str, extra_args=()) -> None:
        stats = _Stats()
        per = n // concurrency
        counts = [per + (1 if i < n % concurrency else 0)
                  for i in range(concurrency)]
        threads = [threading.Thread(
            target=fn, args=(c, stats, *extra_args), daemon=True)
            for c in counts if c]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        out = stats.report(title, time.perf_counter() - t0)
        if reports is not None:
            reports.append(out)

    print(f"benchmarking {master}: n={n} size={size}B "
          f"concurrency={concurrency}")
    if do_write:
        run_phase(lambda c, s: worker_write(c, s), "write")
    if do_read:
        if not fids:
            print("nothing to read (write phase skipped/failed)")
            return 1
        run_phase(lambda c, s: worker_read(c, s, random.Random()),
                  "random read")
    return 0


def _run_benchmark_mp(master: str, n: int, size: int, concurrency: int,
                      procs: int, do_write: bool, do_read: bool,
                      collection: str,
                      reports: list | None) -> int:
    """Spawn `procs` load processes per phase and merge their stats."""
    import multiprocessing as mp
    ctx = mp.get_context("spawn")  # safe even if the parent touched jax
    nthreads = max(1, concurrency // procs)

    def run_phase(phase: str, fids_in: list[str]) -> list[str]:
        outq = ctx.Queue()
        per = n // procs
        counts = [c for c in
                  (per + (1 if i < n % procs else 0)
                   for i in range(procs)) if c]
        # Barrier parties must match the workers actually spawned, or a
        # small -n with zero-count slots would deadlock everyone.
        barrier = ctx.Barrier(len(counts) + 1)
        workers = [ctx.Process(
            target=_mp_worker,
            args=(outq, barrier, master, phase, c, size, collection,
                  nthreads, fids_in, i), daemon=True)
            for i, c in enumerate(counts)]
        for w in workers:
            w.start()
        barrier.wait()  # everyone imported and connected; go
        t0 = time.perf_counter()
        stats = _Stats()
        fids: list[str] = []
        for _ in workers:
            out = outq.get()
            stats.latencies_ms.extend(out["lat"])
            stats.bytes += out["bytes"]
            stats.errors += out["errors"]
            fids.extend(out["fids"])
        wall = time.perf_counter() - t0
        for w in workers:
            w.join()
        title = "write" if phase == "write" else "random read"
        rep = stats.report(f"{title} ({procs} procs x "
                           f"{nthreads} threads)", wall)
        if reports is not None:
            reports.append(rep)
        return fids

    print(f"benchmarking {master}: n={n} size={size}B "
          f"concurrency={concurrency} procs={procs}")
    fids: list[str] = []
    if do_write:
        fids = run_phase("write", [])
    if do_read:
        if not fids:
            print("nothing to read (write phase skipped/failed)")
            return 1
        run_phase("read", fids)
    return 0


register(Command(
    "benchmark",
    "benchmark -master=host:9333 -n=1024 -size=1024 -c=16 -procs=4",
    "write/read load test against a cluster", run_benchmark))
