"""weed benchmark: write/read load generator with latency stats.

Reference: weed/command/benchmark.go:26-141 (write then random read
via assign+upload against a live master, concurrency workers,
latency percentiles printed by printStats :434, synthetic payloads
:523).
"""

from __future__ import annotations

import random
import threading
import time

from . import Command, Flags, register


class _Stats:
    def __init__(self) -> None:
        self.latencies_ms: list[float] = []
        self.bytes = 0
        self.errors = 0
        self.lock = threading.Lock()

    def add(self, seconds: float, nbytes: int) -> None:
        with self.lock:
            self.latencies_ms.append(seconds * 1000.0)
            self.bytes += nbytes

    def error(self) -> None:
        with self.lock:
            self.errors += 1

    def report(self, title: str, wall: float) -> dict:
        lat = sorted(self.latencies_ms)
        n = len(lat)

        def pct(p: float) -> float:
            return lat[min(n - 1, int(n * p))] if n else 0.0
        out = {
            "title": title, "requests": n, "errors": self.errors,
            "seconds": round(wall, 3),
            "req_per_sec": round(n / wall, 2) if wall else 0.0,
            "mb_per_sec": round(self.bytes / wall / 1e6, 2)
            if wall else 0.0,
            "latency_ms": {
                "avg": round(sum(lat) / n, 2) if n else 0.0,
                "p50": round(pct(0.50), 2), "p90": round(pct(0.90), 2),
                "p99": round(pct(0.99), 2),
                "max": round(lat[-1], 2) if n else 0.0,
            },
        }
        print(f"\n--- {title} ---")
        print(f"requests      {n}  (errors {self.errors})")
        print(f"time          {out['seconds']} s")
        print(f"throughput    {out['req_per_sec']} req/s, "
              f"{out['mb_per_sec']} MB/s")
        lm = out["latency_ms"]
        print(f"latency ms    avg {lm['avg']}  p50 {lm['p50']}  "
              f"p90 {lm['p90']}  p99 {lm['p99']}  max {lm['max']}")
        return out


def run_benchmark(flags: Flags, args: list[str]) -> int:
    from ..cluster.client import WeedClient
    master = flags.get("master", "127.0.0.1:9333")
    master = master if master.startswith("http") else f"http://{master}"
    n = flags.get_int("n", 1024)
    size = flags.get_int("size", 1024)
    concurrency = flags.get_int("c", 16)
    do_write = flags.get("write", "true").lower() != "false"
    do_read = flags.get("read", "true").lower() != "false"
    collection = flags.get("collection", "")
    client = WeedClient(master)
    payload = random.Random(7).randbytes(size)
    fids: list[str] = []
    fid_lock = threading.Lock()

    def worker_write(count: int, stats: _Stats) -> None:
        for _ in range(count):
            t0 = time.perf_counter()
            try:
                fid = client.upload_data(payload,
                                         collection=collection)
            except Exception:  # noqa: BLE001 — count, keep loading
                stats.error()
                continue
            stats.add(time.perf_counter() - t0, size)
            with fid_lock:
                fids.append(fid)

    def worker_read(count: int, stats: _Stats,
                    local_rng: random.Random) -> None:
        for _ in range(count):
            with fid_lock:
                fid = local_rng.choice(fids)
            t0 = time.perf_counter()
            try:
                data = client.download(fid)
            except Exception:  # noqa: BLE001
                stats.error()
                continue
            stats.add(time.perf_counter() - t0, len(data))

    def run_phase(fn, title: str, extra_args=()) -> None:
        stats = _Stats()
        per = n // concurrency
        counts = [per + (1 if i < n % concurrency else 0)
                  for i in range(concurrency)]
        threads = [threading.Thread(
            target=fn, args=(c, stats, *extra_args), daemon=True)
            for c in counts if c]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats.report(title, time.perf_counter() - t0)

    print(f"benchmarking {master}: n={n} size={size}B "
          f"concurrency={concurrency}")
    if do_write:
        run_phase(lambda c, s: worker_write(c, s), "write")
    if do_read:
        if not fids:
            print("nothing to read (write phase skipped/failed)")
            return 1
        run_phase(lambda c, s: worker_read(c, s, random.Random()),
                  "random read")
    return 0


register(Command(
    "benchmark",
    "benchmark -master=host:9333 -n=1024 -size=1024 -c=16",
    "write/read load test against a cluster", run_benchmark))
