"""weed mount: mount the filer as a local FUSE filesystem.

Reference: weed/command/mount.go + weed/filesys/ (bazil FUSE there;
ctypes libfuse here — see mount/fuse_ll.py).
"""

from __future__ import annotations

import os
import sys

from . import Command, Flags, register


def run_mount(flags: Flags, args: list[str]) -> int:
    from ..mount.fuse_ll import FuseMount
    from ..mount.vfs import WFS
    mountpoint = flags.get("dir", "")
    if not mountpoint:
        print("missing -dir=<mountpoint>", file=sys.stderr)
        return 1
    if not os.path.isdir(mountpoint):
        print(f"mountpoint {mountpoint} is not a directory",
              file=sys.stderr)
        return 1
    filer = flags.get("filer", "127.0.0.1:8888")
    filer_url = filer if filer.startswith("http") else f"http://{filer}"
    wfs = WFS(filer_url,
              filer_dir=flags.get("filer.path", "/"),
              collection=flags.get("collection", ""),
              replication=flags.get("replication", ""),
              chunk_size=flags.get_int("chunkSizeLimitMB", 4)
              * 1024 * 1024)
    fm = FuseMount(wfs, mountpoint,
                   allow_other=flags.get_bool("allowOthers"))
    print(f"mounting {filer_url}{wfs.root} at {mountpoint}")
    try:
        fm.mount(foreground=True)
    except KeyboardInterrupt:
        fm.unmount()
    return 0


register(Command(
    "mount", "mount -filer=host:8888 -dir=/mnt/weed [-filer.path=/]",
    "mount the filer as a local FUSE filesystem", run_mount))
