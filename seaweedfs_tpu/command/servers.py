"""Server-role subcommands: master / volume / filer / s3 / server
(reference: weed/command/master.go, volume.go, filer.go, s3.go, server.go).

Each starts the corresponding in-process server object and blocks until
SIGINT/SIGTERM.  `weed server` composes master + volume (+ filer + s3)
in one process, like the reference's all-in-one command.

Global flags every server role honors (parsed by the dispatcher,
command/__init__.py, before the role starts):

  -v <level>          glog verbosity — arms the `glog.v(n)` gates
                      (env WEED_V when the flag is absent)
  -events.file <path> persist the cluster event journal as JSONL
  -events.buffer <n>  event ring capacity; -events=false unmounts the
                      event endpoints
  -flows.budget "purpose=RATE,..."
                      per-purpose bandwidth ceilings for the wire-flow
                      plane (e.g. "repair.fetch=50MB/s"); sustained
                      breaches emit flows.budget events and healthz
                      warnings.  -flows.sustain <s> tunes the breach
                      window (default 2s)
  -debug.traces / -debug.faults / -faults "point=spec;..."
                      observability and fault-injection opt-ins
"""

from __future__ import annotations

import signal
import threading

from ..utils import glog
from . import Command, Flags, register


def _security(component: str):
    """Server SSLContext for `component` from the process-wide
    security.toml (reference: security.LoadServerTLS with the shared
    viper config, weed/security/tls.go).  The client half of the plane
    is installed once by the CLI dispatcher before any command runs.
    Config mistakes (bad client_auth, missing cert files) exit with a
    message instead of a traceback."""
    from ..utils.security import load_server_tls, security_configuration
    try:
        ctx = load_server_tls(security_configuration(), component)
    except Exception as e:  # noqa: BLE001 — bad values / cert paths
        import sys
        print(f"security.toml [grpc.{component}]: {e}", file=sys.stderr)
        raise SystemExit(2) from None
    if ctx is not None:
        glog.infof("serving TLS (security.toml [grpc.%s])", component)
    return ctx


def _transport_flag(flags: Flags) -> str | None:
    """-transport=aio|threads: the role's network core.  `aio` is the
    netcore event loop (readiness-driven accept/read/reap, handlers on
    a bounded worker pool — million-connection front door); `threads`
    is thread-per-connection.  Absent = SEAWEEDFS_TPU_TRANSPORT env,
    else threads."""
    return flags.get("transport") or None


def _slo_flags(flags: Flags) -> dict:
    """-slo.read.p99 (seconds) / -slo.availability (0.999 or 99.9):
    declared objectives for the role's SLO burn engine (stats/slo.py).
    0/absent = undeclared — quantiles and /debug/slow exemplars still
    run, but nothing can burn."""
    return {"slo_read_p99": flags.get_float("slo.read.p99", 0.0) or None,
            "slo_availability":
                flags.get_float("slo.availability", 0.0) or None}


def _wait_forever(servers: list, grace: float | None = None) -> int:
    stop = threading.Event()

    def handler(signum, frame):
        stop.set()

    signal.signal(signal.SIGINT, handler)
    signal.signal(signal.SIGTERM, handler)
    try:
        stop.wait()
    finally:
        # Graceful lifecycle: SIGTERM/SIGINT first DRAINS every role
        # that supports it — refuse new writes (503 + Retry-After so
        # clients fail over), finish in-flight requests up to
        # -shutdown.grace, goodbye the master so it unregisters with
        # no dead-sweep window — and only then tears listeners down.
        for s in servers:
            drain = getattr(s, "drain", None)
            if drain is None:
                continue
            try:
                drain(grace) if grace is not None else drain()
            except Exception as e:  # noqa: BLE001 — still stop below
                glog.warningf("drain failed: %s", e)
        for s in reversed(servers):
            s.stop()
    return 0


def _start_grpc_plane(server_obj, flags: Flags, ip: str,
                      component: str, server_cls_path: str,
                      allow_port_flag: bool = True):
    """Start one wire-compatible gRPC plane on http port + 10000
    (ParseServerToGrpcAddress convention; -grpc.port overrides on the
    primary role, -grpc=false disables).  TLS rides the same
    security.toml [grpc.<component>] section as the HTTPS plane; a
    config mistake exits with a message like _security() does."""
    if not flags.get_bool("grpc", True):
        return None
    import importlib
    try:
        mod_name, cls_name = server_cls_path.rsplit(".", 1)
        cls = getattr(importlib.import_module(mod_name), cls_name)
    except ImportError as e:
        glog.warningf("gRPC plane disabled (grpcio missing: %s)", e)
        return None
    from ..utils.security import (grpc_server_credentials,
                                  security_configuration)
    try:
        creds = grpc_server_credentials(security_configuration(),
                                        component)
    except Exception as e:  # noqa: BLE001 — bad values / cert paths
        import sys
        print(f"security.toml [grpc.{component}]: {e}", file=sys.stderr)
        raise SystemExit(2) from None
    port = flags.get_int("grpc.port", 0) if allow_port_flag else 0
    g = cls(server_obj, host=ip, port=port or None, credentials=creds)
    g.start()
    glog.infof("%s gRPC (%s) at %s", component, cls.SERVICE, g.addr())
    return g


def _start_master_grpc(m, flags: Flags, ip: str,
                       allow_port_flag: bool = True):
    return _start_grpc_plane(
        m, flags, ip, "master",
        "seaweedfs_tpu.pb.master_grpc.MasterGrpcServer",
        allow_port_flag)


def _start_filer_grpc(fs, flags: Flags, ip: str,
                      allow_port_flag: bool = True):
    return _start_grpc_plane(
        fs, flags, ip, "filer",
        "seaweedfs_tpu.pb.filer_grpc.FilerGrpcServer",
        allow_port_flag)


def _start_volume_grpc(vs, flags: Flags, ip: str,
                       allow_port_flag: bool = True):
    return _start_grpc_plane(
        vs, flags, ip, "volume",
        "seaweedfs_tpu.pb.volume_grpc.VolumeGrpcServer",
        allow_port_flag)


def run_master(flags: Flags, args: list[str]) -> int:
    from ..cluster.master import MasterServer as Master
    from ..utils.config import load_configuration
    # -peers=host1:9333,host2:9333 turns on raft HA (raft_server.go).
    peers = [p if p.startswith("http") else f"http://{p}"
             for p in flags.get("peers", "").split(",") if p]
    # master.toml [master.maintenance]: unattended EC/balance lifecycle
    # (master_server.go startAdminScripts).
    mcfg = load_configuration("master")
    m = Master(
        host=flags.get("ip", "127.0.0.1"),
        port=flags.get_int("port", 9333),
        meta_dir=flags.get("mdir") or None,
        volume_size_limit_mb=flags.get_int("volumeSizeLimitMB", 30 * 1024),
        default_replication=flags.get("defaultReplication", "000"),
        garbage_threshold=flags.get_float("garbageThreshold", 0.3),
        peers=peers or None,
        jwt_signing_key=flags.get("jwt.key", ""),
        ssl_context=_security("master"),
        admin_scripts=mcfg.get_string("master.maintenance.scripts"),
        admin_script_interval=60 * mcfg.get_int(
            "master.maintenance.sleep_minutes", 17),
        max_concurrent=flags.get_int("max.concurrent", 0),
        idle_timeout=flags.get_float("idle.timeout", 120.0),
        transport=_transport_flag(flags),
        # -replicate.lag.slo (seconds): cross-cluster mirror lag above
        # which /cluster/healthz degrades (0/absent = no SLO).
        replication_lag_slo=flags.get_float("replicate.lag.slo",
                                            0.0) or None,
        # Data-lifecycle plane: -lifecycle.rules names a policy file
        # (line grammar or TOML) and turns on the leader-side daemon
        # that tiers cold volumes and vacuums expired TTL data;
        # -lifecycle.mbps throttles its tier-upload bandwidth.
        lifecycle_rules=flags.get("lifecycle.rules", ""),
        lifecycle_interval=flags.get_float("lifecycle.interval", 60.0),
        lifecycle_mbps=flags.get_float("lifecycle.mbps", 32.0),
        # Tenancy plane: -tenant.rules names the quota/QoS policy file
        # (line grammar or TOML) — hard quotas reject at /dir/assign,
        # rps/bw limits throttle with 429, weights drive DRR fairness.
        tenant_rules=flags.get("tenant.rules", ""),
        # Geo active/active: -geo.cluster.id names THIS region;
        # -replicate.steer (with -replicate.steer.peer = the peer
        # region's master) reorders /dir/lookup toward the freshest
        # in-SLO replica, refreshed every -replicate.steer.refresh s.
        geo_cluster_id=flags.get("geo.cluster.id", ""),
        # Disjoint vid residue classes per region (e.g. stride=2 with
        # offset 0 on one region, 1 on the other): active/active
        # masters must never mint the same volume id.
        geo_vid_stride=int(flags.get("geo.vid.stride", "1")),
        geo_vid_offset=int(flags.get("geo.vid.offset", "0")),
        steer_peer=(_norm_master(flags.get("replicate.steer.peer"))
                    .removeprefix("http://")
                    if flags.get("replicate.steer.peer") else None),
        steer_reads=flags.get_bool("replicate.steer", False),
        steer_refresh=flags.get_float("replicate.steer.refresh", 2.0),
        # Metadata HA: -filer.shards=N arms the sharded filer plane —
        # registered filers get consistent-hash-on-directory shards
        # with an epoch-fenced primary each and log-replicated
        # followers; 0 (default) leaves filers standalone.
        # -pulseSeconds sets the master's liveness clock: dead-node
        # sweeps run at 2 pulses and a dead shard primary's lease is
        # waited out for 3 — without the flag, failover time is
        # welded to the 5s default.
        filer_shards=flags.get_int("filer.shards", 0),
        pulse_seconds=flags.get_float("pulseSeconds", 5.0),
        # Durability autopilot: -repair arms the leader-side daemon
        # that automatically re-replicates and EC-rebuilds after node
        # loss; -repair.delay is the hysteresis window before a
        # deficit is acted on (default 2x the dead-sweep threshold),
        # -repair.concurrent bounds parallel repairs.
        repair_enabled=flags.get_bool("repair", False),
        repair_delay=flags.get_float("repair.delay", 0.0) or None,
        repair_concurrent=flags.get_int("repair.concurrent", 2),
        **_slo_flags(flags))
    m.start()
    glog.infof("master serving at %s", m.server.url())
    g = _start_master_grpc(m, flags, flags.get("ip", "127.0.0.1"))
    return _wait_forever([m] + ([g] if g else []))


def run_volume(flags: Flags, args: list[str]) -> int:
    from ..cluster.volume_server import VolumeServer
    dirs = [d for d in flags.get("dir", "./data").split(",") if d]
    maxes = [int(x) for x in flags.get("max", "8").split(",")]
    if len(maxes) == 1:
        maxes = maxes * len(dirs)
    vs = VolumeServer(
        master_url=[_norm_master(u) for u in
                    flags.get("mserver", "127.0.0.1:9333").split(",")],
        directories=dirs,
        host=flags.get("ip", "127.0.0.1"),
        port=flags.get_int("port", 8080),
        max_volume_counts=maxes,
        data_center=flags.get("dataCenter", "DefaultDataCenter"),
        rack=flags.get("rack", "DefaultRack"),
        jwt_signing_key=flags.get("jwt.key", ""),
        ssl_context=_security("volume"),
        read_redirect=flags.get_bool("read.redirect", True),
        # Data-integrity knobs: -fsync forces per-write durability
        # (every POST acks only after .dat AND .idx are fsynced);
        # -scrub.mbps bounds the background integrity sweep's disk
        # bandwidth and -scrub.interval its cadence (0 = on-demand
        # only via volume.scrub / POST /admin/scrub).
        fsync=flags.get_bool("fsync", False),
        scrub_mbps=flags.get_float("scrub.mbps", 32.0),
        scrub_interval=flags.get_float("scrub.interval", 3600.0),
        # Overload & lifecycle knobs: -max.concurrent bounds per-lane
        # request concurrency (0 = no shedding), -disk.reserve (MB)
        # flips volumes readonly before ENOSPC, -shutdown.grace bounds
        # the drain wait on SIGTERM, -idle.timeout reaps stalled
        # (slow-loris) connections.
        max_concurrent=flags.get_int("max.concurrent", 0),
        queue_depth=flags.get_int("max.queue", 0) or None,
        shutdown_grace=flags.get_float("shutdown.grace", 30.0),
        disk_reserve_mb=flags.get_float("disk.reserve", 0.0),
        idle_timeout=flags.get_float("idle.timeout", 120.0),
        transport=_transport_flag(flags),
        # -read.sendfile.min: smallest whole-needle GET served by the
        # zero-copy sendfile slice path (0 disables; default 4KB —
        # sendfile is the DEFAULT read path, not a big-read special
        # case).
        sendfile_min=(int(flags.get("read.sendfile.min"))
                      if flags.get("read.sendfile.min") != "" else None),
        # -ec.codec: default erasure codec for /admin/ec/generate —
        # "rs" (reference-compatible RS(10,4)) or "lrc" (LRC(10,2,2),
        # 5-read single-shard repair).
        ec_codec=flags.get("ec.codec", "rs"),
        # Cross-cluster async mirroring: -replicate.peer names the
        # STANDBY cluster's master; every local write/delete journals
        # to a per-volume change log and a background shipper tails it
        # to the peer.  -replicate.collections opts specific
        # collections in ("" or `default` = the default collection);
        # empty = mirror everything.
        replicate_peer=(_norm_master(flags.get("replicate.peer"))
                        if flags.get("replicate.peer") else None),
        replicate_collections=flags.get("replicate.collections", ""),
        replicate_interval=flags.get_float("replicate.interval", 0.5),
        # Geo active/active: -geo.cluster.id names THIS region and
        # turns on the per-volume `.lease` fencing plane (writes at a
        # non-holder forward to the holder; stale-epoch batches 409);
        # -replicate.compress zlib-compresses shipped batches so the
        # rlog.ship flow purpose meters actual WAN bytes.
        geo_cluster_id=flags.get("geo.cluster.id", ""),
        replicate_compress=flags.get_bool("replicate.compress", False),
        # Remote-tier knobs: -tier.cache.mb bounds the read-through
        # block cache for tiered volumes; -tier.promote.hits (>0) turns
        # on auto-promotion — a tiered volume whose cache sees that
        # many distinct reads inside -tier.promote.window seconds is
        # downloaded back local.
        tier_cache_mb=flags.get_float("tier.cache.mb", 64.0),
        tier_promote_hits=flags.get_int("tier.promote.hits", 0),
        tier_promote_window=flags.get_float("tier.promote.window", 60.0),
        # Tenancy plane: same policy file as the master's -tenant.rules
        # — here it drives the per-tenant token buckets and DRR weights
        # on this node's admission lanes.
        tenant_rules=flags.get("tenant.rules", ""),
        # -slo.read.p99 / -slo.availability: declared objectives for
        # the burn engine; exemplars + quantiles run regardless.
        **_slo_flags(flags))
    vs.start()
    glog.infof("volume server serving at %s (dirs %s)",
               vs.server.url(), dirs)
    g = _start_volume_grpc(vs, flags, flags.get("ip", "127.0.0.1"))
    return _wait_forever([vs] + ([g] if g else []),
                         grace=flags.get_float("shutdown.grace", 30.0))


def run_msg_broker(flags: Flags, args: list[str]) -> int:
    from ..messaging.broker import MessageBroker
    filer = flags.get("filer", "127.0.0.1:8888")
    mb = MessageBroker(
        filer if filer.startswith("http") else f"http://{filer}",
        host=flags.get("ip", "127.0.0.1"),
        port=flags.get_int("port", 17777),
        ssl_context=_security("msg_broker"))
    mb.start()
    glog.infof("message broker serving at %s", mb.url())
    g = _start_grpc_plane(
        mb, flags, flags.get("ip", "127.0.0.1"), "msg_broker",
        "seaweedfs_tpu.pb.messaging_grpc.MessagingGrpcServer")
    return _wait_forever([mb] + ([g] if g else []))


def run_filer(flags: Flags, args: list[str]) -> int:
    from ..filer.server import FilerServer
    fs = FilerServer(
        master_url=[_norm_master(u) for u in
                    flags.get("master", "127.0.0.1:9333").split(",")],
        host=flags.get("ip", "127.0.0.1"),
        port=flags.get_int("port", 8888),
        store_path=flags.get("dir") or None,
        collection=flags.get("collection", ""),
        replication=flags.get("defaultReplicaPlacement") or None,
        metrics_port=flags.get_int("metricsPort", 0) or None,
        ssl_context=_security("filer"),
        cipher=flags.get_bool("encryptVolumeData", False),
        transport=_transport_flag(flags),
        # Front-door read/write knobs: -filer.cache.mb bounds the
        # read-through chunk cache; -filer.pack.threshold (bytes, 0 =
        # off) group-commits small uploads into shared needles;
        # -filer.proxy.min (bytes, 0 = off) floors the direct
        # volume→client relay for large single-chunk reads.
        cache_mb=(int(flags.get("filer.cache.mb"))
                  if flags.get("filer.cache.mb") != "" else None),
        pack_threshold=flags.get_int("filer.pack.threshold", 0),
        pack_max_bytes=flags.get_int("filer.pack.max", 1 << 20),
        pack_linger=flags.get_float("filer.pack.linger", 0.008),
        proxy_min=(int(flags.get("filer.proxy.min"))
                   if flags.get("filer.proxy.min") != "" else None),
        # Tenancy plane: -tenant.rules arms the filer's front-door QoS
        # gate; -filer.cache.tenant.mb caps any one tenant's share of
        # the chunk cache (0/absent = no per-tenant cap).
        tenant_rules=flags.get("tenant.rules", ""),
        cache_tenant_mb=(int(flags.get("filer.cache.tenant.mb"))
                         if flags.get("filer.cache.tenant.mb") != ""
                         else None),
        # Metadata-HA plane: the heartbeat cadence to the master (the
        # primary lease TTL is 3 pulses) and where the per-shard
        # journals live (default: <-dir>.shards).
        pulse_seconds=flags.get_float("pulseSeconds", 5.0),
        ha_dir=flags.get("filer.ha.dir") or None,
        **_slo_flags(flags))
    fs.start()
    glog.infof("filer serving at %s", fs.server.url())
    g = _start_filer_grpc(fs, flags, flags.get("ip", "127.0.0.1"))
    return _wait_forever([fs] + ([g] if g else []))


def _s3_identities(config_path: str):
    """Load identities from the reference's JSON config shape
    (s3api/auth_credentials.go); None (no -config flag) lets the
    gateway fall back to filer-backed IAM."""
    import json

    from ..s3api.auth import identities_from_dict
    if not config_path:
        return None
    with open(config_path) as f:
        return identities_from_dict(json.load(f))


def run_s3(flags: Flags, args: list[str]) -> int:
    from ..s3api.server import S3ApiServer
    s3 = S3ApiServer(
        filer_url=_norm_master(flags.get("filer", "127.0.0.1:8888")),
        host=flags.get("ip", "127.0.0.1"),
        port=flags.get_int("port", 8333),
        identities=_s3_identities(flags.get("config")),
        metrics_port=flags.get_int("metricsPort", 0) or None,
        ssl_context=_security("s3"))
    s3.start()
    glog.infof("s3 gateway serving at %s", s3.server.url())
    return _wait_forever([s3])


def run_webdav(flags: Flags, args: list[str]) -> int:
    from ..webdav.server import WebDavServer
    dav = WebDavServer(
        filer_url=_norm_master(flags.get("filer", "127.0.0.1:8888")),
        host=flags.get("ip", "127.0.0.1"),
        port=flags.get_int("port", 7333),
        metrics_port=flags.get_int("metricsPort", 0) or None,
        ssl_context=_security("webdav"))
    dav.start()
    glog.infof("webdav serving at %s", dav.server.url())
    return _wait_forever([dav])


def run_server(flags: Flags, args: list[str]) -> int:
    """All-in-one: master + volume [+ filer [+ s3]]."""
    from ..cluster.master import MasterServer as Master
    from ..cluster.volume_server import VolumeServer
    servers: list = []
    ip = flags.get("ip", "127.0.0.1")
    m = Master(host=ip, port=flags.get_int("master.port", 9333),
               meta_dir=flags.get("mdir") or None,
               volume_size_limit_mb=flags.get_int(
                   "volumeSizeLimitMB", 30 * 1024),
               default_replication=flags.get("defaultReplication", "000"),
               ssl_context=_security("master"),
               lifecycle_rules=flags.get("lifecycle.rules", ""),
               lifecycle_interval=flags.get_float("lifecycle.interval",
                                                  60.0),
               lifecycle_mbps=flags.get_float("lifecycle.mbps", 32.0),
               tenant_rules=flags.get("tenant.rules", ""),
               # Durability autopilot flags mirror the standalone
               # master command.
               repair_enabled=flags.get_bool("repair", False),
               repair_delay=flags.get_float("repair.delay", 0.0)
               or None,
               repair_concurrent=flags.get_int("repair.concurrent", 2),
               # -transport applies to EVERY embedded role, like -slo.*.
               transport=_transport_flag(flags),
               # -slo.* applies to EVERY embedded role, same as the
               # standalone commands — half-declared objectives would
               # silently disable master-side burn.
               **_slo_flags(flags))
    m.start()
    servers.append(m)
    dirs = [d for d in flags.get("dir", "./data").split(",") if d]
    maxes = [int(x) for x in flags.get("volume.max", "8").split(",")]
    if len(maxes) == 1:
        maxes = maxes * len(dirs)
    vs = VolumeServer(master_url=m.server.url(), directories=dirs,
                      host=ip, port=flags.get_int("volume.port", 8080),
                      max_volume_counts=maxes,
                      data_center=flags.get("dataCenter",
                                            "DefaultDataCenter"),
                      rack=flags.get("rack", "DefaultRack"),
                      ssl_context=_security("volume"),
                      fsync=flags.get_bool("fsync", False),
                      scrub_mbps=flags.get_float("scrub.mbps", 32.0),
                      scrub_interval=flags.get_float("scrub.interval",
                                                     3600.0),
                      max_concurrent=flags.get_int("max.concurrent", 0),
                      shutdown_grace=flags.get_float("shutdown.grace",
                                                     30.0),
                      disk_reserve_mb=flags.get_float("disk.reserve",
                                                      0.0),
                      ec_codec=flags.get("ec.codec", "rs"),
                      tier_cache_mb=flags.get_float("tier.cache.mb",
                                                    64.0),
                      tier_promote_hits=flags.get_int(
                          "tier.promote.hits", 0),
                      tier_promote_window=flags.get_float(
                          "tier.promote.window", 60.0),
                      tenant_rules=flags.get("tenant.rules", ""),
                      transport=_transport_flag(flags),
                      **_slo_flags(flags))
    vs.start()
    servers.append(vs)
    glog.infof("master at %s, volume at %s", m.server.url(),
               vs.server.url())
    g = _start_master_grpc(m, flags, ip)
    if g:
        servers.append(g)
    grace = flags.get_float("shutdown.grace", 30.0)
    vg = _start_volume_grpc(vs, flags, ip, allow_port_flag=False)
    if vg:
        servers.append(vg)
    if flags.get_bool("filer", False):
        from ..filer.server import FilerServer
        fs = FilerServer(master_url=m.server.url(), host=ip,
                         port=flags.get_int("filer.port", 8888),
                         store_path=flags.get("filer.dir") or None,
                         transport=_transport_flag(flags),
                         pack_threshold=flags.get_int(
                             "filer.pack.threshold", 0),
                         tenant_rules=flags.get("tenant.rules", ""),
                         ssl_context=_security("filer"))
        fs.start()
        servers.append(fs)
        glog.infof("filer at %s", fs.server.url())
        fg = _start_filer_grpc(fs, flags, ip,
                               allow_port_flag=False)
        if fg:
            servers.append(fg)
        if flags.get_bool("s3", False):
            from ..s3api.server import S3ApiServer
            s3 = S3ApiServer(filer_url=fs.server.url(), host=ip,
                             port=flags.get_int("s3.port", 8333),
                             identities=_s3_identities(
                                 flags.get("s3.config")),
                             ssl_context=_security("s3"))
            s3.start()
            servers.append(s3)
            glog.infof("s3 at %s", s3.server.url())
        if flags.get_bool("webdav", False):
            from ..webdav.server import WebDavServer
            dav = WebDavServer(filer_url=fs.server.url(), host=ip,
                               port=flags.get_int("webdav.port", 7333),
                               ssl_context=_security("webdav"))
            dav.start()
            servers.append(dav)
            glog.infof("webdav at %s", dav.server.url())
    return _wait_forever(servers, grace=grace)


def _norm_master(addr: str) -> str:
    return addr if addr.startswith("http") else f"http://{addr}"


register(Command("master", "master -port=9333 -mdir=/tmp/meta"
                 " [-transport=aio|threads]"
                 " [-replicate.lag.slo=30(s)]"
                 " [-lifecycle.rules=rules.txt]"
                 " [-lifecycle.interval=60] [-lifecycle.mbps=32]"
                 " [-tenant.rules=tenants.txt]"
                 " [-geo.cluster.id=A] [-geo.vid.stride=2]"
                 " [-geo.vid.offset=0] [-replicate.steer]"
                 " [-replicate.steer.peer=peer-master:9333]"
                 " [-replicate.steer.refresh=2]"
                 " [-filer.shards=0] [-pulseSeconds=5]",
                 "start a master server", run_master))
register(Command("volume",
                 "volume -port=8080 -dir=/data -max=8 -mserver=host:9333"
                 " [-transport=aio|threads] [-read.sendfile.min=4096]"
                 " [-fsync] [-scrub.mbps=32] [-scrub.interval=3600]"
                 " [-max.concurrent=0] [-disk.reserve=0(MB)]"
                 " [-shutdown.grace=30] [-ec.codec=rs|lrc]"
                 " [-slo.read.p99=0.05] [-slo.availability=99.9]"
                 " [-replicate.peer=standby-master:9333]"
                 " [-replicate.collections=a,b] [-replicate.interval=0.5]"
                 " [-geo.cluster.id=A] [-replicate.compress]"
                 " [-tier.cache.mb=64] [-tier.promote.hits=0]"
                 " [-tier.promote.window=60] [-tenant.rules=tenants.txt]",
                 "start a volume server", run_volume))
register(Command("filer", "filer -port=8888 -master=host:9333"
                 " [-transport=aio|threads] [-filer.cache.mb=64]"
                 " [-filer.pack.threshold=0(B)] [-filer.pack.max=1048576]"
                 " [-filer.pack.linger=0.008] [-filer.proxy.min=262144]"
                 " [-tenant.rules=tenants.txt]"
                 " [-filer.cache.tenant.mb=0]"
                 " [-pulseSeconds=5] [-filer.ha.dir=...]",
                 "start a filer server", run_filer))
register(Command("msg.broker", "msg.broker -port=17777 -filer=host:8888",
                 "start a pub/sub message broker", run_msg_broker))
register(Command("s3", "s3 -port=8333 -filer=host:8888",
                 "start an S3-compatible gateway", run_s3))
register(Command("webdav", "webdav -port=7333 -filer=host:8888",
                 "start a WebDAV gateway", run_webdav))
register(Command("server",
                 "server -dir=/data -filer=true -s3=true"
                 " [-transport=aio|threads]"
                 " [-s3.config=identities.json]"
                 " [-lifecycle.rules=rules.txt]"
                 " [-tenant.rules=tenants.txt]"
                 " [-tier.cache.mb=64] [-tier.promote.hits=0]",
                 "start master+volume(+filer+s3) in one process",
                 run_server))
