"""Replication subcommands: filer.copy / filer.replicate.

Reference: weed/command/filer_copy.go (local tree -> filer upload) and
filer_replication.go (notification queue -> Replicator -> sink).

The old `filer.sync` polling daemon was removed: cross-cluster
mirroring is now the volume-level change-log shipper (-replicate.peer
on the volume server, replication/rlog.py + shipper.py), which is
durable, idempotent, and cutover-verified — properties the mtime-diff
walk never had.
"""

from __future__ import annotations

import mimetypes
import os
import sys
import time

from . import Command, Flags, register


def _filer_url(flags: Flags, key: str = "filer") -> str:
    addr = flags.get(key, "127.0.0.1:8888")
    return addr if addr.startswith("http") else f"http://{addr}"


def run_filer_copy(flags: Flags, args: list[str]) -> int:
    """filer.copy local_file_or_dir ... /target/dir/"""
    from ..filer.client import FilerProxy
    if len(args) < 2:
        print("usage: filer.copy [-filer=host:8888] src... /dest/dir/",
              file=sys.stderr)
        return 1
    *sources, dest = args
    if not dest.startswith("/"):
        print("destination must be an absolute filer path",
              file=sys.stderr)
        return 1
    proxy = FilerProxy(_filer_url(flags))
    dest = dest.rstrip("/") or "/"
    n = 0
    for src in sources:
        if os.path.isdir(src):
            base = os.path.basename(os.path.abspath(src))
            for root, _dirs, files in os.walk(src):
                rel = os.path.relpath(root, src)
                for fname in files:
                    local = os.path.join(root, fname)
                    remote = "/".join(p for p in (
                        dest, base, "" if rel == "." else rel, fname)
                        if p).replace("//", "/")
                    n += _copy_one(proxy, local, remote)
        elif os.path.isfile(src):
            n += _copy_one(proxy, src,
                           f"{dest}/{os.path.basename(src)}")
        else:
            print(f"skip {src}: not found", file=sys.stderr)
    print(f"copied {n} files to {dest}")
    return 0


def _copy_one(proxy, local: str, remote: str) -> int:
    mime = mimetypes.guess_type(local)[0] or "application/octet-stream"
    # Stream the open file: filer.copy of a multi-GB file must not
    # materialize it (the proxy sends readers under Content-Length and
    # the filer's upload route consumes incrementally).
    with open(local, "rb") as f:
        # fstat the OPEN handle: a path-level stat could disagree with
        # the descriptor under a concurrent replace, declaring a length
        # the body never matches (hung or truncated upload).
        proxy.put(remote, f, mime,
                  length=os.fstat(f.fileno()).st_size)
    return 1


def run_filer_replicate(flags: Flags, args: list[str]) -> int:
    """filer.replicate -filer=... -source.dir=/bucket -sink=<spec>

    Sink specs: filer://host:port/dir, local:///path, s3://host/bucket,
    gcs://bucket/dir, b2://bucket/dir, azure://account/container/dir.
    Consumes the filer's meta stream (notification input) and replays it
    on the sink; checkpoints its offset in the source filer KV."""
    from ..filer.client import FilerProxy
    from ..replication.replicator import Replicator
    from ..replication.sink import sink_for_spec
    src = _filer_url(flags)
    src_dir = flags.get("source.dir", "/")
    spec = flags.get("sink", "")
    if not spec:
        print("missing -sink=<spec>", file=sys.stderr)
        return 1
    scheme = spec.partition("://")[0]
    kw = {}
    if scheme in ("s3", "gcs", "b2"):
        kw = {"access_key": flags.get("s3.access_key", ""),
              "secret_key": flags.get("s3.secret_key", "")}
        if flags.get("s3.region"):
            kw["region"] = flags.get("s3.region")
    elif scheme == "azure":
        kw = {"account_key": flags.get("azure.account_key", "")}
    # -sink.endpoint: point a cloud sink at an emulator or
    # S3-interop proxy instead of the vendor default host.
    if scheme in ("gcs", "b2", "azure") and flags.get("sink.endpoint"):
        kw["endpoint"] = flags.get("sink.endpoint")
    sink = sink_for_spec(spec, **kw)
    repl = Replicator(src, src_dir, sink)
    proxy = FilerProxy(src)
    ck_key = f"replicate.offset.{spec}"
    raw = proxy.kv_get(ck_key)
    offset = int(raw) if raw else 0
    one_shot = flags.get_bool("once")
    interval = flags.get_float("interval", 1.0)
    print(f"replicating {src}{src_dir} -> {spec} from offset {offset}")
    try:
        while True:
            # A transient sink/source error must not kill the daemon:
            # skip the checkpoint and retry the batch next tick.
            try:
                out = proxy.meta_events(since_ns=offset, prefix=src_dir)
                for ev in out["events"]:
                    repl.replicate(ev)
            except Exception as e:  # noqa: BLE001
                print(f"replicate batch failed (will retry): {e}",
                      file=sys.stderr)
                if one_shot:
                    return 1
                time.sleep(interval)
                continue
            if out["last_ns"] > offset:
                offset = out["last_ns"]
                proxy.kv_put(ck_key, str(offset).encode())
            elif one_shot:
                return 0
            else:
                time.sleep(interval)
    except KeyboardInterrupt:
        return 0


register(Command(
    "filer.copy", "filer.copy [-filer=host:8888] src... /dest/dir/",
    "copy local files or directories into the filer", run_filer_copy))
register(Command(
    "filer.replicate",
    "filer.replicate -filer=host:8888 -sink=local:///backup",
    "replicate filer changes to a sink (filer/local/s3/gcs/b2/azure)",
    run_filer_replicate))
