"""Client-side subcommands: upload / download / shell / watch / version /
scaffold (reference: weed/command/upload.go, download.go, shell.go,
watch.go, version.go, scaffold.go).
"""

from __future__ import annotations

import json
import os
import sys
import time
import urllib.request

from .. import __version__
from . import Command, Flags, register


def _master(flags: Flags, key: str = "master") -> str:
    addr = flags.get(key, "127.0.0.1:9333")
    return addr if addr.startswith("http") else f"http://{addr}"


def run_upload(flags: Flags, args: list[str]) -> int:
    """Upload files (or a directory with -dir); prints JSON results like
    the reference (command/upload.go)."""
    from ..cluster.client import WeedClient
    client = WeedClient(_master(flags))
    paths: list[str] = []
    if flags.get("dir"):
        for root, _dirs, files in os.walk(flags.get("dir")):
            paths.extend(os.path.join(root, f) for f in files)
    paths.extend(args)
    if not paths:
        print("nothing to upload: pass files or -dir", file=sys.stderr)
        return 2
    results = []
    for p in paths:
        with open(p, "rb") as f:
            data = f.read()
        res = client.submit(data, collection=flags.get("collection", ""),
                            replication=flags.get("replication") or None,
                            ttl=flags.get("ttl", ""))
        res["fileName"] = os.path.basename(p)
        # submit() passes the full upload dict through, including the
        # bytes cipher_key (b"" when no cipher); hex it for the JSON
        # report instead of crashing json.dumps.
        results.append({k: (v.hex() if isinstance(v, bytes) else v)
                        for k, v in res.items()})
    print(json.dumps(results, indent=2))
    return 0


def run_download(flags: Flags, args: list[str]) -> int:
    """Download fids to -dir (command/download.go)."""
    from ..cluster.client import WeedClient
    client = WeedClient(_master(flags, "server"))
    out_dir = flags.get("dir", ".")
    os.makedirs(out_dir, exist_ok=True)
    for fid in args:
        data = client.download(fid)
        name = fid.replace(",", "_")
        with open(os.path.join(out_dir, name), "wb") as f:
            f.write(data)
        print(f"{fid} -> {name} ({len(data)} bytes)")
    return 0


def run_shell(flags: Flags, args: list[str]) -> int:
    from ..shell.repl import run_shell
    filer = flags.get("filer", "")
    if filer and not filer.startswith("http"):
        filer = f"http://{filer}"
    return run_shell(_master(flags), commands=args or None,
                     filer_url=filer or None)


def run_watch(flags: Flags, args: list[str]) -> int:
    """Tail filer metadata events (command/watch.go) over the filer's
    long-lived push stream — events print the moment they commit; the
    connection redials on filer restarts."""
    from ..filer.client import FilerProxy
    filer = flags.get("filer", "127.0.0.1:8888")
    filer = filer if filer.startswith("http") else f"http://{filer}"
    prefix = flags.get("pathPrefix", "/")
    proxy = FilerProxy(filer)
    since_ns = int(time.time() * 1e9)
    while True:
        try:
            _handle, events = proxy.meta_stream(since_ns=since_ns,
                                                prefix=prefix)
            for ev in events:
                since_ns = max(since_ns, ev.get("ts_ns", since_ns))
                if ev.get("_cursor_only"):
                    continue
                print(json.dumps(ev))
                sys.stdout.flush()
        except KeyboardInterrupt:
            return 130
        except Exception:  # noqa: BLE001 — filer down; redial
            pass
        time.sleep(flags.get_float("interval", 1.0))


def run_version(flags: Flags, args: list[str]) -> int:
    print(f"version {__version__} (seaweedfs-tpu)")
    return 0


SCAFFOLDS = {
    "security": '''\
# security.toml — put in ./ , ~/.seaweedfs/ , or /etc/seaweedfs/
[jwt.signing]
key = ""
expires_after_seconds = 10

[access]
ui = false
white_list = []

# TLS for all cluster RPC (reference weed/security/tls.go): every
# server presents its [grpc.<role>] cert; cluster clients dial with
# [grpc.client].  Leave blank for plaintext.
#
# client_auth: "none" (default) serves ordinary TLS so standard
# end-user clients (curl, aws-cli, davfs2, browsers) can connect;
# "require" additionally demands a CA-signed client certificate — the
# reference's mutual-TLS RequireAndVerifyClientCert — appropriate when
# the port is reachable only by cluster peers.
[grpc]
ca = ""

[grpc.master]
cert = ""
key  = ""
# client_auth = "require"

[grpc.volume]
cert = ""
key  = ""
# client_auth = "require"

[grpc.filer]
cert = ""
key  = ""

[grpc.s3]
cert = ""
key  = ""

[grpc.webdav]
cert = ""
key  = ""

[grpc.msg_broker]
cert = ""
key  = ""

[grpc.client]
cert = ""
key  = ""
''',
    "master": '''\
# master.toml
[master.maintenance]
# periodic scripts, one shell command per line
scripts = """
  ec.encode -fullPercent=95 -quietFor=1h
  ec.rebuild -force
  ec.balance -force
  volume.balance -force
"""
sleep_minutes = 17

[master.sequencer]
type = "memory"   # or "etcd"
''',
    "filer": '''\
# filer.toml
[filer.options]
recursive_delete = false

[memory]
enabled = false

[sqlite]
enabled = true
file = "filer.db"

# Embedded ordered-KV store (the reference's leveldb default):
# log-structured, crash-safe, directory-backed.
[ordered_kv]
enabled = false
dir = "."
''',
    "notification": '''\
# notification.toml — the filer publishes every meta event to the
# first enabled queue; `weed filer.replicate` consumes it.
[notification.log]
enabled = false

[notification.file_queue]
enabled = false
dir = "/tmp/weed_notify"

[notification.kafka]
enabled = false
hosts = "localhost:9092"
topic = "seaweedfs_filer"

[notification.aws_sqs]
enabled = false
region = "us-east-1"
sqs_queue_url = "https://sqs.us-east-1.amazonaws.com/1234/queue"
aws_access_key_id = ""
aws_secret_access_key = ""

[notification.google_pub_sub]
enabled = false
project_id = ""
topic = "seaweedfs_filer"
subscription = ""
google_application_credentials = ""
''',
    "replication": '''\
# replication.toml
[source.filer]
enabled = true
grpcAddress = "localhost:8888"
directory = "/buckets"

[sink.filer]
enabled = false
grpcAddress = "localhost:8889"
directory = "/backup"
replication = ""

[sink.local]
enabled = false
directory = "/backup"
''',
}


def run_backup(flags: Flags, args: list[str]) -> int:
    """weed backup: keep an incremental local copy of one volume
    (command/backup.go + storage/volume_backup.go IncrementalBackup).
    First run copies the whole .dat; later runs fetch only records
    appended since the local copy's newest appendAtNs."""
    from ..cluster import rpc
    from ..storage.volume_backup import (apply_incremental,
                                         last_append_at_ns)
    master = _master(flags)
    vid = flags.get_int("volumeId", 0)
    out_dir = flags.get("dir", ".")
    if not vid:
        print("missing -volumeId", file=sys.stderr)
        return 1
    lookup = rpc.call(f"{master}/dir/lookup?volumeId={vid}")
    locs = lookup.get("locations", [])
    if not locs:
        print(f"volume {vid} has no locations", file=sys.stderr)
        return 1
    node = locs[0]["url"]
    os.makedirs(out_dir, exist_ok=True)
    dat_path = os.path.join(out_dir, f"{vid}.dat")
    idx_path = os.path.join(out_dir, f"{vid}.idx")
    if not os.path.exists(dat_path):
        # Full copy (VolumeCopy's CopyFile path).  The .idx comes FIRST
        # so on a live volume the idx snapshot can never reference
        # offsets past the .dat snapshot's EOF.
        rpc.call_to_file(
            f"http://{node}/admin/volume_file?volume={vid}&ext=.idx",
            idx_path)
        rpc.call_to_file(
            f"http://{node}/admin/volume_file?volume={vid}&ext=.dat",
            dat_path)
        print(f"full backup of volume {vid} -> {dat_path}")
        return 0
    since = last_append_at_ns(dat_path)
    import urllib.request
    url = (f"http://{node}/admin/volume_tail?volume={vid}"
           f"&since_ns={since}")
    applied_total = 0
    while True:
        with urllib.request.urlopen(url, timeout=600) as resp:
            delta = resp.read()
            version = int(resp.headers.get("X-Volume-Version", "3"))
            last = int(resp.headers.get("X-Last-Append-Ns", since))
        if not delta:
            break
        applied_total += apply_incremental(dat_path, idx_path, delta,
                                           version)
        if last <= since:
            break
        since = last
        url = (f"http://{node}/admin/volume_tail?volume={vid}"
               f"&since_ns={since}")
    print(f"incremental backup of volume {vid}: "
          f"{applied_total} records appended")
    return 0


def run_scaffold(flags: Flags, args: list[str]) -> int:
    """Emit config templates (command/scaffold.go:12-58)."""
    name = flags.get("config", "filer")
    if name not in SCAFFOLDS:
        print(f"unknown config {name!r}; one of {sorted(SCAFFOLDS)}",
              file=sys.stderr)
        return 2
    content = SCAFFOLDS[name]
    out_dir = flags.get("output", "")
    if out_dir:
        path = os.path.join(out_dir, name + ".toml")
        with open(path, "w") as f:
            f.write(content)
        print(f"wrote {path}")
    else:
        print(content, end="")
    return 0


register(Command("upload", "upload -master=host:9333 file1 [file2 ...]",
                 "upload files to the cluster", run_upload))
register(Command("download", "download -server=host:9333 -dir=. fid1 ...",
                 "download files by fid", run_download))
register(Command("shell", "shell -master=host:9333 ['cmd1' 'cmd2' ...]",
                 "interactive admin shell", run_shell))
register(Command("watch", "watch -filer=host:8888 -pathPrefix=/",
                 "stream filer metadata change events", run_watch))
register(Command("version", "version", "print version", run_version))
register(Command("backup",
                 "backup -master=host:9333 -volumeId=3 -dir=/backup",
                 "incrementally back up one volume locally",
                 run_backup))
register(Command("scaffold", "scaffold -config=filer [-output=.]",
                 "emit a TOML config template", run_scaffold))
