"""HTTP/JSON control plane.

The reference runs gRPC (control) + HTTP (data) between roles
(weed/pb/*.proto, SURVEY §2.4).  This build keeps the same service shapes
— Assign/Lookup/heartbeat/allocate/EC RPCs with the same field names — but
carries them as JSON over HTTP on a threading server: zero-dependency,
debuggable with curl, and swappable for gRPC later without touching the
handlers.  The bulk EC compute plane is jax collectives (parallel/), not
these RPCs.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable


class RpcError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class JsonHttpServer:
    """Route table -> threading HTTP server.

    Handlers: fn(query: dict, body: bytes) -> dict | bytes | tuple.
    Returning bytes sends application/octet-stream; a (status, dict)
    tuple sets the status code.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 pass_headers: bool = False):
        self.host = host
        self.port = port or free_port()
        self.pass_headers = pass_headers
        self.routes: dict[tuple[str, str], Callable] = {}
        self.prefix_routes: list[tuple[str, str, Callable]] = []
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self.metrics = None  # (Registry, Counter, Histogram) when on
        self._metrics_route = False

    def serve_metrics_route(self, registry) -> None:
        """Route GET /metrics -> the registry's text exposition."""
        self._metrics_route = True
        self.route("GET", "/metrics", lambda q, b: (
            200, registry.expose().encode(),
            {"Content-Type": "text/plain; version=0.0.4"}))

    def enable_metrics(self, subsystem: str, registry=None,
                       serve_route: bool = True):
        """Record per-request count + latency (stats/metrics.go request
        vectors) and, unless serve_route=False (gateways whose URL
        namespace is user-controlled serve /metrics on a separate
        port, like the reference's metricsHttpPort), expose /metrics.
        Returns the Registry for the caller to add its own gauges."""
        from ..stats.metrics import Registry
        reg = registry or Registry()
        counter = reg.counter(
            f"SeaweedFS_{subsystem}_request_total",
            f"{subsystem} request count", ("type",))
        hist = reg.histogram(
            f"SeaweedFS_{subsystem}_request_seconds",
            f"{subsystem} request latency", ("type",))
        self.metrics = (reg, counter, hist)
        if serve_route:
            self.serve_metrics_route(reg)
        return reg

    def route(self, method: str, path: str, fn: Callable) -> None:
        self.routes[(method, path)] = fn

    def prefix_route(self, method: str, prefix: str, fn: Callable) -> None:
        """fn(path, query, body) for paths starting with prefix."""
        self.prefix_routes.append((method, prefix, fn))

    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> None:
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet
                pass

            def _dispatch(self, method: str):
                parsed = urllib.parse.urlparse(self.path)
                # keep_blank_values: S3-style flag params (?uploads,
                # ?tagging, ?delete) have no '=value'.
                query = {k: v[0] for k, v in urllib.parse.parse_qs(
                    parsed.query, keep_blank_values=True).items()}
                # Select request headers handlers care about (Range for
                # partial reads, Content-Type for upload mime) ride along
                # in the query dict under reserved keys.
                if self.headers.get("Range"):
                    query["_range_header"] = self.headers["Range"]
                if self.headers.get("Content-Type"):
                    query["_content_type"] = self.headers["Content-Type"]
                if server.pass_headers:
                    # Full header dict + raw query string for handlers
                    # that authenticate requests (S3 sig v4 needs the
                    # exact header set and query encoding).
                    query["_headers"] = {k.lower(): v for k, v
                                         in self.headers.items()}
                    query["_raw_query"] = parsed.query
                    query["_method"] = method
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                fn = server.routes.get((method, parsed.path))
                args = (query, body)
                if fn is None:
                    for m, prefix, pfn in server.prefix_routes:
                        if m == method and parsed.path.startswith(prefix):
                            fn = pfn
                            args = (parsed.path, query, body)
                            break
                if fn is None:
                    self._send(404, {"error": f"no route {method} "
                                              f"{parsed.path}"})
                    return
                metrics = server.metrics
                t0 = time.perf_counter() if metrics else 0.0
                try:
                    result = fn(*args)
                except RpcError as e:
                    self._send(e.status, {"error": e.message})
                    return
                except Exception as e:  # noqa: BLE001
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})
                    return
                finally:
                    # Exclude /metrics only where it IS the scrape
                    # endpoint; on gateways it's a user path to count.
                    if metrics and not (server._metrics_route
                                        and parsed.path == "/metrics"):
                        _reg, counter, hist = metrics
                        counter.inc(type=method)
                        hist.observe(time.perf_counter() - t0,
                                     type=method)
                extra = None
                if isinstance(result, tuple):
                    if len(result) == 3:
                        status, payload, extra = result
                    else:
                        status, payload = result
                else:
                    status, payload = 200, result
                self._send(status, payload, extra)

            def _send(self, status: int, payload, extra=None):
                if hasattr(payload, "read"):
                    # Stream any file-like payload (open file, upstream
                    # HTTP response) without buffering it: O(1MB) memory
                    # per in-flight large read.
                    import shutil
                    extra = dict(extra or {})
                    ctype = extra.pop("Content-Type",
                                      "application/octet-stream")
                    size = extra.pop("Content-Length", None)
                    if size is None:
                        size = str(os.fstat(payload.fileno()).st_size)
                    self.send_response(status)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(size))
                    for k, v in extra.items():
                        self.send_header(k, v)
                    self.end_headers()
                    with payload:
                        if self.command != "HEAD":
                            shutil.copyfileobj(payload, self.wfile,
                                               length=1 << 20)
                    return
                extra = dict(extra or {})
                if isinstance(payload, (bytes, bytearray)):
                    data = bytes(payload)
                    ctype = extra.pop("Content-Type",
                                      "application/octet-stream")
                else:
                    data = json.dumps(payload or {}).encode()
                    ctype = extra.pop("Content-Type", "application/json")
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                # HEAD handlers advertise the real body size without
                # materializing it.
                clen = extra.pop("Content-Length", str(len(data)))
                self.send_header("Content-Length", clen)
                for k, v in extra.items():
                    self.send_header(k, v)
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(data)

            def do_GET(self):
                self._dispatch("GET")

            def do_HEAD(self):
                self._dispatch("HEAD")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

            # WebDAV verbs (gateways route them like any other method)

            def do_OPTIONS(self):
                self._dispatch("OPTIONS")

            def do_PROPFIND(self):
                self._dispatch("PROPFIND")

            def do_PROPPATCH(self):
                self._dispatch("PROPPATCH")

            def do_MKCOL(self):
                self._dispatch("MKCOL")

            def do_MOVE(self):
                self._dispatch("MOVE")

            def do_COPY(self):
                self._dispatch("COPY")

            def do_LOCK(self):
                self._dispatch("LOCK")

            def do_UNLOCK(self):
                self._dispatch("UNLOCK")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name=f"http:{self.port}",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def call(url: str, method: str = "GET", body: bytes | None = None,
         timeout: float = 10.0):
    """HTTP call returning parsed JSON (dict) or raw bytes."""
    req = urllib.request.Request(url, data=body, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            data = resp.read()
            if resp.headers.get("Content-Type", "").startswith(
                    "application/json"):
                return json.loads(data or b"{}")
            return data
    except urllib.error.HTTPError as e:
        try:
            message = json.loads(e.read() or b"{}").get("error", str(e))
        except Exception:  # noqa: BLE001
            message = str(e)
        raise RpcError(e.code, message) from None


def call_to_file(url: str, path: str, timeout: float = 600.0) -> int:
    """Stream a GET response to a file in chunks; returns byte count.
    Bulk transfers (volume/shard copies) must never buffer a 30GB .dat
    in memory (the reference streams CopyFile in chunks too)."""
    req = urllib.request.Request(url)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp, \
                open(path, "wb") as f:
            total = 0
            while True:
                chunk = resp.read(1 << 20)
                if not chunk:
                    return total
                f.write(chunk)
                total += len(chunk)
    except urllib.error.HTTPError as e:
        try:
            message = json.loads(e.read() or b"{}").get("error", str(e))
        except Exception:  # noqa: BLE001
            message = str(e)
        raise RpcError(e.code, message) from None


def call_json(url: str, method: str = "POST", payload: dict | None = None,
              timeout: float = 10.0) -> dict:
    body = json.dumps(payload or {}).encode()
    out = call(url, method, body, timeout)
    assert isinstance(out, dict)
    return out
