"""HTTP/JSON control plane.

The reference runs gRPC (control) + HTTP (data) between roles
(weed/pb/*.proto, SURVEY §2.4).  This build keeps the same service shapes
— Assign/Lookup/heartbeat/allocate/EC RPCs with the same field names — but
carries them as JSON over HTTP: zero-dependency, debuggable with curl, and
swappable for gRPC later without touching the handlers.  The bulk EC
compute plane is jax collectives (parallel/), not these RPCs.

Both halves are hand-rolled for per-request CPU, because on the write/read
hot path the HTTP framing IS the workload (the storage op itself is
~0.13ms): the server is a thread-per-connection keep-alive loop with a
~30-line parser (http.server's BaseHTTPRequestHandler burns ~0.3ms/request
in email.parser), and the client is a raw-socket keep-alive pool
(http.client spends ~0.25ms/request the same way).  The reference's Go
net/http does the equivalent in microseconds; this is the Python analog of
its pooled transports (operation/upload_content.go:67).

TLS: pass an ssl.SSLContext as JsonHttpServer(ssl_context=...) to serve
https, and install the client side with set_client_ssl_context()
(security.toml plane, reference weed/security/tls.go).
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
import urllib.parse
import weakref
from typing import Callable

from ..events import journal as _events
from ..fault import registry as _fault
from ..netcore.bufio import SockReader
from ..netcore.registry import ConnRegistry, CountedConn, \
    conns_reaped_total
from ..stats import contention as _contention
from ..stats import flows as _flows
from ..stats import phases as _phases
from ..stats.metrics import Counter, Gauge, Histogram
from ..tenancy import context as _tenant_ctx
from ..trace import tracer as _tracer
from . import resilience as _res

# Transport selection for every JsonHttpServer in the process that is
# not given an explicit transport= (the -transport flag): "threads" is
# the thread-per-connection keep-alive loop, "aio" the netcore event
# loop.  The env override lets the whole test suite run on aio in one
# line: SEAWEEDFS_TPU_TRANSPORT=aio pytest tests/.
TRANSPORTS = ("threads", "aio")


def default_transport() -> str:
    t = os.environ.get("SEAWEEDFS_TPU_TRANSPORT", "").strip().lower()
    return t if t in TRANSPORTS else "threads"

_REASONS = {200: "OK", 201: "Created", 204: "No Content",
            206: "Partial Content", 301: "Moved Permanently",
            302: "Found", 304: "Not Modified", 307: "Temporary Redirect",
            400: "Bad Request", 401: "Unauthorized", 403: "Forbidden",
            404: "Not Found", 405: "Method Not Allowed",
            406: "Not Acceptable", 409: "Conflict",
            412: "Precondition Failed", 414: "URI Too Long",
            416: "Range Not Satisfiable", 423: "Locked",
            429: "Too Many Requests",
            431: "Request Header Fields Too Large",
            500: "Internal Server Error",
            501: "Not Implemented", 503: "Service Unavailable",
            507: "Insufficient Storage"}

# Internal cluster traffic (replication fan-out, scrub repair fetches,
# EC rebuild shard gathers/scatters) marks itself with this header so
# the receiving server's admission control routes it through the
# lower-priority `internal` lane — a repair storm must never starve
# user reads (the operational lesson of arXiv:1309.0186).
PRIORITY_HEADER = "X-Weed-Priority"
PRIORITY_LOW = {PRIORITY_HEADER: "low"}


import re as _re

_RANGE_RE = _re.compile(r"^bytes=([0-9]*)-([0-9]*)$")

# A needle fid path: `/3,0172cb7d…` (optionally `/vid,fid/name.ext`).
_FID_PATH_RE = _re.compile(r"^/\d+,")


def endpoint_family(path: str, literal: bool) -> str:
    """Bounded-cardinality endpoint label for the request histogram and
    the SLO plane.  Literal routes (the static route table — which is
    how every real /admin/* endpoint is mounted, so the admin surface
    keeps its literal paths) keep their path; the per-needle data plane
    (`/3,0172…`) collapses to `/needle`; everything else (filer user
    paths, S3 objects, probes of unmounted paths — unbounded,
    client-chosen namespaces) collapses to `/other`.  The label set is
    therefore bounded by the route table + 3.  There is deliberately
    NO startswith("/admin/") carve-out: on a gateway whose / namespace
    is user-controlled, a client could mint unlimited /admin/<x> paths
    and grow the label set (and the SLO sketch table) without bound."""
    if literal:
        return path
    if _FID_PATH_RE.match(path):
        return "/needle"
    if path.startswith("/debug/"):
        return "/debug/*"
    return "/other"


def parse_byte_range(rng: str, size: int) -> tuple[int, int] | None:
    """Single-range 'bytes=' header -> (lo, hi) inclusive; None means
    serve the whole payload (RFC 7233 lets a server ignore unparseable
    or multi-part ranges — matching processRangeRequest's single-range
    fast path, weed/server/common.go:233).  A lo past the end raises
    RpcError(416)."""
    # Digits only, exactly one dash, at least one side present — like
    # Go's parseRange; Python's int() would otherwise accept '+5',
    # '1_0', or whitespace, and 'bytes=--10' would misparse as a
    # suffix range with a negative length.
    m = _RANGE_RE.match(rng)
    if m is None:
        return None
    lo_s, hi_s = m.group(1), m.group(2)
    if not lo_s and not hi_s:
        return None
    if lo_s:
        lo = int(lo_s)
        hi = int(hi_s) if hi_s else size - 1
    else:  # suffix form: bytes=-N
        lo = max(size - int(hi_s), 0)
        hi = size - 1
    if lo >= size:
        if size == 0 and not lo_s:
            return None  # suffix range of an empty body: serve it all
        raise RpcError(416, f"range {rng} beyond size {size}")
    hi = min(hi, size - 1)
    if hi < lo:  # reversed/negative range: unsatisfiable (Go's
        return None  # parseRange rejects start > end; serve it all)
    return lo, hi


class RpcError(Exception):
    def __init__(self, status: int, message: str,
                 headers: dict | None = None,
                 retry_after: float | None = None):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message
        # Extra response headers a handler wants on its error answer
        # (Retry-After on 429/503 sheds and drain refusals).
        self.headers = dict(headers or {})
        # Parsed Retry-After from a server's answer (client side):
        # RetryPolicy honors it as a backoff floor on 429/503.
        self.retry_after = retry_after


# -- admission control --------------------------------------------------------
# Per-role overload protection: bounded concurrency in three lanes
# (read / write / internal) with a bounded wait queue per lane.  A
# request that finds its lane full AND its queue full (or waits out the
# queue timeout) is shed with 429 + Retry-After instead of queueing the
# server into collapse.  Internal traffic (PRIORITY_HEADER: low —
# replication, scrub repair, EC rebuilds) runs in its own smaller lane
# so a repair storm cannot starve user traffic.  With max_concurrent=0
# nothing is ever shed, but in-flight requests are still counted — the
# graceful-drain path waits on that count.

# Like the breaker/retry/fault instruments, these are process-global:
# roles sharing one process (`weed server`, test stacks) report merged
# numbers on every scrape — the established convention for this
# codebase's RPC-plane instruments (see enable_metrics).
requests_shed_total = Counter(
    "SeaweedFS_requests_shed_total",
    "requests shed (429) by admission control", ("lane",))

_admission_instances: "weakref.WeakSet[AdmissionControl]" = \
    weakref.WeakSet()


def _inflight_values() -> dict:
    out = {("read",): 0.0, ("write",): 0.0, ("internal",): 0.0}
    for adm in list(_admission_instances):
        for lane in adm.lanes.values():
            out[(lane.name,)] += float(lane.inflight)
    return out


inflight_requests = Gauge(
    "SeaweedFS_inflight_requests",
    "admitted requests currently executing", ("lane",),
    callback=_inflight_values)


def _queue_depth_values() -> dict:
    out = {("read",): 0.0, ("write",): 0.0, ("internal",): 0.0}
    for adm in list(_admission_instances):
        for lane in adm.lanes.values():
            out[(lane.name,)] += float(lane.waiting)
    return out


# Per-lane queue pressure: the signal worker-pool autoscaling (and an
# operator eyeballing a saturated role) needs BEFORE sheds start — a
# nonzero depth with zero sheds is the early-warning band.
admission_queue_depth = Gauge(
    "SeaweedFS_admission_queue_depth",
    "admission waiters currently queued per lane", ("lane",),
    callback=_queue_depth_values)

# Realized queue wait per lane (admitted AND timed-out waits): the
# companion latency signal to the depth gauge above.
admission_wait_seconds = Histogram(
    "SeaweedFS_admission_wait_seconds",
    "time spent waiting in the admission queue", ("lane",))

# Per-tenant QoS throttles (tenancy/qos.py token buckets): an
# over-rate tenant's 429s, named — the flooding principal is visible
# on any role's scrape, distinct from lane sheds which blame no one.
tenant_throttled_total = Counter(
    "SeaweedFS_tenant_throttled_total",
    "requests throttled (429) by per-tenant QoS token buckets",
    ("tenant",))


class _Lane:
    """One admission lane: a concurrency cap plus a bounded wait queue.

    cap == 0 means unlimited (count in-flight only, never shed).  The
    queue is bounded in BOTH dimensions: at most `queue_depth` waiters,
    each waiting at most `queue_timeout` seconds — so under sustained
    overload latency stays bounded and the excess is shed immediately
    instead of building an unbounded backlog that outlives the burst.
    """

    __slots__ = ("name", "cap", "queue_depth", "queue_timeout", "_sem",
                 "inflight", "waiting", "shed", "_lock",
                 "_last_shed_emit", "_drr")

    def __init__(self, name: str, cap: int, queue_depth: int,
                 queue_timeout: float, weight_for=None):
        from ..tenancy.qos import DrrQueue
        self.name = name
        self.cap = cap
        self.queue_depth = queue_depth
        self.queue_timeout = queue_timeout
        self._sem = threading.BoundedSemaphore(cap) if cap > 0 else None
        # Per-tenant sub-queues inside this lane: freed slots are
        # handed out deficit-round-robin across tenants (weighted by
        # quota-rule weight=), so one flooding tenant's backlog cannot
        # monopolize the queue.  Untenanted traffic shares the ""
        # sub-queue — with a single tenant (or none) this degrades to
        # the plain FIFO the lane always had.
        self._drr = DrrQueue(weight_for=weight_for)
        self.inflight = 0
        self.waiting = 0
        self.shed = 0
        # Metered (stats/contention.py) only when a concurrency cap is
        # configured: with cap=0 this lock guards a bare in-flight
        # counter on EVERY request and admission can never queue or
        # shed — wrapping it would stretch a ~100ns critical section
        # into ~1µs of Python bookkeeping under the GIL (a measured
        # ~5% throughput tax at 4k req/s) for a lock whose contention
        # explains nothing.  With a cap, lane behavior IS the
        # front-door story and the metering earns its cost.
        # hold_observe_min: the normal hold is two counter increments;
        # only pathological holds deserve histogram rows.
        self._lock = _contention.MeteredLock(
            f"admission.{name}", hold_observe_min=1e-3) \
            if cap > 0 else threading.Lock()
        self._last_shed_emit = 0.0

    def enter(self, tenant: str = "") -> bool:
        """Admit (possibly after a bounded wait) or shed; True = admitted
        (the caller MUST pair it with exit()).

        The wait queue is per-tenant DRR: a waiter parks in its
        tenant's sub-queue and is woken by exit() handing it a freed
        slot directly (the semaphore is bypassed on handoff, so queued
        waiters can never be barged by fast-path newcomers — a free
        permit only exists while nobody waits)."""
        if self._sem is None:
            with self._lock:
                self.inflight += 1
            return True
        if self._sem.acquire(blocking=False):
            with self._lock:
                self.inflight += 1
            return True
        with self._lock:
            queue_full = self.waiting >= self.queue_depth
            if not queue_full:
                w = self._drr.push(tenant)
                self.waiting += 1
        if queue_full:
            self._record_shed()
            return False
        t0 = time.perf_counter()
        granted = w.event.wait(self.queue_timeout)
        admission_wait_seconds.observe(time.perf_counter() - t0,
                                       lane=self.name)
        with self._lock:
            self.waiting -= 1
            if not granted and w.event.is_set():
                # Lost race: exit() handed us the slot between the wait
                # timing out and this lock — the handoff is already
                # made, so refusing it would leak a permit.
                granted = True
            if granted:
                self.inflight += 1
            else:
                self._drr.discard(w)
        if not granted:
            self._record_shed()
        return granted

    def exit(self) -> None:
        with self._lock:
            self.inflight -= 1
            if self._sem is None:
                return
            w = self._drr.pop()
            if w is not None:
                # Direct handoff: the permit moves to the waiter.  Set
                # INSIDE the lock — a waiter timing out concurrently
                # rechecks is_set() under this same lock, so the slot
                # is either visibly handed or still poppable, never
                # handed to a corpse.
                w.event.set()
                return
        self._sem.release()

    def _record_shed(self) -> None:
        requests_shed_total.inc(lane=self.name)
        with self._lock:
            self.shed += 1
            now = time.monotonic()
            emit = now - self._last_shed_emit >= 5.0
            if emit:
                self._last_shed_emit = now
            shed_total = self.shed
        if emit:
            # Events are state transitions, not per-request traffic:
            # one journal row per shedding episode (>=5s apart), with
            # the cumulative count so the timeline still quantifies it.
            with _tracer.root_span("admission.shed", "rpc"):
                _events.emit("server.shed", severity="warn",
                             lane=self.name, shed_total=shed_total,
                             cap=self.cap,
                             queue_depth=self.queue_depth)


# Paths never queued or shed: operator/introspection surfaces must stay
# reachable exactly when the server is overloaded or draining (which is
# when they are needed), heartbeats keep the master's liveness view
# honest, and long-lived push streams (/cluster/watch) would pin a lane
# slot forever.  The /debug/ PREFIX exemption below covers the whole
# profiling plane (/debug/pprof/*, /debug/locks, /debug/slow, ...):
# a 30s blocking profile runs exactly when the server is saturated —
# the one moment it must not occupy a read-lane slot and compete with
# the traffic being diagnosed (asserted by
# tests/test_attribution.py's saturated-server profile test).
_ADMISSION_EXEMPT = {"/metrics", "/cluster/healthz", "/heartbeat",
                     "/filer/heartbeat", "/admin/drain",
                     "/admin/status", "/cluster/watch"}


def _admission_exempt(path: str) -> bool:
    return path in _ADMISSION_EXEMPT or path.startswith("/debug/")


class AdmissionControl:
    """Admission state for one server role (-max.concurrent).

    read / write lanes each get `max_concurrent` slots; the internal
    lane (PRIORITY_HEADER: low, and ?type=replicate fan-outs) gets a
    quarter of that, so background repair/replication pressure is
    capped below user traffic.  queue_depth defaults to 2x the lane's
    concurrency."""

    LANES = ("read", "write", "internal")

    def __init__(self, max_concurrent: int = 0,
                 queue_depth: int | None = None,
                 queue_timeout: float = 2.0,
                 internal_concurrent: int | None = None,
                 retry_after: float = 1.0,
                 tenant_policy=None):
        from ..tenancy.qos import TenantBuckets
        self.max_concurrent = max_concurrent
        if queue_depth is None:
            queue_depth = 2 * max_concurrent
        if internal_concurrent is None:
            internal_concurrent = max(1, max_concurrent // 4) \
                if max_concurrent else 0
        self.retry_after = retry_after
        # Tenancy QoS (-tenant.rules): per-tenant req/s + write-MB/s
        # token buckets at the gate, and DRR weights inside the lane
        # queues.  No policy = no throttling, weight 1 for everyone.
        self.tenant_policy = tenant_policy
        self.tenant_buckets = TenantBuckets(tenant_policy)
        weight_for = tenant_policy.weight_for if tenant_policy \
            is not None else None
        self._last_throttle_emit: dict[str, float] = {}
        self.lanes = {
            "read": _Lane("read", max_concurrent, queue_depth,
                          queue_timeout, weight_for),
            "write": _Lane("write", max_concurrent, queue_depth,
                           queue_timeout, weight_for),
            "internal": _Lane("internal", internal_concurrent,
                              max(1, queue_depth // 2)
                              if internal_concurrent else 0,
                              queue_timeout, weight_for),
        }
        _admission_instances.add(self)

    def throttle(self, tenant: str, nbytes: int = 0) -> float:
        """Per-tenant token-bucket check: 0.0 = admitted, else the
        Retry-After to surface on the 429.  Counts + journals the
        throttle (one `tenant.throttled` row per tenant per >=5s
        episode, like the lane-shed event)."""
        if not tenant:
            return 0.0
        retry = self.tenant_buckets.admit(tenant, nbytes)
        if retry <= 0.0:
            return 0.0
        tenant_throttled_total.inc(tenant=tenant)
        now = time.monotonic()
        if now - self._last_throttle_emit.get(tenant, 0.0) >= 5.0:
            self._last_throttle_emit[tenant] = now
            with _tracer.root_span("tenant.throttled", "rpc"):
                _events.emit(
                    "tenant.throttled", severity="warn", tenant=tenant,
                    retry_after=round(retry, 3),
                    throttled_total=int(
                        tenant_throttled_total.value(tenant=tenant)))
        return retry

    def lane_for(self, method: str, headers: dict,
                 query: dict) -> _Lane:
        if headers.get("x-weed-priority") == "low" or \
                query.get("type") == "replicate":
            return self.lanes["internal"]
        if method in ("GET", "HEAD"):
            return self.lanes["read"]
        return self.lanes["write"]

    def inflight_total(self) -> int:
        return sum(lane.inflight for lane in self.lanes.values())

    def snapshot(self) -> dict:
        out = {}
        for name, lane in self.lanes.items():
            with lane._lock:  # DrrQueue is lane-lock serialized
                queued = lane._drr.tenants()
            out[name] = {"cap": lane.cap, "inflight": lane.inflight,
                         "waiting": lane.waiting, "shed": lane.shed,
                         "queued_tenants": queued}
        return out


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _LineTooLong(Exception):
    """A request/header line exceeded the 64KB cap (maps to 414/431)."""


def _read_headers(rf) -> dict[str, str]:
    """Read header lines into a lowercase-keyed dict.

    EOF mid-headers is a connection error, not end-of-headers — a
    truncated request must never be parsed as a complete one.  A line
    missing its newline at the 64KB cap raises _LineTooLong instead of
    being silently truncated (and then misparsed)."""
    headers: dict[str, str] = {}
    while True:
        line = rf.readline(65537)
        if line in (b"\r\n", b"\n"):
            return headers
        if not line:
            raise ConnectionError("eof in headers")
        if not line.endswith(b"\n"):
            # A newline-less line shorter than the cap is EOF truncation
            # (peer died mid-line); only a full-cap line is too long.
            if len(line) < 65537:
                raise ConnectionError("eof mid-header line")
            raise _LineTooLong("header line exceeds 64KB")
        i = line.find(b":")
        if i > 0:
            headers[line[:i].decode("latin-1").strip().lower()] = \
                line[i + 1:].decode("latin-1").strip()


def _iter_chunks(rf):
    """Transfer-Encoding: chunked parser — yields each chunk's payload.
    The single implementation behind both the server's one-shot body
    read and the client's incremental response reader."""
    while True:
        line = rf.readline(65537)
        if not line:
            raise ConnectionError("eof in chunked body")
        size = int(line.split(b";")[0].strip() or b"0", 16)
        if size == 0:
            # trailers until blank line
            while rf.readline(65537) not in (b"\r\n", b"\n", b""):
                pass
            return
        piece = rf.read(size)
        if len(piece) < size:
            raise ConnectionError("eof in chunked body")
        yield piece
        rf.read(2)  # CRLF


def _chunk_pump(chunk_iter, buf: bytes, n: int):
    """Pull up to n bytes (all when n<0) from a chunk iterator with a
    carry buffer — the one chunked-read state machine shared by request
    (BodyReader) and response (_Resp) sides.  Returns
    (data, leftover_buf, exhausted)."""
    out = bytearray()
    exhausted = False
    while n < 0 or len(out) < n:
        if not buf:
            try:
                buf = next(chunk_iter)
            except StopIteration:
                exhausted = True
                break
        take = len(buf) if n < 0 else min(n - len(out), len(buf))
        out += buf[:take]
        buf = buf[take:]
    return bytes(out), buf, exhausted


class EventStream:
    """Unbounded push channel served as a chunked response — the
    HTTP-plane analog of the reference's long-lived gRPC streams
    (KeepConnected, SubscribeMetadata).  A handler returns one of
    these; producer threads push() JSON-able docs, each going out as
    one NDJSON line.  Blank-line heartbeats flow every `heartbeat`
    seconds so a dead peer is detected by the send failing; close()
    (run by the response writer on disconnect or end()) fires the
    registered cleanups (unsubscribe hooks)."""

    # A consumer that stops reading must not buffer the producer's
    # events forever: past this bound the stream terminates and the
    # client reconnects, resuming from its cursor (offsets make every
    # push channel resumable, so ending early is always safe).
    MAX_QUEUED = 65536

    def __init__(self, heartbeat: float = 10.0):
        import queue
        self._q: "queue.Queue[bytes]" = queue.Queue()
        self._empty = queue.Empty
        self.heartbeat = heartbeat
        self._cleanups: list = []
        self._closed = False
        self._overflowed = False

    def push(self, doc: dict) -> None:
        self.push_raw(json.dumps(doc).encode() + b"\n")

    def push_raw(self, line: bytes) -> None:
        if self._overflowed:
            return
        if self._q.qsize() >= self.MAX_QUEUED:
            self._overflowed = True
            self._q.put(b"")  # end: the slow consumer redials
            return
        self._q.put(line)

    def end(self) -> None:
        """Terminate the stream from the producer side."""
        self._q.put(b"")

    def on_close(self, fn) -> None:
        self._cleanups.append(fn)

    def read(self, n: int = -1) -> bytes:
        if self._closed:
            return b""
        try:
            return self._q.get(timeout=self.heartbeat)
        except self._empty:
            return b"\n"  # heartbeat keeps dead-peer detection alive

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._closed = True
        for fn in self._cleanups:
            try:
                fn()
            except Exception:  # noqa: BLE001
                pass
        return False


class BodyReader:
    """Incremental request-body reader for stream_body routes.

    Handlers call read(n) for bounded pieces (exactly n bytes until
    EOF) or read() for the remainder; the server drains anything left
    over so keep-alive framing survives handlers that bail early.  A
    peer that dies mid-body raises ConnectionError — a short body must
    never be mistaken for a complete one."""

    def __init__(self, rf, length: int | None, chunked: bool):
        self._rf = rf
        self._remaining = length or 0
        self._chunk_iter = _iter_chunks(rf) if chunked else None
        self._buf = b""
        self.truncated = False
        # Declared size; None for chunked bodies (handlers that want to
        # forward with a Content-Length check this).
        self.length = None if chunked else length
        # Wire-flow attribution: set by _serve_one so consumed bytes
        # (handler reads AND the post-dispatch drain) count as the
        # request's "in" leg.
        self.flow_note = None

    def read(self, n: int = -1) -> bytes:
        if self._chunk_iter is not None:
            return self._read_chunked(n)
        want = self._remaining if n < 0 else min(n, self._remaining)
        out = bytearray()
        while len(out) < want:
            piece = self._rf.read(want - len(out))
            if not piece:
                self.truncated = True
                raise ConnectionError(
                    f"request body truncated: {self._remaining - len(out)}"
                    f" bytes missing")
            out += piece
        self._remaining -= len(out)
        if out and self.flow_note is not None:
            self.flow_note(len(out))
        return bytes(out)

    def _read_chunked(self, n: int) -> bytes:
        try:
            data, self._buf, exhausted = _chunk_pump(
                self._chunk_iter, self._buf, n)
        except Exception:  # malformed/truncated framing mid-body
            self.truncated = True
            raise ConnectionError(
                "chunked request body truncated") from None
        if exhausted:
            self._chunk_iter = None
            self._remaining = 0
        if data and self.flow_note is not None:
            self.flow_note(len(data))
        return data

    def drain(self) -> None:
        while True:
            if not self.read(1 << 20):
                return


def _read_chunked(rf) -> bytes:
    """Minimal Transfer-Encoding: chunked body reader (whole body)."""
    return b"".join(_iter_chunks(rf))


def _drain_then_fin(conn, rf, limit: int = 1 << 20) -> None:
    """Graceful error-close: signal FIN and drain the peer's unread
    request bytes (bounded) so the kernel doesn't RST away the error
    response we just sent."""
    try:
        conn.shutdown(socket.SHUT_WR)
        conn.settimeout(2.0)
        while limit > 0:
            data = rf.read(min(65536, limit))
            if not data:
                return
            limit -= len(data)
    except OSError:
        pass


class JsonHttpServer:
    """Route table -> threaded keep-alive HTTP server.

    Handlers: fn(query: dict, body: bytes) -> dict | bytes | tuple.
    Returning bytes sends application/octet-stream; a (status, dict)
    tuple sets the status code; a 3-tuple adds extra headers; a
    file-like payload is streamed.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 pass_headers: bool = False, ssl_context=None,
                 idle_timeout: float = 120.0,
                 admission: AdmissionControl | None = None,
                 transport: str | None = None,
                 stall_timeout: float | None = None,
                 workers: int = 0):
        self.host = host
        self.port = port or free_port()
        self.pass_headers = pass_headers
        self.ssl_context = ssl_context
        # Per-connection socket timeout: a peer that stalls mid-request
        # (slow-loris) or goes silent is reaped after this many idle
        # seconds, freeing its thread + (if admitted) its lane slot.
        self.idle_timeout = idle_timeout
        # Mid-request stall deadline (aio transport): a peer with a
        # request IN FLIGHT that goes silent is a slow-loris, not an
        # idle keep-alive conn — it is reaped much harder than
        # idle_timeout.  The threaded transport cannot tell the two
        # apart (its kernel SO_RCVTIMEO covers both).
        self.stall_timeout = stall_timeout if stall_timeout is not None \
            else min(idle_timeout, max(1.0, idle_timeout / 4.0))
        self.transport = (transport or default_transport()).lower()
        if self.transport not in TRANSPORTS:
            raise ValueError(f"unknown transport {self.transport!r} "
                             f"(want one of {TRANSPORTS})")
        self.workers = workers or 16
        # Long-lived push-stream routes: under aio these are diverted
        # to dedicated threads at dispatch so they never pin worker
        # slots (a /cluster/watch stream lives for the peer's lifetime).
        self.stream_paths = {"/cluster/watch", "/.meta/subscribe"}
        # Live-connection registry, shared by both transports: feeds
        # GET /debug/conns and SeaweedFS_open_connections{role,state}.
        self.conns = ConnRegistry()
        self._aio = None  # netcore.loop.EventLoopTransport when aio
        # Overload protection (AdmissionControl).  Always present so
        # in-flight accounting works even with no concurrency cap —
        # graceful drain waits on it.
        self.admission = admission or AdmissionControl(0)
        self.routes: dict[tuple[str, str], Callable] = {}
        self.prefix_routes: list[tuple[str, str, Callable]] = []
        self.metrics = None  # (Registry, Counter, Histogram) when on
        self.slo = None      # stats.slo.SloTracker once metrics are on
        # Wire-flow attribution (stats/flows.py): the role this server
        # answers X-Weed-Role with ("master"/"volume"/"filer"/...),
        # set by enable_metrics from its subsystem name.
        self.flow_role = ""
        # Service name for the tracing middleware; set by
        # trace.setup_server_tracing — None means no server spans.
        self.trace_service: str | None = None
        self._metrics_route = False
        self._sock: socket.socket | None = None
        self._running = False
        # Live accepted connections, severed on stop(): closing only
        # the listener leaves idle keep-alive threads free to serve
        # one more request each, and a thread blocked in accept()
        # keeps the kernel listener itself alive past close().
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        # C10k observability on every role (literal routes win over a
        # filer's "/" prefix route, same precedence as /metrics).
        self.route("GET", "/debug/conns", self._debug_conns)
        # Wire-flow attribution: this process's per-purpose byte
        # ledger + budget verdicts (admission-exempt via /debug/).
        self.route("GET", "/debug/flows", lambda q, b: _flows.debug_doc(
            f"{self.host}:{self.port}", self.flow_role))
        # Device roofline (stats/roofline.py): per-kernel achieved
        # fractions, pipeline occupancy, probed peaks — on every role
        # (any process can run EC kernels in-process).
        self.route("GET", "/debug/device", self._debug_device)

    def _debug_device(self, query: dict, body) -> dict:
        from ..stats import roofline as _roofline
        return _roofline.debug_doc(f"{self.host}:{self.port}",
                                   self.flow_role)

    def _debug_conns(self, query: dict, body) -> dict:
        """Per-connection state from the live registry: age, lane,
        lifecycle state, request count, bytes — the event loop reports
        precise idle/reading/handling, threaded conns report "open"."""
        try:
            limit = int(query.get("limit", 256))
        except ValueError:
            limit = 256
        return {
            "transport": self.transport,
            "open": len(self.conns),
            "states": self.conns.state_counts(),
            "idle_timeout": self.idle_timeout,
            "stall_timeout": self.stall_timeout,
            "conns": self.conns.snapshot(limit),
        }

    def serve_metrics_route(self, registry) -> None:
        """Route GET /metrics -> the registry's text exposition."""
        self._metrics_route = True
        self.route("GET", "/metrics", lambda q, b: (
            200, registry.expose().encode(),
            {"Content-Type": "text/plain; version=0.0.4"}))

    def enable_metrics(self, subsystem: str, registry=None,
                       serve_route: bool = True):
        """Record per-request count + latency (stats/metrics.go request
        vectors) and, unless serve_route=False (gateways whose URL
        namespace is user-controlled serve /metrics on a separate
        port, like the reference's metricsHttpPort), expose /metrics.
        Returns the Registry for the caller to add its own gauges.

        Idempotent: a second call returns the existing registry instead
        of stacking a second counter/histogram family (a duplicate
        exposition block fails the promtool validator — the
        rolling-restart / re-init regression in tests/test_slo.py)."""
        from ..stats import slo as _slo
        from ..stats.metrics import Registry
        if self.metrics is not None:
            return self.metrics[0]
        reg = registry or Registry()
        counter = reg.counter(
            f"SeaweedFS_{subsystem}_request_total",
            f"{subsystem} request count", ("type",))
        # The latency histogram separates error tails from success
        # tails: status-class (2xx/4xx/5xx) and a bounded
        # endpoint-family label (endpoint_family) beside the method.
        hist = reg.histogram(
            f"SeaweedFS_{subsystem}_request_seconds",
            f"{subsystem} request latency",
            ("type", "family", "status"))
        self.metrics = (reg, counter, hist)
        # SLO plane (stats/slo.py): live windowed quantiles + exemplars
        # for every role, burn rates once objectives are declared
        # (set_objectives).  The gauges are PER-TRACKER, registered
        # into this (fresh) registry — process-global singletons below
        # use register_once so re-registration can never duplicate an
        # exposition family.
        self.slo = _slo.SloTracker(subsystem,
                                   node=f"{self.host}:{self.port}")
        reg.gauge("SeaweedFS_request_quantile_seconds",
                  "live request-latency quantiles over the sliding "
                  "window (sketch relative error documented in "
                  "stats/sketch.py)",
                  ("role", "family", "status", "q"),
                  callback=self.slo.quantile_gauge_values)
        reg.gauge("SeaweedFS_slo_burn_rate",
                  "error-budget burn rate per declared SLO and window "
                  "(fast burn >= 14.4 degrades /cluster/healthz)",
                  ("role", "slo", "window"),
                  callback=self.slo.burn_gauge_values)
        # Time-attribution plane (stats/phases.py): live windowed
        # quantiles of each request phase — where the wall time of
        # this role's requests actually goes, per endpoint family.
        reg.gauge("SeaweedFS_request_phase_seconds",
                  "live request phase-time quantiles over the sliding "
                  "window (queue/lock/handler/disk/device/"
                  "rpc_downstream; same sketch bounds as the request "
                  "quantiles)",
                  ("role", "family", "phase", "q"),
                  callback=self.slo.phase_gauge_values)
        # RPC-plane resilience instruments are process-global singletons
        # (every role's outbound client shares the pool + breakers);
        # registering them here puts retry counts, breaker states, and
        # injected-fault counts on every role's /metrics scrape.
        reg.register_once(_res.rpc_retries_total)
        reg.register_once(_res.breaker_state_gauge)
        reg.register_once(_fault.faults_injected_total)
        reg.register_once(_events.events_total)
        # Overload-protection instruments (admission control): shed
        # counts by lane and the live in-flight gauge.
        reg.register_once(requests_shed_total)
        reg.register_once(inflight_requests)
        # Tenancy & QoS instruments: live per-lane queue depth, time
        # spent waiting for admission, and per-tenant throttle counts.
        reg.register_once(admission_queue_depth)
        reg.register_once(admission_wait_seconds)
        reg.register_once(tenant_throttled_total)
        # Front-door instruments: live connections by lifecycle state
        # (per-server registry, sampled at scrape) and event-loop reap
        # counts (process-global — kinds in netcore/registry.py).
        reg.gauge("SeaweedFS_open_connections",
                  "live server connections by transport lifecycle "
                  "state (aio: idle/reading/handling; threads: open)",
                  ("role", "state"),
                  callback=lambda: self.conns.gauge_values(subsystem))
        reg.register_once(conns_reaped_total)
        # Wire-flow attribution: every role exposes the per-purpose
        # wire-byte counter (process-global singleton — both the
        # client and server choke points observe into it) and
        # self-identifies on request/response headers so peers'
        # ledgers attribute links by node, not bare IP.
        self.flow_role = _flows.role_of(subsystem)
        _flows.set_process_identity(f"{self.host}:{self.port}",
                                    self.flow_role)
        reg.register_once(_flows.wire_bytes_total)
        # Lock-contention metering (stats/contention.py) and the
        # continuous profiler's runnable-threads gauge — process-global
        # singletons like the breaker/fault instruments above.
        reg.register_once(_contention.lock_wait_seconds)
        reg.register_once(_contention.lock_hold_seconds)
        from ..utils.pprof import runnable_threads as _runnable
        reg.register_once(_runnable)
        if serve_route:
            self.serve_metrics_route(reg)
        return reg

    def route(self, method: str, path: str, fn: Callable,
              stream_body: bool = False) -> None:
        self.routes[(method, path)] = (fn, stream_body)

    def prefix_route(self, method: str, prefix: str, fn: Callable,
                     stream_body: bool = False) -> None:
        """fn(path, query, body) for paths starting with prefix.  With
        stream_body=True the handler receives a BodyReader instead of
        bytes — a multi-GB PUT is consumed incrementally instead of
        ballooning RSS (the reference streams uploads,
        filer_server_handlers_write_autochunk.go:188)."""
        self.prefix_routes.append((method, prefix, fn, stream_body))

    def url(self) -> str:
        scheme = "https" if self.ssl_context else "http"
        return f"{scheme}://{self.host}:{self.port}"

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        import sys as _sys
        if _sys.getswitchinterval() > 0.001:
            # Thread-per-connection + the default 5ms GIL switch
            # interval convoys request latency to ~5ms p50 under
            # concurrent load; 1ms keeps handler threads responsive.
            _sys.setswitchinterval(0.001)
        self._sock = socket.create_server((self.host, self.port),
                                          backlog=512)
        self._running = True
        if self.transport == "aio":
            from ..netcore.loop import EventLoopTransport
            self._aio = EventLoopTransport(self)
            self._aio.start()
            return
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"http:{self.port}").start()

    def stop(self) -> None:
        self._running = False
        if self._aio is not None:
            self._aio.stop()
        sock, self._sock = self._sock, None
        if sock is not None:
            # shutdown() wakes a thread blocked in accept(); a bare
            # close() does not, and the in-progress syscall then pins
            # the kernel listener open — the "stopped" server keeps
            # accepting, and a pinned-port restart gets EADDRINUSE.
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        # Sever live keep-alive connections too: their threads sit in
        # readline() and would otherwise serve one more request each
        # after "stop" (standby-death chaos relies on stop = stopped).
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_conn,
                             args=(conn, addr[0] if addr else ""),
                             daemon=True).start()

    # -- connection loop -----------------------------------------------------

    def _serve_conn(self, conn: socket.socket, peer_ip: str = "") -> None:
        raw = conn  # pre-TLS socket: shutdown() severs either way
        with self._conns_lock:
            self._conns.add(raw)
        info = self.conns.add(peer_ip, "threads"
                              if self.transport == "threads" else "tls")
        info.state = "open"  # thread blocks in readline: idle-vs-
        #                      handling is invisible without per-read
        #                      bookkeeping the hot path shouldn't pay
        try:
            if self.ssl_context is not None:
                # Handshake in the connection thread so a slow/bogus
                # client can't stall the accept loop.
                conn = self.ssl_context.wrap_socket(conn, server_side=True)
                conn.settimeout(self.idle_timeout)
            else:
                # Kernel-enforced timeouts keep the socket in blocking
                # mode: Python's settimeout() makes every read a
                # poll+recv syscall pair; SO_RCVTIMEO keeps it one
                # recv.  A timed-out recv surfaces as EAGAIN, which
                # BufferedReader maps to b"" — _serve_one treats that
                # as peer-gone and closes the connection, the right
                # outcome for a 120s-idle conn.  (The CLIENT pool must
                # NOT use this trick: there b"" would trigger the
                # stale-keep-alive retry and re-send a non-idempotent
                # RPC on a mere timeout.)
                tv = struct.pack("ll", int(self.idle_timeout),
                                 int(self.idle_timeout % 1 * 1e6))
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_RCVTIMEO, tv)
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO, tv)
            rf = conn.makefile("rb", buffering=1 << 16)
            conn = CountedConn(conn, info)
            while self._running:
                if not self._serve_one(conn, rf, peer_ip, info):
                    return
                info.requests += 1
                info.touch()
        except Exception:  # noqa: BLE001 — peer reset / TLS failure / ...
            pass
        finally:
            self.conns.remove(info)
            with self._conns_lock:
                self._conns.discard(raw)
            try:
                conn.close()
            except OSError:
                pass

    def _serve_conn_buffered(self, conn: socket.socket, peer_ip: str,
                             prefix: bytes, info) -> None:
        """Dedicated-thread serve for a connection the aio loop already
        read `prefix` bytes from — long-lived push streams
        (stream_paths) whose handlers block for the peer's lifetime
        and must not pin event-loop worker slots."""
        try:
            tv = struct.pack("ll", int(self.idle_timeout),
                             int(self.idle_timeout % 1 * 1e6))
            conn.setblocking(True)
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_RCVTIMEO, tv)
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO, tv)
            rf = SockReader(prefix, conn, info)
            cc = CountedConn(conn, info)
            info.state = "handling"
            while self._running:
                if not self._serve_one(cc, rf, peer_ip, info):
                    return
                info.requests += 1
                info.touch()
        except Exception:  # noqa: BLE001 — peer reset mid-stream
            pass
        finally:
            self.conns.remove(info)
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _serve_one(self, conn, rf, peer_ip: str = "", info=None) -> bool:
        """Handle one request; returns False when the connection is done."""
        line = rf.readline(65537)
        if not line:
            return False
        if not line.endswith(b"\n"):
            if len(line) < 65537:
                return False  # EOF mid-request-line: peer died
            self._respond(conn, "GET", 414, {"error": "URI too long"},
                          None, close=True)
            _drain_then_fin(conn, rf)
            return False
        try:
            method, target, version = \
                line.decode("latin-1").rstrip("\r\n").split(" ", 2)
        except ValueError:
            self._respond(conn, "GET", 400, {"error": "bad request line"},
                          None, close=True)
            return False
        try:
            headers = _read_headers(rf)
        except _LineTooLong:
            self._respond(conn, method, 431,
                          {"error": "header line too long"}, None,
                          close=True)
            _drain_then_fin(conn, rf)
            return False
        except ConnectionError:
            return False  # truncated request: never route it
        if headers.get("expect", "").lower() == "100-continue":
            conn.sendall(b"HTTP/1.1 100 Continue\r\n\r\n")
        chunked = headers.get("transfer-encoding", "").lower() == "chunked"
        keep = (version == "HTTP/1.1"
                and headers.get("connection", "").lower() != "close")

        # Fast path for the common hot-path target shape (`/vid,fid` —
        # no query string): skip urlparse + parse_qs entirely; they
        # cost ~15µs/request, which is real money at 10k req/core-sec.
        # Absolute-form targets (RFC 7230 §5.3.2 `GET http://h/p`) and
        # anything else not starting with "/" take the urlparse path.
        if "?" in target or not target.startswith("/"):
            parsed = urllib.parse.urlparse(target)
            raw_query = parsed.query
            req_path = parsed.path
            # keep_blank_values: S3-style flag params (?uploads,
            # ?tagging, ?delete) have no '=value'.  Underscore-prefixed
            # keys are RESERVED for header-derived values below — a
            # client must not be able to forge e.g.
            # ?_content_encoding=gzip and get a plaintext needle stored
            # with the compressed flag.
            query = {k: v[0] for k, v in urllib.parse.parse_qs(
                raw_query, keep_blank_values=True).items()
                if not k.startswith("_")}
        else:
            req_path = target
            raw_query = ""
            query = {}
        # Select request headers handlers care about (Range for partial
        # reads, Content-Type for upload mime) ride along in the query
        # dict under reserved keys.
        if peer_ip:
            # Peer address for the heavy-hitter tracker (hot client
            # IPs, stats/hotkeys.py) — reserved key, unforgeable like
            # the header-derived ones.
            query["_remote_addr"] = peer_ip
        if "range" in headers:
            query["_range_header"] = headers["range"]
        if "if-none-match" in headers:
            query["_if_none_match"] = headers["if-none-match"]
        if "if-modified-since" in headers:
            query["_if_modified_since"] = headers["if-modified-since"]
        if "content-type" in headers:
            query["_content_type"] = headers["content-type"]
        # Compression negotiation (volume server gzip path): the upload
        # side declares pre-compressed bodies, the read side declares
        # whether it can take gzip back.
        if "content-encoding" in headers:
            query["_content_encoding"] = headers["content-encoding"]
        if "accept-encoding" in headers:
            query["_accept_encoding"] = headers["accept-encoding"]
        if self.pass_headers:
            # Full header dict + raw query string for handlers that
            # authenticate requests (S3 sig v4 needs the exact header
            # set and query encoding).
            query["_headers"] = headers
            query["_raw_query"] = raw_query
            query["_method"] = method

        hit = self.routes.get((method, req_path))
        fn, stream = hit if hit else (None, False)
        prefix_args = None
        if fn is None:
            for m, prefix, pfn, pstream in self.prefix_routes:
                if m == method and req_path.startswith(prefix):
                    fn, stream = pfn, pstream
                    prefix_args = req_path
                    break
        # Wire-flow attribution (stats/flows.py): resolve the peer's
        # identity (self-declared node/role headers, else bare IP +
        # "client") and the transfer purpose (explicit header from our
        # own client > ?type=replicate > path heuristic) ONCE, bind
        # this thread's local identity so outbound hops made while
        # handling attribute to this server, and park the per-request
        # context for _respond's response-leg note.
        flow_peer = headers.get("x-weed-node", "") or peer_ip or "?"
        flow_peer_role = headers.get("x-weed-role", "") or "client"
        flow_purpose = _flows.resolve(
            method, req_path, headers.get("x-weed-purpose", ""),
            query.get("type", ""),
            headers.get("x-weed-priority", "") == "low")
        _flows.bind_thread(f"{self.host}:{self.port}",
                           self.flow_role or "server")
        _flows.begin_request(flow_peer, flow_peer_role, flow_purpose)
        # Read (or wrap) the body only after routing so a streaming
        # route never sees it buffered.
        if stream:
            body = BodyReader(rf,
                              None if chunked
                              else int(headers.get("content-length") or 0),
                              chunked)
            # Streamed request bodies count as the handler (and the
            # post-dispatch drain) consumes them; the op lands now.
            body.flow_note = \
                lambda n: _flows.LEDGER.note(
                    flow_purpose, "in", n, peer=flow_peer,
                    peer_role=flow_peer_role, ops=0)
            _flows.LEDGER.note(flow_purpose, "in", 0, peer=flow_peer,
                               peer_role=flow_peer_role)
        elif chunked:
            body = _read_chunked(rf)
        else:
            clen = int(headers.get("content-length") or 0)
            body = rf.read(clen) if clen else b""
            if clen and len(body) < clen:
                return False  # truncated request
        if not stream:
            _flows.LEDGER.note(flow_purpose, "in", len(body),
                               peer=flow_peer,
                               peer_role=flow_peer_role)
        args = (prefix_args, query, body) if prefix_args is not None \
            else (query, body)
        if fn is None:
            self._respond(conn, method, 404,
                          {"error": f"no route {method} {req_path}"},
                          None, close=not keep)
            return keep

        # Principal resolution (tenancy/): the tenant is the
        # X-Weed-Tenant header (stamped by the S3 gateway from the
        # authenticated identity, or set explicitly by a client), else
        # the collection as fallback; the originating client rides
        # X-Weed-Client on proxy legs (filer→volume) so hot-key
        # attribution names the real caller, not the proxy's IP.
        # Resolved ONCE here, parked in reserved query keys for the
        # handlers and in the thread-local principal context so every
        # outbound hop this thread makes auto-forwards it (same model
        # as the traceparent).
        tenant = headers.get("x-weed-tenant", "") \
            or query.get("collection", "")
        client = headers.get("x-weed-client", "") \
            or query.get("_remote_addr", "")
        query["_tenant"] = tenant
        if client:
            query["_client"] = client
        _tenant_ctx.set_principal(tenant, client)

        # Admission gate: classify into a lane (read / write /
        # internal) and acquire a slot — or shed with 429 +
        # Retry-After when the lane AND its bounded wait queue are
        # full.  The body was already read (or is drained below), so
        # keep-alive framing survives a shed.  Exempt paths
        # (introspection, heartbeats, push streams) skip the gate.
        lane = None
        queue_wait = 0.0
        if not _admission_exempt(req_path):
            lane = self.admission.lane_for(method, headers, query)
            if info is not None:
                info.lane = lane.name
            # Per-tenant QoS at the gate (token buckets): over-rate
            # tenants are refused BEFORE touching the lane, so their
            # excess never competes for queue slots.  Internal cluster
            # traffic is tenant-exempt, like the low-priority lane.
            if tenant and lane.name != "internal":
                wbytes = len(body) if isinstance(
                    body, (bytes, bytearray)) and \
                    method not in ("GET", "HEAD") else 0
                retry = self.admission.throttle(tenant, wbytes)
                if retry > 0.0:
                    if not self._finish_stream_body(body):
                        keep = False
                    self._observe_request(method, req_path, 429, 0.0)
                    self._respond(
                        conn, method, 429,
                        {"error": f"tenant {tenant!r} over rate "
                                  f"quota; retry"},
                        {"Retry-After": f"{retry:.3g}"},
                        close=not keep)
                    return keep
            t_gate = time.perf_counter()
            if not lane.enter("" if lane.name == "internal"
                              else tenant):
                if not self._finish_stream_body(body):
                    keep = False
                # Sheds are part of the error tail: count them in the
                # request histogram (status-class 4xx, with the REAL
                # time spent waiting in the bounded queue) and the SLO
                # burn windows' dedicated `shed` column — the tracker
                # keeps them out of the latency sketches, where a
                # refused request would fake a fast one.
                self._observe_request(method, req_path, 429,
                                      time.perf_counter() - t_gate)
                self._respond(
                    conn, method, 429,
                    {"error": f"overloaded: {lane.name} lane and its "
                              f"wait queue are full; retry"},
                    {"Retry-After":
                     f"{self.admission.retry_after:g}"},
                    close=not keep)
                return keep
            # Admitted (possibly after a bounded wait): the wait is
            # the request's `queue` phase — seeded into the ledger so
            # slow exemplars show admission pressure, not mystery wall.
            queue_wait = time.perf_counter() - t_gate
        try:
            return self._dispatch(conn, method, req_path, headers,
                                  query, body, fn, args, keep,
                                  queue_wait)
        finally:
            if lane is not None:
                lane.exit()
            # Keep-alive threads serve many requests: a stale
            # principal must not leak into the next one.
            _tenant_ctx.clear_principal()
            _flows.end_request()

    def _observe_request(self, method: str, req_path: str, status: int,
                         seconds: float, trace_id: str = "",
                         phases: dict | None = None) -> None:
        """One request observed: request counter + the labeled latency
        histogram (method / endpoint-family / status-class) + the SLO
        plane (windowed quantiles, burn windows, slow exemplars, the
        per-phase time budget).  Excludes the scrape endpoint where
        /metrics IS the scrape."""
        if self._metrics_route and req_path == "/metrics":
            return
        metrics = self.metrics
        if metrics is None:
            return
        family = endpoint_family(req_path,
                                 (method, req_path) in self.routes)
        _reg, counter, hist = metrics
        counter.inc(type=method)
        hist.observe(seconds, type=method, family=family,
                     status=f"{status // 100}xx")
        if self.slo is not None:
            self.slo.observe(family, method, status, seconds, trace_id,
                             phases)

    def _dispatch(self, conn, method: str, req_path: str,
                  headers: dict, query: dict, body, fn, args,
                  keep: bool, queue_wait: float = 0.0) -> bool:
        """Run the routed handler and write its response — the back
        half of _serve_one, split out so the admission gate can wrap
        it in one try/finally slot release."""
        t0 = time.perf_counter()
        # Tracing middleware: one server span per routed request,
        # continuing the caller's traceparent context (or head-sampling
        # a fresh root).  Scrape/debug endpoints are not traced — a
        # trace of the trace endpoint is pure noise — but only when the
        # path actually IS such a mounted route: on the filer, paths
        # like /metrics or /debug/build.log are user files (served by
        # prefix routes) and must trace like any other request (same
        # route-aware stance as the metrics exclusion below).  Every
        # exit path below MUST end the span: handler threads serve many
        # keep-alive requests, and a leaked thread-local span would
        # mis-parent every later request on the connection.
        tspan = None
        skip_trace = (self._metrics_route and req_path == "/metrics") \
            or (req_path.startswith("/debug/")
                and (method, req_path) in self.routes)
        if self.trace_service is not None and not skip_trace:
            tspan = _tracer.begin_server_span(
                self.trace_service, method, req_path,
                headers.get("traceparent", ""))
            if tspan is not None and query.get("_tenant"):
                tspan.attrs["tenant"] = query["_tenant"]
        # Phase ledger (stats/phases.py): opened on this thread for
        # the handler's lifetime; instrumentation anywhere below
        # (metered locks, disk wrappers, EC device timers, outbound
        # rpc) accumulates into it.  Seeded with the admission wait.
        ledger = _phases.start(queue_wait)

        def _observe(status: int) -> None:
            # Status is known at every exit (unlike the pre-SLO finally
            # block, which observed before the handler's tuple was
            # parsed) — that is what makes the status-class label and
            # the exemplar's trace id possible.  The ledger closes
            # FIRST (computing the `handler` residual) and rides the
            # span — phases must land before end_server_span snapshots
            # the span into the trace ring — then the SLO observation.
            # Materialization is LAZY: the budget dict is built only
            # for spans that will actually be recorded (sampled, or
            # slow enough for the always-sample trigger); fast
            # unsampled requests never pay it here, and the SLO layer
            # materializes on its own only for exemplars/sketch
            # samples.
            seconds = time.perf_counter() - t0
            ph = _phases.finish(ledger) if ledger is not None else None
            if tspan is not None and ph is not None and (
                    tspan.sampled
                    or seconds >= _tracer.slow_threshold_seconds()):
                tspan.attrs["phases"] = ph.to_dict()
            _tracer.end_server_span(tspan, status)
            self._observe_request(
                method, req_path, status, seconds,
                tspan.trace_id if tspan is not None else "", ph)

        try:
            result = fn(*args)
        except _fault.DropConnection:
            # Injected mid-exchange disconnect (fault `drop` kind): no
            # response bytes, just a dead connection — the client sees
            # EOF exactly as if the process was killed.
            _observe(500)
            return False
        except RpcError as e:
            _observe(e.status)
            if not self._finish_stream_body(body):
                keep = False
            self._respond(conn, method, e.status, {"error": e.message},
                          e.headers or None, close=not keep)
            return keep
        except ConnectionError as e:
            _observe(500)
            if isinstance(body, BodyReader) and body.truncated:
                # Truncated streaming body: the wire framing is gone,
                # no reliable response is possible.
                return False
            # Otherwise this is an UPSTREAM peer failure (a dead
            # master/volume behind rpc.call) — the client deserves a
            # 500, exactly as before streaming existed.
            if not self._finish_stream_body(body):
                keep = False
            self._respond(conn, method, 500,
                          {"error": f"{type(e).__name__}: {e}"},
                          None, close=not keep)
            return keep
        except Exception as e:  # noqa: BLE001
            _observe(500)
            if not self._finish_stream_body(body):
                keep = False
            self._respond(conn, method, 500,
                          {"error": f"{type(e).__name__}: {e}"},
                          None, close=not keep)
            return keep

        if not self._finish_stream_body(body):
            keep = False
        extra = None
        if isinstance(result, tuple):
            if len(result) == 3:
                status, payload, extra = result
            else:
                status, payload = result
        else:
            status, payload = 200, result
        # Span end covers handler execution, not the response write (a
        # slow reader streaming a 30GB body is not server time) — and
        # the histogram/SLO observation matches that boundary.
        _observe(status)
        self._respond(conn, method, status, payload, extra,
                      close=not keep)
        return keep

    @staticmethod
    def _finish_stream_body(body) -> bool:
        """Drain whatever a streaming handler left unread so the next
        keep-alive request parses; False = connection unusable."""
        if not isinstance(body, BodyReader):
            return True
        try:
            body.drain()
            return not body.truncated
        except ConnectionError:
            return False

    def _respond(self, conn, method: str, status: int, payload,
                 extra=None, close: bool = False) -> None:
        extra = dict(extra or {})
        reason = _REASONS.get(status, "Unknown")
        head = [f"HTTP/1.1 {status} {reason}"]
        if self.flow_role:
            # Self-identify so the client's flow ledger labels this
            # link's peer_role — paired with X-Weed-Node on requests.
            head.append(f"{_flows.ROLE_HEADER}: {self.flow_role}")

        # Response leg of the flow ledger: body/payload bytes only
        # (headers + chunked framing excluded on BOTH sides, so A->B
        # sent matches B<-A received).  Early error responses that
        # predate purpose resolution (bad request line, 414) have no
        # request context and are skipped.
        _req_flow = _flows.current_request()

        def _note_out(n: int, ops: int = 0,
                      _rq=_req_flow) -> None:
            if _rq is not None:
                _flows.LEDGER.note(_rq[2], "out", n, peer=_rq[0],
                                   peer_role=_rq[1], ops=ops)

        if hasattr(payload, "read"):
            # Stream any file-like payload (open file, upstream HTTP
            # response, or an unbounded push channel) without buffering
            # it: O(1MB) memory per in-flight large read.  Payloads
            # with a known size go out under Content-Length; sizeless
            # ones (no fileno — e.g. a live event stream) use chunked
            # transfer-encoding and end when read() returns b"".
            ctype = extra.pop("Content-Type", "application/octet-stream")
            size = extra.pop("Content-Length", None)
            if size is None and hasattr(payload, "fileno"):
                size = str(os.fstat(payload.fileno()).st_size)
            head.append(f"Content-Type: {ctype}")
            chunked = size is None
            if chunked:
                head.append("Transfer-Encoding: chunked")
            else:
                head.append(f"Content-Length: {size}")
            for k, v in extra.items():
                head.append(f"{k}: {v}")
            if close:
                head.append("Connection: close")
            # Header send happens INSIDE the payload's context: a peer
            # that RSTs before/during the head must still run
            # payload.close() (a NeedleSlice owns an fd).
            with payload:
                conn.sendall(("\r\n".join(head) + "\r\n\r\n")
                             .encode("latin-1"))
                _note_out(0, ops=1)
                if method != "HEAD":
                    sf = getattr(payload, "sendfile_to", None)
                    if sf is not None and not chunked \
                            and self.ssl_context is None:
                        # Zero-copy: the payload (a NeedleSlice or a
                        # spliced proxy body) moves its bytes
                        # kernel-side with os.sendfile/os.splice; TLS
                        # and chunked responses take the read loop.
                        # The flow note rides INTO the syscall loop —
                        # these bytes never transit userspace, so the
                        # ledger counts the syscall-returned totals.
                        sf(conn, note=_note_out)
                        nt = getattr(conn, "note_tx", None)
                        if nt is not None:
                            nt(int(size))
                    else:
                        while True:
                            chunk = payload.read(1 << 20)
                            if not chunk:
                                break
                            _note_out(len(chunk))
                            if chunked:
                                conn.sendall(b"%x\r\n" % len(chunk)
                                             + chunk + b"\r\n")
                            else:
                                conn.sendall(chunk)
                if chunked:
                    conn.sendall(b"0\r\n\r\n")
            return

        if isinstance(payload, (bytes, bytearray)):
            data = bytes(payload)
            ctype = extra.pop("Content-Type", "application/octet-stream")
        else:
            data = json.dumps(payload or {}).encode()
            ctype = extra.pop("Content-Type", "application/json")
        head.append(f"Content-Type: {ctype}")
        # HEAD handlers advertise the real body size without
        # materializing it.
        head.append(f"Content-Length: {extra.pop('Content-Length', None) or len(data)}")
        for k, v in extra.items():
            head.append(f"{k}: {v}")
        if close:
            head.append("Connection: close")
        buf = ("\r\n".join(head) + "\r\n\r\n").encode("latin-1")
        if method != "HEAD":
            buf += data
        _note_out(len(data) if method != "HEAD" else 0, ops=1)
        conn.sendall(buf)


# -- pooled HTTP client ------------------------------------------------------
# The reference's hot path assumes connection reuse (its Go http.Client
# pools transport connections; operation/upload_content.go:67).  A fresh
# TCP handshake per RPC capped the write path at ~360 req/s in bench_e2e,
# and http.client's email.parser header handling costs another
# ~0.25ms/request; this is a raw-socket keep-alive pool.

_client_ssl_context = None
_force_https = False


def set_client_ssl_context(ctx, force_https: bool = False) -> None:
    """Install the ssl.SSLContext used for https:// RPCs (security.toml
    TLS plane — see utils/security).  With force_https=True every
    outgoing http:// URL is dialed over TLS instead: cluster code builds
    addresses as `http://host:port`, and like the reference's gRPC dial
    options (security/tls.go LoadClientTLS) the transport — not each
    call site — decides whether the wire is encrypted.  Pass ctx=None to
    reset (plaintext)."""
    global _client_ssl_context, _force_https
    # Connections negotiated under the previous plane must not outlive
    # it: close everything idle AND bump the pool generation so
    # in-flight connections are dropped (not re-pooled) when released.
    # Context swap and generation bump happen under the pool lock so
    # acquire() can snapshot (ctx, gen) atomically — a dial racing the
    # rotation can't get the old identity stamped with the new gen.
    with _pool._lock:
        _client_ssl_context = ctx
        _force_https = bool(ctx) and force_https
        _pool.gen += 1
        for conns in _pool._idle.values():
            for conn in conns:
                conn.close()
        _pool._idle.clear()


class _Conn:
    """One pooled keep-alive connection."""

    __slots__ = ("sock", "rf", "key", "gen", "timeout")

    def __init__(self, sock: socket.socket, key: tuple, gen: int = 0,
                 timeout: float | None = None):
        self.sock = sock
        self.rf = sock.makefile("rb", buffering=1 << 16)
        self.key = key
        self.gen = gen
        self.timeout = timeout  # last settimeout applied (skip repeats)

    def close(self) -> None:
        # Shut the socket down FIRST: a reader blocked in recv() on
        # another thread holds the buffered-reader lock, and rf.close()
        # would wait for it (tens of seconds on an idle push stream);
        # shutdown() forces that recv to return immediately.
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.rf.close()
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class _Resp:
    """Response with lazily-read body (callers stream or read())."""

    __slots__ = ("status", "reason", "headers", "_rf", "_remaining",
                 "_chunks", "_chunk_iter", "_chunk_buf", "will_close",
                 "_done", "flow_note")

    def __init__(self, status, reason, headers, rf):
        self.status = status
        self.reason = reason
        self.headers = headers
        self._rf = rf
        # Wire-flow attribution: set by _request so body bytes count
        # as the call's "in" leg as the caller consumes them (the
        # spliced proxy path feeds the same note with its syscall
        # totals — see client.ProxiedBody._splice_to).
        self.flow_note = None
        self.will_close = headers.get("connection", "").lower() == "close"
        self._chunks = headers.get("transfer-encoding",
                                   "").lower() == "chunked"
        self._chunk_iter = None
        self._chunk_buf = b""
        if self._chunks:
            self._remaining = -1
        else:
            clen = headers.get("content-length")
            if clen is None:
                self.will_close = True  # read-until-close body
                self._remaining = -1
            else:
                self._remaining = int(clen)
        self._done = False

    def getheader(self, name: str, default=None):
        return self.headers.get(name.lower(), default)

    def read(self, n: int = -1) -> bytes:
        if self._done:
            return b""
        if self._chunks:
            data = self._read_chunked_n(n)
            if data and self.flow_note is not None:
                self.flow_note(len(data))
            return data
        if self._remaining < 0:  # until close
            data = self._rf.read() if n < 0 else self._rf.read(n)
            if not data or n < 0:
                self._done = True
            if data and self.flow_note is not None:
                self.flow_note(len(data))
            return data
        want = self._remaining if n < 0 else min(n, self._remaining)
        data = self._rf.read(want) if want else b""
        self._remaining -= len(data)
        if data and self.flow_note is not None:
            self.flow_note(len(data))
        if self._remaining == 0:
            self._done = True
        elif len(data) < want:
            # Early peer close with Content-Length unsatisfied is a
            # failed transfer, never a short success (http.client raised
            # IncompleteRead here; so do we).
            raise ConnectionError(
                f"incomplete read: peer closed with {self._remaining} "
                f"of {self.headers.get('content-length')} bytes unread")
        return data

    def read_any(self) -> bytes:
        """Next available piece — for live push streams, where read(n)
        would block accumulating n bytes that may never come.  Returns
        one chunked frame (or buffered leftover), b"" at end."""
        if self._done:
            return b""
        if self._chunks:
            if self._chunk_iter is None:
                self._chunk_iter = _iter_chunks(self._rf)
            if self._chunk_buf:
                out, self._chunk_buf = self._chunk_buf, b""
            else:
                try:
                    out = next(self._chunk_iter)
                except StopIteration:
                    self._done = True
                    return b""
            if out and self.flow_note is not None:
                self.flow_note(len(out))
            return out
        return self.read(65536)

    def _read_chunked_n(self, n: int) -> bytes:
        """Incremental chunked-body reader honoring the requested size
        (so call_to_file keeps its 1MB streaming for chunked upstreams),
        driven by the shared _chunk_pump state machine."""
        if self._chunk_iter is None:
            self._chunk_iter = _iter_chunks(self._rf)
        data, self._chunk_buf, exhausted = _chunk_pump(
            self._chunk_iter, self._chunk_buf, n)
        if exhausted:
            self._done = True
        return data


class _ConnPool:
    def __init__(self, max_idle_per_host: int = 32):
        self.max_idle = max_idle_per_host
        self._idle: dict[tuple, list[_Conn]] = {}
        # Metered (stats/contention.py): every outbound RPC takes this
        # lock at least once; a convoy here serializes the whole
        # client plane, so it must show up in the wait histogram.
        # Holds are dict pushes/pops — histogram only the pathological.
        self._lock = _contention.MeteredLock("rpc.pool",
                                             hold_observe_min=1e-3)
        # Bumped on TLS-plane changes: connections from an older
        # generation are never re-pooled, so a rotated client identity
        # can't keep riding sessions negotiated under the old one.
        self.gen = 0

    def acquire(self, scheme: str, host: str, port: int,
                timeout: float):
        """Returns (conn, was_reused).

        Client sockets keep Python-level settimeout (NOT the server's
        SO_RCVTIMEO trick): with a kernel timeout, a slow server is
        indistinguishable from a closed connection (readline returns
        b"" either way), and _request's stale-keep-alive retry would
        re-send non-idempotent RPCs on a mere timeout — exactly the
        case its comment forbids.  A Python timeout raises
        socket.timeout, which takes the no-retry path.  The timeout is
        only re-armed when it differs from the connection's last one
        (a setsockopt saved per pooled reuse).

        Per-host circuit breaker: an open breaker fails the acquire
        fast (BreakerOpen, before any socket work — even pooled reuse,
        whose idle conns likely predate the partition that opened it);
        connect failures feed it, and _request records the 5xx/success
        outcomes.  The rpc.connect fault point fires on every acquire —
        pooled or fresh — so an armed fault behaves like the host being
        unreachable, not like a pool-state lottery."""
        key = (scheme, host, port)
        hostport = f"{host}:{port}"
        breaker = _res.breaker_for(hostport)
        if not breaker.allow():
            raise _res.BreakerOpen(
                f"{hostport}: circuit breaker open")
        if _fault.ARMED:
            try:
                _fault.hit("rpc.connect", host=hostport)
            except Exception:
                breaker.record_failure()
                raise
        with self._lock:
            pool = self._idle.get(key)
            if pool:
                conn = pool.pop()
                if conn.timeout != timeout:
                    conn.sock.settimeout(timeout)
                    conn.timeout = timeout
                return conn, True
            # Snapshot the TLS plane atomically with its generation:
            # if a rotation lands during our handshake below, this
            # conn keeps the OLD gen and release() will drop it.
            ctx, gen = _client_ssl_context, self.gen
        try:
            sock = socket.create_connection((host, port),
                                            timeout=timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if scheme == "https":
                import ssl
                ctx = ctx or ssl.create_default_context()
                sock = ctx.wrap_socket(sock, server_hostname=host)
        except OSError as e:
            breaker.record_failure()
            # No request bytes hit the wire: mark the failure as
            # always-safe-to-retry for the RetryPolicy classifier.
            raise _res.ConnectError(f"{hostport}: {e}") from e
        return _Conn(sock, key, gen, timeout), False

    def release(self, conn: _Conn) -> None:
        with self._lock:
            if conn.gen == self.gen:
                pool = self._idle.setdefault(conn.key, [])
                if len(pool) < self.max_idle:
                    pool.append(conn)
                    return
        conn.close()


_pool = _ConnPool()


def _request(url: str, method: str, body, timeout: float,
             max_redirects: int = 3, req_headers: dict | None = None):
    """One pooled request; returns (_Resp, _Conn) with the body NOT yet
    read (callers stream or read()).  Retries exactly once on a stale
    reused keep-alive connection (failure before any response bytes)."""
    # Trace-context propagation: every outbound hop carries the active
    # span's traceparent so the downstream server span links to it.  An
    # explicit header wins — fan-out paths that run on worker threads
    # (replication, EC shard gather) pass their captured context in.
    tp = _tracer.current_traceparent()
    if tp and (req_headers is None or
               _tracer.TRACEPARENT_HEADER not in req_headers):
        req_headers = {**(req_headers or {}),
                       _tracer.TRACEPARENT_HEADER: tp}
    # Principal propagation rides the same way: the thread's resolved
    # tenant/client forward on every outbound hop so proxy legs
    # (filer→volume, volume→replica) keep the ORIGINAL attribution.
    _t = _tenant_ctx.current_tenant()
    if _t and (req_headers is None or
               "X-Weed-Tenant" not in req_headers):
        req_headers = {**(req_headers or {}), "X-Weed-Tenant": _t}
    _c = _tenant_ctx.current_client()
    if _c and (req_headers is None or
               "X-Weed-Client" not in req_headers):
        req_headers = {**(req_headers or {}), "X-Weed-Client": _c}
    # Manual split on the hot path: urlsplit costs ~7µs/request and
    # its internal cache misses on per-fid URLs.  Anything unusual
    # (IPv6 brackets, userinfo, missing scheme, query-with-no-path)
    # falls back to urlsplit.
    if url.startswith("http://"):
        scheme, rest = "http", url[7:]
    elif url.startswith("https://"):
        scheme, rest = "https", url[8:]
    else:
        scheme, rest = "", url
    slash = rest.find("/")
    netloc, path = (rest[:slash], rest[slash:]) if slash >= 0 \
        else (rest, "/")
    if not scheme or "@" in netloc or "[" in netloc or "?" in netloc:
        u = urllib.parse.urlsplit(url)
        scheme = u.scheme or "http"
        if scheme == "http" and _force_https:
            scheme = "https"  # before the port default: dial 443
        host = u.hostname or "127.0.0.1"
        port = u.port or (443 if scheme == "https" else 80)
        path = u.path or "/"
        if u.query:
            path += "?" + u.query
    else:
        if scheme == "http" and _force_https:
            scheme = "https"
        host, _, port_s = netloc.rpartition(":")
        if host and port_s.isdigit():
            port = int(port_s)
        else:
            host = netloc or "127.0.0.1"
            port = 443 if scheme == "https" else 80
    # Wire-flow attribution: resolve this call's purpose — an explicit
    # call-site header wins (validated loudly: our own call sites must
    # not ship typos), else the thread's purpose context, else the
    # path heuristic — and ALWAYS stamp it, so the server attributes
    # the same purpose and conservation holds by construction.  The
    # local identity (this process's server, when it has one) rides
    # X-Weed-Node/X-Weed-Role so the master's matrix pairs the link.
    flow_purpose = (req_headers or {}).get(_flows.PURPOSE_HEADER)
    if flow_purpose is not None:
        _flows.validate(flow_purpose)
    else:
        flow_purpose = _flows.current_purpose()
    if flow_purpose is None:
        flow_purpose = _flows.resolve(
            method, path, "", "",
            (req_headers or {}).get(PRIORITY_HEADER) == "low")
    if req_headers is None or _flows.PURPOSE_HEADER not in req_headers:
        req_headers = {**(req_headers or {}),
                       _flows.PURPOSE_HEADER: flow_purpose}
    flow_local = _flows.local_identity()[0]
    if flow_local and _flows.NODE_HEADER not in req_headers:
        req_headers = {**req_headers,
                       _flows.NODE_HEADER: flow_local,
                       _flows.ROLE_HEADER: _flows.local_identity()[1]}
    extra = ""
    for k, v in (req_headers or {}).items():
        extra += f"{k}: {v}\r\n"
    req = (f"{method} {path} HTTP/1.1\r\n"
           f"Host: {host}:{port}\r\n"
           f"Content-Length: {len(body) if body else 0}\r\n"
           f"{extra}"
           "\r\n").encode("latin-1")
    if body:
        req += body
    for attempt in (0, 1):
        conn, reused = _pool.acquire(scheme, host, port, timeout)
        try:
            # Fault points fire INSIDE the retry loop's try: an armed
            # `fail` surfaces as a peer reset and takes the exact
            # stale-keep-alive path a real one would.
            if _fault.ARMED:
                _fault.hit("rpc.send", host=f"{host}:{port}", url=url)
            if _fault.ARMED and "net.slow_client" in _fault.ARMED:
                # Slow-loris injector: send half the request, fire the
                # fault (a `delay:S` spec stalls here mid-request), then
                # send the rest.  A server whose idle timeout is shorter
                # than the stall reaps the connection, and the second
                # sendall/read surfaces it as a peer reset.
                half = max(1, len(req) // 2)
                conn.sock.sendall(req[:half])
                _fault.hit("net.slow_client", host=f"{host}:{port}",
                           url=url)
                conn.sock.sendall(req[half:])
            else:
                conn.sock.sendall(req)
            if _fault.ARMED:
                _fault.hit("rpc.recv", host=f"{host}:{port}", url=url)
            line = conn.rf.readline(65537)
            if not line:
                raise ConnectionResetError("server closed connection")
            parts = line.decode("latin-1").rstrip("\r\n").split(" ", 2)
            status = int(parts[1])
            reason = parts[2] if len(parts) > 2 else ""
            headers = _read_headers(conn.rf)
        except (ConnectionResetError, BrokenPipeError):
            # A reused keep-alive the server closed between our
            # requests: safe to retry once.  NOT for timeouts — a slow
            # server may still be processing, and a re-send would run a
            # non-idempotent RPC twice.
            conn.close()
            if reused and attempt == 0:
                continue
            raise
        except Exception:
            conn.close()
            raise
        while status == 100:  # ignore interim responses
            line = conn.rf.readline(65537)
            parts = line.decode("latin-1").rstrip("\r\n").split(" ", 2)
            status = int(parts[1])
            reason = parts[2] if len(parts) > 2 else ""
            headers = _read_headers(conn.rf)
        # Breaker bookkeeping: a 5xx answer (other than 503 — a live
        # server redirecting load, e.g. a follower master, is not a
        # sick one) counts toward opening the host's breaker; anything
        # else closes it.
        breaker = _res.breaker_for(f"{host}:{port}")
        if status >= 500 and status != 503:
            breaker.record_failure()
        else:
            breaker.record_success()
        resp = _Resp(status, reason, headers, conn.rf)
        # Flow ledger, client side: the request body went out (one
        # op), the response body counts in as the caller reads it.
        # Error-status bodies count too — their bytes crossed the
        # wire like any other.  Redirect legs each count separately.
        flow_peer = f"{host}:{port}"
        flow_prole = headers.get(_flows.ROLE_HEADER.lower(), "") \
            or "server"
        _flows.LEDGER.note(flow_purpose, "out",
                           len(body) if body else 0, peer=flow_peer,
                           peer_role=flow_prole, local=flow_local)
        _flows.LEDGER.note(flow_purpose, "in", 0, peer=flow_peer,
                           peer_role=flow_prole, local=flow_local)
        resp.flow_note = \
            lambda n, _p=flow_purpose, _peer=flow_peer, \
            _pr=flow_prole, _l=flow_local: \
            _flows.LEDGER.note(_p, "in", n, peer=_peer, peer_role=_pr,
                               local=_l, ops=0)
        if status in (301, 302, 307, 308) and max_redirects > 0:
            location = resp.getheader("location")
            if location:
                try:
                    resp.read()
                    _finish(conn, resp)
                except Exception:  # noqa: BLE001 — truncated redirect body
                    conn.close()
                return _request(
                    urllib.parse.urljoin(url, location), method, body,
                    timeout, max_redirects - 1, req_headers)
        return resp, conn
    raise AssertionError("unreachable")


def _finish(conn: _Conn, resp: _Resp) -> None:
    """Return a fully-read connection to the pool (or close it)."""
    if resp.will_close or not resp._done:
        conn.close()
    else:
        _pool.release(conn)


def _raise_rpc_error(resp: _Resp, data: bytes) -> None:
    try:
        message = json.loads(data or b"{}").get(
            "error", f"HTTP Error {resp.status}: {resp.reason}")
    except Exception:  # noqa: BLE001
        message = f"HTTP Error {resp.status}: {resp.reason}"
    # Surface the server's pacing hint (admission sheds, drain
    # refusals): RetryPolicy uses it as a backoff floor on 429/503.
    retry_after = None
    ra = resp.getheader("retry-after")
    if ra:
        try:
            retry_after = float(ra)
        except ValueError:
            pass
    raise RpcError(resp.status, message, retry_after=retry_after)


def call(url: str, method: str = "GET", body: bytes | None = None,
         timeout: float = 10.0, headers: dict | None = None):
    """HTTP call returning parsed JSON (dict) or raw bytes."""
    # Phase attribution: a handler blocked here is waiting on a
    # downstream server, not burning its own CPU — the whole
    # round-trip (send + response body) lands in `rpc_downstream`.
    with _phases.phase("rpc_downstream"):
        resp, conn = _request(url, method, body, timeout,
                              req_headers=headers)
        try:
            if method == "HEAD":
                data = b""        # no body follows a HEAD response
                resp._done = True  # even when Content-Length says so
            else:
                data = resp.read()
        except Exception:
            conn.close()
            raise
        _finish(conn, resp)
    if resp.status >= 400:
        _raise_rpc_error(resp, data)
    if (resp.getheader("content-type") or "").startswith(
            "application/json"):
        return json.loads(data or b"{}")
    return data


def call_status(url: str, method: str = "GET",
                body: bytes | None = None, timeout: float = 10.0,
                headers: dict | None = None):
    """Like call() but returns (status, parsed-body) without raising on
    HTTP errors — for endpoints whose status code IS the answer and
    whose error responses carry a full JSON document
    (/cluster/healthz)."""
    with _phases.phase("rpc_downstream"):
        resp, conn = _request(url, method, body, timeout,
                              req_headers=headers)
        try:
            data = resp.read()
        except Exception:
            conn.close()
            raise
        _finish(conn, resp)
    if (resp.getheader("content-type") or "").startswith(
            "application/json"):
        try:
            return resp.status, json.loads(data or b"{}")
        except ValueError:
            pass
    return resp.status, data


def call_to_file(url: str, path: str, timeout: float = 600.0,
                 headers: dict | None = None) -> int:
    """Stream a GET response to a file in chunks; returns byte count.
    Bulk transfers (volume/shard copies) must never buffer a 30GB .dat
    in memory (the reference streams CopyFile in chunks too).  Writes
    land in a `.dl.tmp` sibling renamed into place only on a complete
    transfer, so a truncated download never masquerades as a valid
    shard/volume file at the destination path."""
    with _phases.phase("rpc_downstream"):
        resp, conn = _request(url, "GET", None, timeout,
                              req_headers=headers)
        if resp.status >= 400:
            try:
                data = resp.read()
            except Exception:
                conn.close()
                raise
            _finish(conn, resp)
            _raise_rpc_error(resp, data)
        tmp = path + ".dl.tmp"
        try:
            with open(tmp, "wb") as f:
                total = 0
                while True:
                    chunk = resp.read(1 << 20)
                    if not chunk:
                        break
                    f.write(chunk)
                    total += len(chunk)
            clen = resp.getheader("content-length")
            if clen is not None and total != int(clen):
                raise ConnectionError(
                    f"incomplete download: got {total} of {clen} bytes")
        except Exception:
            conn.close()
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
        os.replace(tmp, path)
        _finish(conn, resp)
        return total


class StreamHandle:
    """A live NDJSON push stream (EventStream consumer side): iterate
    `.events()` for parsed docs; `.close()` tears the connection down
    IMMEDIATELY from any thread (urllib's close would block draining
    the endless body).  An optional stop_event makes shutdown
    deterministic even if close() races the handle's creation: the
    server's ≤heartbeat-interval blank lines wake the reader, which
    checks the event on every wakeup — not just on data."""

    def __init__(self, resp, conn, stop_event=None):
        self._resp = resp
        self._conn = conn
        self._stop = stop_event
        self._closed = False

    def close(self) -> None:
        self._closed = True
        self._conn.close()

    def _should_stop(self) -> bool:
        return self._closed or (self._stop is not None
                                and self._stop.is_set())

    def events(self):
        buf = b""
        try:
            while not self._should_stop():
                chunk = self._resp.read_any()
                if not chunk or self._should_stop():
                    return
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if line.strip():
                        yield json.loads(line)
        except (OSError, ConnectionError, ValueError):
            return  # closed mid-read (including via close())
        finally:
            self._conn.close()


def call_stream(url: str, timeout: float = 60.0,
                stop_event=None) -> StreamHandle:
    """Open a long-lived push stream (EventStream server side)."""
    resp, conn = _request(url, "GET", None, timeout)
    if resp.status >= 400:
        data = resp.read()
        conn.close()
        _raise_rpc_error(resp, data)
    return StreamHandle(resp, conn, stop_event)


def call_json(url: str, method: str = "POST", payload: dict | None = None,
              timeout: float = 10.0) -> dict:
    body = json.dumps(payload or {}).encode()
    out = call(url, method, body, timeout)
    assert isinstance(out, dict)
    return out
