"""Unified RPC resilience policy: retry/backoff + per-host circuit
breakers.

Before this module every call site hand-rolled (or omitted) its own
retry.  Now there is ONE policy object (`RetryPolicy`: exponential
backoff with full jitter, a per-attempt timeout under a total deadline
budget, idempotency-aware classification) and ONE per-host breaker
(`CircuitBreaker`: closed → open after K consecutive connect/5xx
failures, half-open probe after a cooldown), and the degraded paths —
`WeedClient.upload` re-assign, replication fan-out, the EC rebuild
shard gather — route through them.

Idempotency rule (extends rpc._request's stale-keep-alive rule): a
non-idempotent body must NEVER be re-sent after bytes may have hit the
wire.  The transport marks the one failure class where that is provably
safe — `ConnectError`, raised when the dial itself failed — and
`RetryPolicy.run` retries non-idempotent calls only on it (and on
`BreakerOpen`, which fails before any socket work at all).

This module deliberately imports nothing from cluster.rpc (rpc imports
it); classification is by exception type and a duck-typed `.status`
attribute.

Knobs (env, read at import as defaults; server flags in README):

- SEAWEEDFS_TPU_BREAKER_THRESHOLD  consecutive failures to open
                                   (default 5; 0 disables breakers)
- SEAWEEDFS_TPU_BREAKER_COOLDOWN   seconds open before a half-open
                                   probe (default 2.0)
"""

from __future__ import annotations

import random
import threading
import time

from ..stats.metrics import Counter, Gauge
from ..utils import env_float as _env_float


class ConnectError(ConnectionError):
    """Failure before any request bytes hit the wire (dial/TLS
    handshake).  Always safe to retry, idempotent or not."""


class BreakerOpen(ConnectionError):
    """Fast-fail: the per-host circuit breaker is open.  No socket was
    touched, so retrying (elsewhere, or after the cooldown) is safe."""


rpc_retries_total = Counter(
    "SeaweedFS_rpc_retries_total",
    "RPC retries by failure class", ("reason",))


# -- circuit breaker ---------------------------------------------------------

CLOSED, HALF_OPEN, OPEN = 0, 1, 2
_STATE_NAMES = {CLOSED: "closed", HALF_OPEN: "half-open", OPEN: "open"}


BREAKER_THRESHOLD = int(_env_float("SEAWEEDFS_TPU_BREAKER_THRESHOLD", 5))
BREAKER_COOLDOWN = _env_float("SEAWEEDFS_TPU_BREAKER_COOLDOWN", 2.0)


class CircuitBreaker:
    """Per-host breaker guarding the client pool's dials.

    closed: all traffic flows; K consecutive failures (connect errors,
    or 5xx answers other than 503 — a 503 is a live server saying "go
    elsewhere", not a sick one) open it.  open: every acquire fails
    fast with BreakerOpen until `cooldown` elapses.  half-open: ONE
    probe request is let through; success closes the breaker, failure
    re-opens it for another cooldown.
    """

    __slots__ = ("threshold", "cooldown", "host", "_state", "_failures",
                 "_opened_at", "_probe_at", "_lock")

    def __init__(self, threshold: int = BREAKER_THRESHOLD,
                 cooldown: float = BREAKER_COOLDOWN, host: str = ""):
        self.threshold = threshold
        self.cooldown = cooldown
        self.host = host
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_at = 0.0
        self._lock = threading.Lock()

    @property
    def state(self) -> str:
        return _STATE_NAMES[self._state]

    def _emit(self, type_: str, **attrs) -> None:
        """Journal a state transition — called OUTSIDE the breaker lock
        (the journal is cheap but must never nest under it)."""
        from ..events import emit as emit_event
        emit_event(type_, severity="warn" if type_ == "breaker.open"
                   else "info", host=self.host, **attrs)

    def allow(self) -> bool:
        # Hot path: a closed breaker (the universal steady state) is one
        # lock-free attribute check.
        if self._state == CLOSED or self.threshold <= 0:
            return True
        now = time.monotonic()
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if now - self._opened_at < self.cooldown:
                    return False
                self._state = HALF_OPEN
                self._probe_at = now
                half_open = True
            else:
                # HALF_OPEN: one probe in flight.  If the prober died
                # without recording an outcome, let a new probe through
                # after another cooldown rather than staying stuck open.
                if now - self._probe_at >= self.cooldown:
                    self._probe_at = now
                    return True
                return False
        if half_open:
            self._emit("breaker.half_open")
        return True  # the half-open probe

    def record_success(self) -> None:
        if self._state == CLOSED and self._failures == 0:
            return  # lock-free steady state
        with self._lock:
            closed = self._state != CLOSED
            self._state = CLOSED
            self._failures = 0
        if closed:
            self._emit("breaker.close")

    def record_failure(self) -> None:
        if self.threshold <= 0:
            return
        opened = reopened = False
        with self._lock:
            if self._state == HALF_OPEN:
                self._state = OPEN
                self._opened_at = time.monotonic()
                opened = reopened = True
            else:
                self._failures += 1
                if self._failures >= self.threshold:
                    opened = self._state != OPEN
                    self._state = OPEN
                    self._opened_at = time.monotonic()
        if opened:
            self._emit("breaker.open", failures=self.threshold,
                       probe_failed=reopened)


_breakers: dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def breaker_for(hostport: str) -> CircuitBreaker:
    b = _breakers.get(hostport)
    if b is None:
        with _breakers_lock:
            b = _breakers.setdefault(hostport,
                                     CircuitBreaker(host=hostport))
    return b


def reset_breakers() -> None:
    """Forget all breaker state (tests; config reload)."""
    with _breakers_lock:
        _breakers.clear()


def _breaker_states() -> dict:
    with _breakers_lock:
        return {(hp,): float(b._state) for hp, b in _breakers.items()}


breaker_state_gauge = Gauge(
    "SeaweedFS_rpc_breaker_state",
    "per-host circuit breaker state (0 closed, 1 half-open, 2 open)",
    ("server",), callback=_breaker_states)


# -- retry policy ------------------------------------------------------------

class RetryPolicy:
    """Exponential backoff with full jitter under a total deadline.

    run(fn, idempotent=...) calls fn(attempt, timeout) up to
    max_attempts times.  `timeout` is the per-attempt budget, clipped
    to whatever remains of total_deadline — a dead peer costs one
    bounded attempt, never the whole deadline.

    Classification (which failures are retried):

    - ConnectError / BreakerOpen: no bytes hit the wire — retried
      always ("connect").
    - .status == 429 (admission shed — refused before the handler
      ran): retried always ("shed"), honoring the server's
      Retry-After pacing hint.
    - exceptions with .status in retry_statuses (5xx): the server
      answered — retried only when `idempotent` ("status").
    - other OSError/ConnectionError (reset mid-exchange, timeout):
      bytes may have been processed — retried only when `idempotent`
      ("io").

    Everything else (4xx answers, application errors) raises
    immediately.
    """

    def __init__(self, max_attempts: int = 3,
                 base_delay: float = 0.05, max_delay: float = 2.0,
                 per_attempt_timeout: float = 10.0,
                 total_deadline: float | None = None,
                 retry_statuses: tuple[int, ...] = (429, 500, 502,
                                                    503, 504),
                 rng: random.Random | None = None):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.per_attempt_timeout = per_attempt_timeout
        self.total_deadline = total_deadline
        self.retry_statuses = retry_statuses
        self._rng = rng or random

    def backoff(self, attempt: int) -> float:
        """Full-jitter delay before retry number `attempt`+1: uniform
        in [0, min(max_delay, base * 2^attempt)] — decorrelates a
        thundering herd of clients retrying the same dead server."""
        cap = min(self.max_delay, self.base_delay * (2 ** attempt))
        return self._rng.uniform(0.0, cap)

    def classify(self, exc: BaseException,
                 idempotent: bool) -> str | None:
        """Retry reason for `exc`, or None = do not retry."""
        if isinstance(exc, (ConnectError, BreakerOpen)):
            return "connect"
        status = getattr(exc, "status", None)
        if status is not None:
            if status == 429 and 429 in self.retry_statuses:
                # Admission shed: the server refused BEFORE running
                # the handler, so retrying never replays a
                # non-idempotent body — safe like ConnectError.
                return "shed"
            if status in self.retry_statuses and idempotent:
                return "status"
            return None
        if isinstance(exc, (OSError, ConnectionError)) and idempotent:
            return "io"
        return None

    def run(self, fn, idempotent: bool = True, on_retry=None):
        """fn(attempt, timeout) with retries.  `on_retry(exc, attempt)`
        is called before each backoff sleep (logging hooks)."""
        deadline = (time.monotonic() + self.total_deadline
                    if self.total_deadline else None)
        last: BaseException | None = None
        for attempt in range(self.max_attempts):
            timeout = self.per_attempt_timeout
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                timeout = min(timeout, remaining)
            try:
                return fn(attempt, timeout)
            except BaseException as e:  # noqa: BLE001 — reclassified
                reason = self.classify(e, idempotent)
                if reason is None or attempt == self.max_attempts - 1:
                    raise
                last = e
                rpc_retries_total.inc(reason=reason)
                if on_retry is not None:
                    on_retry(e, attempt)
                delay = self.backoff(attempt)
                # A 429/503 answer may carry the server's own pacing
                # hint (Retry-After from admission sheds and drain
                # refusals): honor it as a floor under the jittered
                # backoff, capped at the per-attempt budget so a
                # hostile/buggy header can't park the client.
                retry_after = getattr(e, "retry_after", None)
                if retry_after:
                    delay = max(delay, min(float(retry_after),
                                           self.per_attempt_timeout))
                if deadline is not None:
                    delay = min(delay,
                                max(0.0, deadline - time.monotonic()))
                time.sleep(delay)
        if last is not None:
            raise last
        raise TimeoutError(
            f"retry deadline {self.total_deadline}s exhausted before "
            "the first attempt")
