"""Raft consensus for master HA.

Reference: the master embeds a raft node (weed/server/raft_server.go,
github.com/chrislusf/raft) whose single state-machine command is
MaxVolumeId (weed/topology/cluster_commands.go) — the leader owns volume
id assignment, followers proxy mutating requests to the leader
(master_server.go:155).

This is a from-scratch Raft (election + log replication + persistence +
snapshot/compaction + single-server membership change), not a port:
RPCs ride the same JSON/HTTP plane as the rest of the cluster (mounted
on the master's own server), and the state machine is a callback so the
master wires MaxVolumeId (or anything else) in.

Snapshotting: when the applied log grows past `compact_threshold`
entries, the node asks the state machine for a snapshot (snapshot_fn),
persists it (tmp+fsync+rename next to the log), and truncates the
journal — the log is bounded on a long-lived cluster.  A follower so
far behind that the needed entries were compacted away receives the
snapshot over /raft/install_snapshot (InstallSnapshot, Raft §7).

Membership: one server at a time via add_server()/remove_server()
(Raft thesis §4.1 single-server changes — no joint consensus needed
when changes don't overlap).  The configuration is a log entry applied
on APPEND (latest-config-in-log rule) and included in snapshots.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Callable

from . import rpc

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class NotLeader(Exception):
    def __init__(self, leader: str | None):
        super().__init__(f"not the leader (leader={leader})")
        self.leader = leader


class RaftNode:
    """One consensus participant.

    `node_id` / `peers` are base URLs (http://host:port) whose HTTP
    servers route /raft/* to this node via `mount()`.  `apply_fn(cmd)`
    is invoked exactly once per committed entry, in log order, on every
    node.
    """

    def __init__(self, node_id: str, peers: list[str],
                 apply_fn: Callable[[dict], None],
                 state_path: str | None = None,
                 election_timeout: tuple[float, float] = (0.6, 1.2),
                 heartbeat_interval: float = 0.15,
                 snapshot_fn: Callable[[], dict] | None = None,
                 restore_fn: Callable[[dict], None] | None = None,
                 compact_threshold: int = 1000):
        self.id = node_id
        self.peers = [p for p in peers if p != node_id]
        # The construction-time membership: the config baseline when no
        # snapshot and no raft_config log entry says otherwise.
        self._initial_peers = sorted(set(peers) | {node_id})
        self._config_lock = threading.Lock()
        self.apply_fn = apply_fn
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self.compact_threshold = compact_threshold
        self.state_path = state_path
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval

        # Volatile state first — snapshot loading touches it.
        self.state = FOLLOWER
        self.leader_id: str | None = None
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}
        self._wake_events: dict[str, threading.Event] = {}
        # Peers removed from the config but still owed the removal
        # entry: peer -> log index after which replication stops.  A
        # removed server must SEE its removal or it never learns to
        # stop campaigning.
        self._parting: dict[str, int] = {}
        # Membership: a node removed from the configuration stops
        # electing itself (it keeps serving reads/redirects).
        self.in_config = True

        # Persistent state (term, vote, log, snapshot).
        self.current_term = 0
        self.voted_for: str | None = None
        self.log: list[dict] = []  # {"term": int, "cmd": dict}
        # Compaction base: entries 1..log_base live in the snapshot;
        # self.log[0] is entry log_base+1.
        self.log_base = 0
        self.log_base_term = 0
        self._snap_state: dict = {}
        self._snap_peers: list[str] = []
        # Journal lines written since the last rewrite — rewrites are
        # amortized (see _maybe_compact_locked).
        self._journal_lines = 0
        self._load_state()

        # Everything at or below log_base lives in the snapshot and is
        # committed+applied by definition.
        self.commit_index = self.log_base
        self.last_applied = self.log_base

        self._lock = threading.RLock()
        self._commit_cv = threading.Condition(self._lock)
        # Serializes state-machine mutations (apply_fn batches vs
        # restore_fn on InstallSnapshot).  Without it a snapshot can be
        # restored between an apply batch's last_applied bump and the
        # apply_fn calls, and the stale commands then land ON TOP of
        # the newer snapshot state.  Ordering: _sm_lock before _lock,
        # never the reverse.
        self._sm_lock = threading.Lock()
        self._last_heartbeat = time.monotonic()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # Outbound RPC transport — injectable so fault-injection tests
        # can partition nodes (raise on blocked links) without touching
        # the network stack.  Production uses the pooled JSON client.
        self.transport: Callable = rpc.call_json

    # -- persistence ---------------------------------------------------------
    # Meta (term/vote) is a tiny JSON rewritten on change; the log is an
    # append-only JSONL journal — appending an entry is O(1), not a
    # rewrite of history.  Conflict truncation (rare) rewrites the
    # journal.

    def _log_path(self) -> str | None:
        return self.state_path + ".log" if self.state_path else None

    def _snap_path(self) -> str | None:
        return self.state_path + ".snap" if self.state_path else None

    @staticmethod
    def _fsync_dir(path: str) -> None:
        """fsync the parent directory so a rename/create survives power
        loss — without this the fsynced file's directory entry may
        still be lost, forgetting a granted vote."""
        fd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                     os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def _load_state(self) -> None:
        if not self.state_path:
            return
        embedded = False
        try:
            with open(self.state_path) as f:
                d = json.load(f)
            self.current_term = d.get("term", 0)
            self.voted_for = d.get("voted_for")
            # Migration: early versions embedded the log in the meta file.
            self.log = d.get("log", [])
            embedded = bool(self.log)
        except (OSError, json.JSONDecodeError):
            pass
        try:
            with open(self._snap_path()) as f:
                snap = json.load(f)
            self._install_snapshot_locked(snap, persist=False)
        except (OSError, json.JSONDecodeError):
            pass
        try:
            with open(self._log_path()) as f:
                for line in f:
                    if not line.strip():
                        continue
                    e = json.loads(line)
                    # Journal entries carry their global index so a
                    # crash between snapshot write and journal rewrite
                    # cannot graft stale pre-compaction entries after
                    # the new log_base (Log Matching would break).
                    i = e.pop("i", None)
                    if i is None:  # legacy journal: sequential from 1
                        i = self.log_base + len(self.log) + 1
                    if i <= self.log_base:
                        continue  # already inside the snapshot
                    if i != self.log_base + len(self.log) + 1:
                        break  # gap/stale tail: discard the rest
                    self.log.append(e)
                    self._maybe_apply_config(e)
        except (OSError, json.JSONDecodeError):
            pass
        if embedded:  # move embedded entries into the journal once
            self._rewrite_log()
            self._save_meta()

    def _save_meta(self) -> None:
        if not self.state_path:
            return
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"term": self.current_term,
                       "voted_for": self.voted_for}, f)
            f.flush()
            os.fsync(f.fileno())  # a granted vote must survive power loss
        os.replace(tmp, self.state_path)
        self._fsync_dir(self.state_path)

    def _append_log(self, entries: list[dict],
                    first_index: int) -> None:
        """Journal a suffix; each line records its global index."""
        path = self._log_path()
        if not path or not entries:
            return
        created = not os.path.exists(path)
        with open(path, "a") as f:
            for off, e in enumerate(entries):
                rec = dict(e, i=first_index + off)
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            f.flush()
            # An acked log suffix is a durability promise to the leader.
            os.fsync(f.fileno())
        self._journal_lines += len(entries)
        if created:
            self._fsync_dir(path)

    def _rewrite_log(self) -> None:
        path = self._log_path()
        if not path:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            for off, e in enumerate(self.log):
                rec = dict(e, i=self.log_base + 1 + off)
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._fsync_dir(path)
        self._journal_lines = len(self.log)

    def _save_state(self) -> None:  # kept for vote/term call sites
        self._save_meta()

    # -- snapshot / compaction (Raft §7) -------------------------------------

    def _write_snapshot_file(self, snap: dict) -> None:
        path = self._snap_path()
        if not path:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._fsync_dir(path)

    def _current_snapshot(self) -> dict:
        return {"last_index": self.log_base,
                "last_term": self.log_base_term,
                "state": self._snap_state,
                "peers": list(self._snap_peers
                              or self._initial_peers)}

    def _install_snapshot_locked(self, snap: dict,
                                 persist: bool = True) -> None:
        """Replace log prefix (or everything) with a snapshot."""
        self.log_base = snap["last_index"]
        self.log_base_term = snap.get("last_term", 0)
        self._snap_state = snap.get("state", {})
        self._snap_peers = list(snap.get("peers", []))
        if self.restore_fn is not None:
            try:
                self.restore_fn(self._snap_state)
            except Exception:  # noqa: BLE001 — state machine bug must
                pass           # not kill consensus
        if snap.get("peers"):
            self._set_peers(snap["peers"])
        self.log = []
        if persist:
            self._write_snapshot_file(snap)
            self._rewrite_log()

    def _maybe_compact_locked(self) -> None:
        """Snapshot + truncate once the applied portion of the log
        exceeds the threshold — bounds the journal on long-lived
        clusters."""
        if self.last_applied - self.log_base < self.compact_threshold:
            return
        state = self.snapshot_fn() if self.snapshot_fn else {}
        last = self.last_applied
        last_term = self._term_at(last)
        # The snapshot's membership is the config AS OF its last
        # entry — an uncommitted config later in the log must not be
        # baked into the baseline (conflict truncation could revert it).
        self._snap_peers = self._config_at(last)
        del self.log[: last - self.log_base]
        self.log_base = last
        self.log_base_term = last_term
        self._snap_state = state
        self._write_snapshot_file(self._current_snapshot())
        # The journal rewrite is AMORTIZED: every line carries its
        # global index, so the loader already skips entries at or below
        # log_base — correctness never needs the rewrite, only disk
        # bounding does.  Rewriting on every compaction would hold the
        # raft lock across a multi-fsync pass and (on a slow disk)
        # starve heartbeats into spurious elections.
        if self._journal_lines > 4 * self.compact_threshold:
            self._rewrite_log()

    # -- membership (thesis §4.1 single-server changes) ----------------------

    def _set_peers(self, peer_ids: list[str]) -> None:
        new = [p for p in peer_ids if p != self.id]
        self.in_config = self.id in peer_ids
        added = [p for p in new if p not in self.peers]
        removed = [p for p in self.peers if p not in new]
        self.peers = new
        for p in removed:
            if self.state == LEADER and p in self.match_index:
                # Keep replicating until the peer HAS its removal entry
                # (it must learn to stop campaigning), then its loop
                # tears the structures down.
                self._parting[p] = self._last_log_index()
                ev = self._wake_events.get(p)
                if ev is not None:
                    ev.set()
            else:
                self.next_index.pop(p, None)
                self.match_index.pop(p, None)
                ev = self._wake_events.pop(p, None)
                if ev is not None:
                    ev.set()  # its loop exits on the config check
        if self.state == LEADER:
            nxt = self._last_log_index() + 1
            for p in added:
                self._parting.pop(p, None)  # re-added mid-parting
                if p in self.match_index:
                    continue  # replicator already alive
                self.next_index.setdefault(p, nxt)
                self.match_index.setdefault(p, 0)
                self._wake_events[p] = threading.Event()
                threading.Thread(
                    target=self._peer_loop, args=(p, self.current_term),
                    daemon=True, name=f"raft-repl-{p}").start()

    def _maybe_apply_config(self, entry: dict) -> None:
        """Configuration entries take effect as soon as they are in the
        log (latest-config-in-log rule), commit or not."""
        cmd = entry.get("cmd", {})
        if cmd.get("op") == "raft_config":
            self._set_peers(cmd["peers"])

    def _config_at(self, index: int) -> list[str]:
        """Membership as of a log index: the persisted snapshot
        baseline plus every config entry at or below `index`."""
        peers = list(self._snap_peers or self._initial_peers)
        for off, e in enumerate(self.log):
            if self.log_base + 1 + off > index:
                break
            if e.get("cmd", {}).get("op") == "raft_config":
                peers = e["cmd"]["peers"]
        return peers

    def _recompute_config(self) -> None:
        """After a conflict truncation, the live config is the latest
        one still in snapshot+log — NOT the possibly-truncated config
        this node had applied."""
        self._set_peers(self._config_at(self._last_log_index()))

    def _config_change(self, peers: list[str], timeout: float) -> None:
        # _config_lock serializes concurrent add/remove end to end:
        # without it two changes could both pass the in-flight scan and
        # the later one would silently erase the earlier (the
        # single-server-change safety argument needs them ordered).
        with self._config_lock:
            with self._lock:
                if self.state != LEADER:
                    raise NotLeader(self.leader_id)
                for i in range(self.commit_index + 1,
                               self._last_log_index() + 1):
                    if self.log[i - self.log_base - 1]["cmd"].get("op") \
                            == "raft_config":
                        raise RuntimeError(
                            "a membership change is already in flight")
            self.propose({"op": "raft_config",
                          "peers": sorted(set(peers))},
                         timeout=timeout)

    def add_server(self, peer: str, timeout: float = 10.0) -> None:
        """Grow the cluster by one voter (leader only)."""
        with self._lock:
            members = set(self.peers) | {self.id, peer}
        self._config_change(sorted(members), timeout)

    def remove_server(self, peer: str, timeout: float = 10.0) -> None:
        """Shrink the cluster by one voter (leader only; a leader does
        not remove itself — transfer leadership first)."""
        if peer == self.id:
            raise ValueError("leader cannot remove itself; demote a "
                             "follower or stop this node instead")
        with self._lock:
            members = (set(self.peers) | {self.id}) - {peer}
        self._config_change(sorted(members), timeout)

    # -- lifecycle -----------------------------------------------------------

    def mount(self, server: rpc.JsonHttpServer) -> None:
        server.route("POST", "/raft/request_vote", self._h_request_vote)
        server.route("POST", "/raft/append_entries",
                     self._h_append_entries)
        server.route("POST", "/raft/install_snapshot",
                     self._h_install_snapshot)
        server.route("GET", "/raft/status", self._h_status)

    def start(self) -> None:
        for target, name in ((self._election_loop, "raft-election"),
                             (self._apply_loop, "raft-apply")):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        with self._commit_cv:
            self._commit_cv.notify_all()
        for t in self._threads:
            t.join(timeout=2)

    # -- log helpers (1-based global indices; log_base = snapshot) -----------

    def _last_log_index(self) -> int:
        return self.log_base + len(self.log)

    def _term_at(self, index: int) -> int:
        if index == self.log_base:
            return self.log_base_term
        i = index - self.log_base
        return self.log[i - 1]["term"] if 1 <= i <= len(self.log) else 0

    # -- RPC handlers --------------------------------------------------------

    def _h_request_vote(self, query: dict, body: bytes) -> dict:
        req = json.loads(body)
        with self._lock:
            if req["term"] > self.current_term:
                self._become_follower(req["term"], None)
            granted = False
            if req["term"] == self.current_term and \
                    self.voted_for in (None, req["candidate_id"]):
                # §5.4.1: candidate's log must be at least as up-to-date.
                my_last_term = self._term_at(self._last_log_index())
                up_to_date = (
                    req["last_log_term"] > my_last_term
                    or (req["last_log_term"] == my_last_term
                        and req["last_log_index"] >=
                        self._last_log_index()))
                if up_to_date:
                    granted = True
                    self.voted_for = req["candidate_id"]
                    self._last_heartbeat = time.monotonic()
                    self._save_state()
            return {"term": self.current_term, "vote_granted": granted}

    def _h_append_entries(self, query: dict, body: bytes) -> dict:
        req = json.loads(body)
        with self._lock:
            if req["term"] > self.current_term or \
                    (req["term"] == self.current_term
                     and self.state != FOLLOWER):
                self._become_follower(req["term"], req["leader_id"])
            if req["term"] < self.current_term:
                return {"term": self.current_term, "success": False}
            self.leader_id = req["leader_id"]
            self._last_heartbeat = time.monotonic()
            prev_idx = req["prev_log_index"]
            prev_term = req["prev_log_term"]
            entries = req.get("entries", [])
            if prev_idx < self.log_base:
                # Everything at or below log_base is snapshotted and
                # committed; skip the already-incorporated prefix.  The
                # effective prev entry is the batch's own entry at
                # log_base — comparing the ORIGINAL prev term against
                # the snapshot term would spuriously reject forever.
                skip = self.log_base - prev_idx
                if skip >= len(entries):
                    return {"term": self.current_term, "success": True,
                            "match_index": max(prev_idx + len(entries),
                                               self.log_base)}
                entries = entries[skip:]
                prev_idx = self.log_base
                # A committed prefix matches the snapshot by Log
                # Matching; trust it rather than the leader's term
                # for an entry we compacted away.
                prev_term = self.log_base_term
            if prev_idx > self._last_log_index() or \
                    self._term_at(prev_idx) != prev_term:
                return {"term": self.current_term, "success": False,
                        "hint_index": min(prev_idx,
                                          self._last_log_index())}
            # Append/overwrite conflicting suffix.
            idx = prev_idx
            truncated = False
            appended: list[dict] = []
            appended_at = 0
            for e in entries:
                idx += 1
                if idx <= self._last_log_index():
                    if self._term_at(idx) != e["term"]:
                        del self.log[idx - self.log_base - 1:]
                        truncated = True
                        self.log.append(e)
                        if not appended:
                            appended_at = idx
                        appended.append(e)
                else:
                    self.log.append(e)
                    if not appended:
                        appended_at = idx
                    appended.append(e)
            if truncated:
                self._rewrite_log()
                self._recompute_config()
            elif appended:
                self._append_log(appended, appended_at)
            for e in appended:
                self._maybe_apply_config(e)
            if req["leader_commit"] > self.commit_index:
                self.commit_index = min(req["leader_commit"],
                                        self._last_log_index())
                self._commit_cv.notify_all()
            return {"term": self.current_term, "success": True,
                    "match_index": req["prev_log_index"]
                    + len(req.get("entries", []))}

    def _h_install_snapshot(self, query: dict, body: bytes) -> dict:
        """InstallSnapshot (Raft §7): the leader ships its snapshot to
        a follower whose needed entries were compacted away."""
        req = json.loads(body)
        # _sm_lock first (same order as the apply loop): restore_fn
        # must not run while an apply batch is mid-flight, or stale
        # pre-snapshot commands would mutate the restored state.
        with self._sm_lock, self._lock:
            if req["term"] < self.current_term:
                return {"term": self.current_term, "success": False}
            if req["term"] > self.current_term or self.state != FOLLOWER:
                self._become_follower(req["term"], req["leader_id"])
            self.leader_id = req["leader_id"]
            self._last_heartbeat = time.monotonic()
            snap = req["snapshot"]
            if snap["last_index"] > max(self.log_base,
                                        self.last_applied,
                                        self.commit_index):
                self._install_snapshot_locked(snap)
                self.commit_index = snap["last_index"]
                self.last_applied = snap["last_index"]
                self._commit_cv.notify_all()
            # An older snapshot than our applied state would REWIND the
            # state machine while last_applied stayed high (the gap
            # would never re-apply): refuse it but report our matching
            # prefix so the leader resumes AppendEntries from there.
            return {"term": self.current_term, "success": True,
                    "match_index": self.log_base}

    def _h_status(self, query: dict, body: bytes) -> dict:
        with self._lock:
            return {"id": self.id, "state": self.state,
                    "term": self.current_term, "leader": self.leader_id,
                    "commit_index": self.commit_index,
                    "log_base": self.log_base,
                    "log_length": len(self.log),
                    "peers": sorted(self.peers),
                    "in_config": self.in_config}

    # -- state transitions ---------------------------------------------------

    def _become_follower(self, term: int, leader: str | None) -> None:
        # Election safety: a vote binds to a term — only forget it when
        # the term actually advances.  The same-term step-down path
        # (leader discovery) must keep voted_for or a node could grant
        # two votes in one term (two leaders possible).
        was_leader = self.state == LEADER
        if term > self.current_term:
            self.voted_for = None
        self.current_term = term
        self.state = FOLLOWER
        if leader is not None:
            self.leader_id = leader
        self._save_state()
        if was_leader:
            from ..events import emit as emit_event
            from ..trace import root_span
            with root_span("raft.stepdown", "master", node=self.id):
                emit_event("leader.stepdown", node=self.id,
                           severity="warn", term=term,
                           new_leader=leader or "")

    def _become_leader(self) -> None:
        self.state = LEADER
        self.leader_id = self.id
        from ..events import emit as emit_event
        from ..trace import root_span
        with root_span("raft.elect", "master", node=self.id):
            emit_event("leader.elect", node=self.id,
                       term=self.current_term,
                       peers=sorted(self.peers))
        # Barrier no-op (§8): entries inherited from prior terms can't
        # be count-committed; committing a current-term entry commits
        # them transitively, so the new leader's state machine catches
        # up before it serves any read-modify-write (id issuance).
        entry = {"term": self.current_term, "cmd": {"op": "noop"}}
        self.log.append(entry)
        self._append_log([entry], self._last_log_index())
        nxt = self._last_log_index() + 1
        self.next_index = {p: nxt for p in self.peers}
        self.match_index = {p: 0 for p in self.peers}
        if not self.peers:
            self.commit_index = self._last_log_index()
            self._commit_cv.notify_all()
        # One long-lived replicator per peer for this term; each paces
        # itself at heartbeat_interval and is woken early by propose().
        term = self.current_term
        self._wake_events = {p: threading.Event() for p in self.peers}
        for peer in self.peers:
            threading.Thread(target=self._peer_loop, args=(peer, term),
                             daemon=True,
                             name=f"raft-repl-{peer}").start()

    def barrier(self, timeout: float = 5.0) -> None:
        """Wait until this node has applied every entry currently in its
        log — the leader's read-your-own-writes fence."""
        with self._lock:
            target = self._last_log_index()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self.last_applied >= target:
                    return
                if self.state != LEADER:
                    raise NotLeader(self.leader_id)
            time.sleep(0.01)
        raise TimeoutError(f"barrier at index {target} not reached")

    # -- election ------------------------------------------------------------

    def _election_loop(self) -> None:
        while not self._stop.is_set():
            timeout = random.uniform(*self.election_timeout)
            self._stop.wait(self.heartbeat_interval / 2)
            with self._lock:
                if self.state == LEADER or not self.in_config:
                    continue  # removed nodes never campaign
                elapsed = time.monotonic() - self._last_heartbeat
                if elapsed < timeout:
                    continue
                # Start an election.
                self.state = CANDIDATE
                self.current_term += 1
                self.voted_for = self.id
                self._save_state()
                term = self.current_term
                last_idx = self._last_log_index()
                last_term = self._term_at(last_idx)
                self._last_heartbeat = time.monotonic()
            if not self.peers:  # single-node cluster
                with self._lock:
                    if self.state == CANDIDATE and \
                            self.current_term == term:
                        self._become_leader()
                continue
            votes = [1]  # self-vote
            votes_lock = threading.Lock()

            def ask(peer: str) -> None:
                try:
                    out = self.transport(
                        peer + "/raft/request_vote",
                        payload={"term": term, "candidate_id": self.id,
                                 "last_log_index": last_idx,
                                 "last_log_term": last_term},
                        timeout=0.5)
                except Exception:  # noqa: BLE001 — unreachable peer
                    return
                with self._lock:
                    if out["term"] > self.current_term:
                        self._become_follower(out["term"], None)
                        return
                if out.get("vote_granted"):
                    with votes_lock:
                        votes[0] += 1

            threads = [threading.Thread(target=ask, args=(p,),
                                        daemon=True) for p in self.peers]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=0.6)
            with self._lock:
                if self.state == CANDIDATE and \
                        self.current_term == term and \
                        votes[0] * 2 > len(self.peers) + 1:
                    self._become_leader()

    # -- leader replication --------------------------------------------------

    def _peer_loop(self, peer: str, term: int) -> None:
        """Replicate to one peer until this term's leadership ends: one
        in-flight AppendEntries at a time, paced at heartbeat_interval,
        woken early when propose() appends."""
        ev = self._wake_events.get(peer)
        while not self._stop.is_set():
            with self._lock:
                if self.state != LEADER or self.current_term != term:
                    return
                if peer not in self.match_index:
                    return  # removed from the configuration
                part = self._parting.get(peer)
                if part is not None and \
                        self.match_index.get(peer, 0) >= part:
                    # The removed peer has its removal entry: done.
                    self._parting.pop(peer, None)
                    self.next_index.pop(peer, None)
                    self.match_index.pop(peer, None)
                    self._wake_events.pop(peer, None)
                    return
            self._replicate_to(peer, term)
            if ev is not None:
                ev.wait(self.heartbeat_interval)
                ev.clear()
            else:
                self._stop.wait(self.heartbeat_interval)

    def _replicate_to(self, peer: str, term: int) -> None:
        with self._lock:
            if self.state != LEADER or self.current_term != term:
                return
            if peer not in self.match_index:
                return  # removed from the configuration
            nxt = self.next_index.get(peer, self._last_log_index() + 1)
            if nxt <= self.log_base:
                # The entries this follower needs were compacted away:
                # ship the snapshot instead (InstallSnapshot, §7).
                snap = self._current_snapshot()
            else:
                snap = None
                prev_idx = nxt - 1
                prev_term = self._term_at(prev_idx)
                entries = self.log[nxt - self.log_base - 1:]
                commit = self.commit_index
        if snap is not None:
            try:
                out = self.transport(
                    peer + "/raft/install_snapshot",
                    payload={"term": term, "leader_id": self.id,
                             "snapshot": snap},
                    timeout=2.0)
            except Exception:  # noqa: BLE001 — retried next beat
                return
            with self._lock:
                if out["term"] > self.current_term:
                    self._become_follower(out["term"], None)
                    return
                if self.state != LEADER or self.current_term != term:
                    return
                if out.get("success"):
                    self.match_index[peer] = out.get("match_index",
                                                     snap["last_index"])
                    self.next_index[peer] = self.match_index[peer] + 1
            return
        try:
            out = self.transport(
                peer + "/raft/append_entries",
                payload={"term": term, "leader_id": self.id,
                         "prev_log_index": prev_idx,
                         "prev_log_term": prev_term,
                         "entries": entries, "leader_commit": commit},
                timeout=0.5)
        except Exception:  # noqa: BLE001 — peer down; retried next beat
            return
        with self._lock:
            if out["term"] > self.current_term:
                self._become_follower(out["term"], None)
                return
            if self.state != LEADER or self.current_term != term:
                return
            if out.get("success"):
                self.match_index[peer] = out.get("match_index", prev_idx)
                self.next_index[peer] = self.match_index[peer] + 1
            else:
                # Back off (use follower's hint when present).
                self.next_index[peer] = max(
                    1, out.get("hint_index", nxt - 1))
        self._maybe_advance_commit()

    def _maybe_advance_commit(self) -> None:
        with self._lock:
            if self.state != LEADER:
                return
            for n in range(self._last_log_index(), self.commit_index, -1):
                # §5.4.2: only commit entries from the current term by
                # counting; older ones commit transitively.
                if self._term_at(n) != self.current_term:
                    break
                replicas = 1 + sum(
                    1 for p in self.peers if self.match_index.get(p, 0)
                    >= n)
                if replicas * 2 > len(self.peers) + 1:
                    self.commit_index = n
                    self._commit_cv.notify_all()
                    break

    # -- apply ---------------------------------------------------------------

    def _apply_loop(self) -> None:
        while not self._stop.is_set():
            with self._commit_cv:
                while self.last_applied >= self.commit_index and \
                        not self._stop.is_set():
                    self._commit_cv.wait(timeout=0.5)
                if self._stop.is_set():
                    return
                start = self.last_applied + 1
                end = self.commit_index
                entries = self.log[start - self.log_base - 1:
                                   end - self.log_base]
            applied = False
            with self._sm_lock:
                with self._lock:
                    # Re-check under the mutation lock: an
                    # InstallSnapshot may have restored a newer state
                    # while we were between locks — our batch is then
                    # stale and must be dropped, not applied on top.
                    if self.last_applied == start - 1:
                        self.last_applied = end
                        applied = True
                if applied:
                    for e in entries:
                        if e["cmd"].get("op") in ("noop", "raft_config"):
                            continue  # consensus bookkeeping only
                        try:
                            self.apply_fn(e["cmd"])
                        except Exception:  # noqa: BLE001 — state
                            pass           # machine bug must not kill
                            #                consensus
            with self._lock:
                try:
                    self._maybe_compact_locked()
                except Exception:  # noqa: BLE001 — a failed snapshot
                    pass           # write must not kill consensus

    # -- client API ----------------------------------------------------------

    def is_leader(self) -> bool:
        with self._lock:
            return self.state == LEADER

    def leader(self) -> str | None:
        with self._lock:
            return self.leader_id

    def propose(self, cmd: dict, timeout: float = 5.0) -> int:
        """Append a command, wait for commit; returns its log index.
        Raises NotLeader on followers (caller proxies to .leader())."""
        with self._lock:
            if self.state != LEADER:
                raise NotLeader(self.leader_id)
            entry = {"term": self.current_term, "cmd": cmd}
            self.log.append(entry)
            index = self._last_log_index()
            self._append_log([entry], index)
            self._maybe_apply_config(entry)
        if not self.peers:
            with self._lock:
                self.commit_index = max(self.commit_index, index)
                self._commit_cv.notify_all()
        else:
            with self._lock:  # the dict mutates during membership
                events = list(self._wake_events.values())
            for ev in events:
                ev.set()  # wake the replicators now, not next beat
        deadline = time.monotonic() + timeout
        with self._commit_cv:
            while self.commit_index < index:
                if self._stop.is_set() or \
                        time.monotonic() > deadline:
                    raise TimeoutError(
                        f"entry {index} not committed in {timeout}s")
                if self.state != LEADER:
                    raise NotLeader(self.leader_id)
                self._commit_cv.wait(timeout=0.1)
        # Wait until locally applied so the caller observes the effect.
        deadline2 = time.monotonic() + timeout
        while time.monotonic() < deadline2:
            with self._lock:
                if self.last_applied >= index:
                    return index
            time.sleep(0.005)
        return index
