"""Raft consensus for master HA.

Reference: the master embeds a raft node (weed/server/raft_server.go,
github.com/chrislusf/raft) whose single state-machine command is
MaxVolumeId (weed/topology/cluster_commands.go) — the leader owns volume
id assignment, followers proxy mutating requests to the leader
(master_server.go:155).

This is a from-scratch Raft (election + log replication + persistence),
not a port: RPCs ride the same JSON/HTTP plane as the rest of the
cluster (mounted on the master's own server), and the state machine is a
callback so the master wires MaxVolumeId (or anything else) in.

Scope notes: log compaction/snapshotting is not implemented (the log
holds tiny id-bump commands; millions of entries fit in memory), and
membership is static from `-peers` like the reference's default
deployment.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Callable

from . import rpc

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class NotLeader(Exception):
    def __init__(self, leader: str | None):
        super().__init__(f"not the leader (leader={leader})")
        self.leader = leader


class RaftNode:
    """One consensus participant.

    `node_id` / `peers` are base URLs (http://host:port) whose HTTP
    servers route /raft/* to this node via `mount()`.  `apply_fn(cmd)`
    is invoked exactly once per committed entry, in log order, on every
    node.
    """

    def __init__(self, node_id: str, peers: list[str],
                 apply_fn: Callable[[dict], None],
                 state_path: str | None = None,
                 election_timeout: tuple[float, float] = (0.6, 1.2),
                 heartbeat_interval: float = 0.15):
        self.id = node_id
        self.peers = [p for p in peers if p != node_id]
        self.apply_fn = apply_fn
        self.state_path = state_path
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval

        # Persistent state (term, vote, log).
        self.current_term = 0
        self.voted_for: str | None = None
        self.log: list[dict] = []  # {"term": int, "cmd": dict}
        self._load_state()

        # Volatile state.
        self.state = FOLLOWER
        self.leader_id: str | None = None
        self.commit_index = 0   # 1-based index of last committed entry
        self.last_applied = 0
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}

        self._lock = threading.RLock()
        self._commit_cv = threading.Condition(self._lock)
        self._last_heartbeat = time.monotonic()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._wake_events: dict[str, threading.Event] = {}

    # -- persistence ---------------------------------------------------------
    # Meta (term/vote) is a tiny JSON rewritten on change; the log is an
    # append-only JSONL journal — appending an entry is O(1), not a
    # rewrite of history.  Conflict truncation (rare) rewrites the
    # journal.

    def _log_path(self) -> str | None:
        return self.state_path + ".log" if self.state_path else None

    @staticmethod
    def _fsync_dir(path: str) -> None:
        """fsync the parent directory so a rename/create survives power
        loss — without this the fsynced file's directory entry may
        still be lost, forgetting a granted vote."""
        fd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                     os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def _load_state(self) -> None:
        if not self.state_path:
            return
        embedded = False
        try:
            with open(self.state_path) as f:
                d = json.load(f)
            self.current_term = d.get("term", 0)
            self.voted_for = d.get("voted_for")
            # Migration: early versions embedded the log in the meta file.
            self.log = d.get("log", [])
            embedded = bool(self.log)
        except (OSError, json.JSONDecodeError):
            pass
        try:
            with open(self._log_path()) as f:
                for line in f:
                    if line.strip():
                        self.log.append(json.loads(line))
        except (OSError, json.JSONDecodeError):
            pass
        if embedded:  # move embedded entries into the journal once
            self._rewrite_log()
            self._save_meta()

    def _save_meta(self) -> None:
        if not self.state_path:
            return
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"term": self.current_term,
                       "voted_for": self.voted_for}, f)
            f.flush()
            os.fsync(f.fileno())  # a granted vote must survive power loss
        os.replace(tmp, self.state_path)
        self._fsync_dir(self.state_path)

    def _append_log(self, entries: list[dict]) -> None:
        path = self._log_path()
        if not path or not entries:
            return
        created = not os.path.exists(path)
        with open(path, "a") as f:
            for e in entries:
                f.write(json.dumps(e, separators=(",", ":")) + "\n")
            f.flush()
            # An acked log suffix is a durability promise to the leader.
            os.fsync(f.fileno())
        if created:
            self._fsync_dir(path)

    def _rewrite_log(self) -> None:
        path = self._log_path()
        if not path:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            for e in self.log:
                f.write(json.dumps(e, separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._fsync_dir(path)

    def _save_state(self) -> None:  # kept for vote/term call sites
        self._save_meta()

    # -- lifecycle -----------------------------------------------------------

    def mount(self, server: rpc.JsonHttpServer) -> None:
        server.route("POST", "/raft/request_vote", self._h_request_vote)
        server.route("POST", "/raft/append_entries",
                     self._h_append_entries)
        server.route("GET", "/raft/status", self._h_status)

    def start(self) -> None:
        for target, name in ((self._election_loop, "raft-election"),
                             (self._apply_loop, "raft-apply")):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        with self._commit_cv:
            self._commit_cv.notify_all()
        for t in self._threads:
            t.join(timeout=2)

    # -- log helpers (1-based indices; index 0 = empty sentinel) -------------

    def _last_log_index(self) -> int:
        return len(self.log)

    def _term_at(self, index: int) -> int:
        return self.log[index - 1]["term"] if 1 <= index <= len(self.log) \
            else 0

    # -- RPC handlers --------------------------------------------------------

    def _h_request_vote(self, query: dict, body: bytes) -> dict:
        req = json.loads(body)
        with self._lock:
            if req["term"] > self.current_term:
                self._become_follower(req["term"], None)
            granted = False
            if req["term"] == self.current_term and \
                    self.voted_for in (None, req["candidate_id"]):
                # §5.4.1: candidate's log must be at least as up-to-date.
                my_last_term = self._term_at(self._last_log_index())
                up_to_date = (
                    req["last_log_term"] > my_last_term
                    or (req["last_log_term"] == my_last_term
                        and req["last_log_index"] >=
                        self._last_log_index()))
                if up_to_date:
                    granted = True
                    self.voted_for = req["candidate_id"]
                    self._last_heartbeat = time.monotonic()
                    self._save_state()
            return {"term": self.current_term, "vote_granted": granted}

    def _h_append_entries(self, query: dict, body: bytes) -> dict:
        req = json.loads(body)
        with self._lock:
            if req["term"] > self.current_term or \
                    (req["term"] == self.current_term
                     and self.state != FOLLOWER):
                self._become_follower(req["term"], req["leader_id"])
            if req["term"] < self.current_term:
                return {"term": self.current_term, "success": False}
            self.leader_id = req["leader_id"]
            self._last_heartbeat = time.monotonic()
            prev_idx = req["prev_log_index"]
            if prev_idx > self._last_log_index() or \
                    self._term_at(prev_idx) != req["prev_log_term"]:
                return {"term": self.current_term, "success": False,
                        "hint_index": min(prev_idx,
                                          self._last_log_index())}
            # Append/overwrite conflicting suffix.
            entries = req.get("entries", [])
            idx = prev_idx
            truncated = False
            appended: list[dict] = []
            for e in entries:
                idx += 1
                if idx <= self._last_log_index():
                    if self._term_at(idx) != e["term"]:
                        del self.log[idx - 1:]
                        truncated = True
                        self.log.append(e)
                        appended.append(e)
                else:
                    self.log.append(e)
                    appended.append(e)
            if truncated:
                self._rewrite_log()
            elif appended:
                self._append_log(appended)
            if req["leader_commit"] > self.commit_index:
                self.commit_index = min(req["leader_commit"],
                                        self._last_log_index())
                self._commit_cv.notify_all()
            return {"term": self.current_term, "success": True,
                    "match_index": prev_idx + len(entries)}

    def _h_status(self, query: dict, body: bytes) -> dict:
        with self._lock:
            return {"id": self.id, "state": self.state,
                    "term": self.current_term, "leader": self.leader_id,
                    "commit_index": self.commit_index,
                    "log_length": len(self.log)}

    # -- state transitions ---------------------------------------------------

    def _become_follower(self, term: int, leader: str | None) -> None:
        # Election safety: a vote binds to a term — only forget it when
        # the term actually advances.  The same-term step-down path
        # (leader discovery) must keep voted_for or a node could grant
        # two votes in one term (two leaders possible).
        if term > self.current_term:
            self.voted_for = None
        self.current_term = term
        self.state = FOLLOWER
        if leader is not None:
            self.leader_id = leader
        self._save_state()

    def _become_leader(self) -> None:
        self.state = LEADER
        self.leader_id = self.id
        # Barrier no-op (§8): entries inherited from prior terms can't
        # be count-committed; committing a current-term entry commits
        # them transitively, so the new leader's state machine catches
        # up before it serves any read-modify-write (id issuance).
        entry = {"term": self.current_term, "cmd": {"op": "noop"}}
        self.log.append(entry)
        self._append_log([entry])
        nxt = self._last_log_index() + 1
        self.next_index = {p: nxt for p in self.peers}
        self.match_index = {p: 0 for p in self.peers}
        if not self.peers:
            self.commit_index = self._last_log_index()
            self._commit_cv.notify_all()
        # One long-lived replicator per peer for this term; each paces
        # itself at heartbeat_interval and is woken early by propose().
        term = self.current_term
        self._wake_events = {p: threading.Event() for p in self.peers}
        for peer in self.peers:
            threading.Thread(target=self._peer_loop, args=(peer, term),
                             daemon=True,
                             name=f"raft-repl-{peer}").start()

    def barrier(self, timeout: float = 5.0) -> None:
        """Wait until this node has applied every entry currently in its
        log — the leader's read-your-own-writes fence."""
        with self._lock:
            target = self._last_log_index()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self.last_applied >= target:
                    return
                if self.state != LEADER:
                    raise NotLeader(self.leader_id)
            time.sleep(0.01)
        raise TimeoutError(f"barrier at index {target} not reached")

    # -- election ------------------------------------------------------------

    def _election_loop(self) -> None:
        while not self._stop.is_set():
            timeout = random.uniform(*self.election_timeout)
            self._stop.wait(self.heartbeat_interval / 2)
            with self._lock:
                if self.state == LEADER:
                    continue
                elapsed = time.monotonic() - self._last_heartbeat
                if elapsed < timeout:
                    continue
                # Start an election.
                self.state = CANDIDATE
                self.current_term += 1
                self.voted_for = self.id
                self._save_state()
                term = self.current_term
                last_idx = self._last_log_index()
                last_term = self._term_at(last_idx)
                self._last_heartbeat = time.monotonic()
            if not self.peers:  # single-node cluster
                with self._lock:
                    if self.state == CANDIDATE and \
                            self.current_term == term:
                        self._become_leader()
                continue
            votes = [1]  # self-vote
            votes_lock = threading.Lock()

            def ask(peer: str) -> None:
                try:
                    out = rpc.call_json(
                        peer + "/raft/request_vote",
                        payload={"term": term, "candidate_id": self.id,
                                 "last_log_index": last_idx,
                                 "last_log_term": last_term},
                        timeout=0.5)
                except Exception:  # noqa: BLE001 — unreachable peer
                    return
                with self._lock:
                    if out["term"] > self.current_term:
                        self._become_follower(out["term"], None)
                        return
                if out.get("vote_granted"):
                    with votes_lock:
                        votes[0] += 1

            threads = [threading.Thread(target=ask, args=(p,),
                                        daemon=True) for p in self.peers]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=0.6)
            with self._lock:
                if self.state == CANDIDATE and \
                        self.current_term == term and \
                        votes[0] * 2 > len(self.peers) + 1:
                    self._become_leader()

    # -- leader replication --------------------------------------------------

    def _peer_loop(self, peer: str, term: int) -> None:
        """Replicate to one peer until this term's leadership ends: one
        in-flight AppendEntries at a time, paced at heartbeat_interval,
        woken early when propose() appends."""
        ev = self._wake_events.get(peer)
        while not self._stop.is_set():
            with self._lock:
                if self.state != LEADER or self.current_term != term:
                    return
            self._replicate_to(peer, term)
            if ev is not None:
                ev.wait(self.heartbeat_interval)
                ev.clear()
            else:
                self._stop.wait(self.heartbeat_interval)

    def _replicate_to(self, peer: str, term: int) -> None:
        with self._lock:
            if self.state != LEADER or self.current_term != term:
                return
            nxt = self.next_index.get(peer, self._last_log_index() + 1)
            prev_idx = nxt - 1
            prev_term = self._term_at(prev_idx)
            entries = self.log[nxt - 1:]
            commit = self.commit_index
        try:
            out = rpc.call_json(
                peer + "/raft/append_entries",
                payload={"term": term, "leader_id": self.id,
                         "prev_log_index": prev_idx,
                         "prev_log_term": prev_term,
                         "entries": entries, "leader_commit": commit},
                timeout=0.5)
        except Exception:  # noqa: BLE001 — peer down; retried next beat
            return
        with self._lock:
            if out["term"] > self.current_term:
                self._become_follower(out["term"], None)
                return
            if self.state != LEADER or self.current_term != term:
                return
            if out.get("success"):
                self.match_index[peer] = out.get("match_index", prev_idx)
                self.next_index[peer] = self.match_index[peer] + 1
            else:
                # Back off (use follower's hint when present).
                self.next_index[peer] = max(
                    1, out.get("hint_index", nxt - 1))
        self._maybe_advance_commit()

    def _maybe_advance_commit(self) -> None:
        with self._lock:
            if self.state != LEADER:
                return
            for n in range(self._last_log_index(), self.commit_index, -1):
                # §5.4.2: only commit entries from the current term by
                # counting; older ones commit transitively.
                if self._term_at(n) != self.current_term:
                    break
                replicas = 1 + sum(
                    1 for p in self.peers if self.match_index.get(p, 0)
                    >= n)
                if replicas * 2 > len(self.peers) + 1:
                    self.commit_index = n
                    self._commit_cv.notify_all()
                    break

    # -- apply ---------------------------------------------------------------

    def _apply_loop(self) -> None:
        while not self._stop.is_set():
            with self._commit_cv:
                while self.last_applied >= self.commit_index and \
                        not self._stop.is_set():
                    self._commit_cv.wait(timeout=0.5)
                if self._stop.is_set():
                    return
                start = self.last_applied + 1
                end = self.commit_index
                entries = self.log[start - 1:end]
                self.last_applied = end
            for e in entries:
                if e["cmd"].get("op") == "noop":
                    continue  # leadership barrier, not state
                try:
                    self.apply_fn(e["cmd"])
                except Exception:  # noqa: BLE001 — state machine bug
                    pass           # must not kill consensus

    # -- client API ----------------------------------------------------------

    def is_leader(self) -> bool:
        with self._lock:
            return self.state == LEADER

    def leader(self) -> str | None:
        with self._lock:
            return self.leader_id

    def propose(self, cmd: dict, timeout: float = 5.0) -> int:
        """Append a command, wait for commit; returns its log index.
        Raises NotLeader on followers (caller proxies to .leader())."""
        with self._lock:
            if self.state != LEADER:
                raise NotLeader(self.leader_id)
            entry = {"term": self.current_term, "cmd": cmd}
            self.log.append(entry)
            self._append_log([entry])
            index = self._last_log_index()
        if not self.peers:
            with self._lock:
                self.commit_index = max(self.commit_index, index)
                self._commit_cv.notify_all()
        else:
            for ev in self._wake_events.values():
                ev.set()  # wake the replicators now, not next beat
        deadline = time.monotonic() + timeout
        with self._commit_cv:
            while self.commit_index < index:
                if self._stop.is_set() or \
                        time.monotonic() > deadline:
                    raise TimeoutError(
                        f"entry {index} not committed in {timeout}s")
                if self.state != LEADER:
                    raise NotLeader(self.leader_id)
                self._commit_cv.wait(timeout=0.1)
        # Wait until locally applied so the caller observes the effect.
        deadline2 = time.monotonic() + timeout
        while time.monotonic() < deadline2:
            with self._lock:
                if self.last_applied >= index:
                    return index
            time.sleep(0.005)
        return index
