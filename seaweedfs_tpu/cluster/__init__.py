"""Cluster roles: master, volume servers, clients — the reference's
server/gateway layers over an HTTP/JSON control plane."""
