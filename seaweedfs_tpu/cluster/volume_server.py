"""Volume server: needle CRUD over HTTP + admin/EC RPCs + heartbeats.

Surface mirrors the reference volume server
(weed/server/volume_server_handlers_*.go, volume_grpc_*.go):

  public:  GET/POST/DELETE /{fid}   (?type=replicate suppresses fan-out)
  admin:   POST /admin/assign_volume | delete_volume | readonly | vacuum
           POST /admin/ec/generate | mount | rebuild | delete_shards
           GET  /admin/status
           GET  /admin/ec/shard_read?volume=&shard=&offset=&size=

Replicated writes fan out to sibling replicas looked up at the master
(topology/store_replicate.go) — all-or-fail like the reference.
"""

from __future__ import annotations

import concurrent.futures
import json
import os
import random
import re
import threading
import time
import urllib.parse

from ..core import types as t
from ..core.needle import CURRENT_VERSION, Needle
from ..ec import TOTAL_SHARDS, to_ext
from ..ec.encoder import rebuild_ec_files, write_ec_files, \
    write_sorted_file_from_idx
from ..ec.shard_bits import ShardBits
from ..ec.volume import EcVolume, NeedleNotFound
from ..events import emit as emit_event
from ..fault import registry as _fault
from ..codecs import get_codec
from ..stats import flows as _flows
from ..stats import roofline as _roofline
from ..stats.metrics import (ec_repair_read_bytes_total,
                             needle_repairs_total, observe_ec_stage)
from ..storage.scrub import ScrubDaemon
from ..storage.store import Store
from ..storage.vacuum import vacuum as vacuum_volume
from ..storage.volume import (CorruptNeedleError, DiskFullError,
                              NotFoundError, TierReadError, VolumeError)
from ..trace import span as trace_span
from . import rpc

# How long a receive_ecc fragment may wait for its receive_shard before
# it stops being trusted (see VolumeServer._ec_pending_ecc).  Scatter
# pushes follow their fragment within seconds; minutes-old entries mean
# the push failed and a LATER encode generation must not inherit them.
_PENDING_ECC_TTL = 600.0


class VolumeServer:
    def __init__(self, master_url: str | list[str],
                 directories: list[str],
                 host: str = "127.0.0.1", port: int = 0,
                 max_volume_counts: list[int] | None = None,
                 data_center: str = "DefaultDataCenter",
                 rack: str = "DefaultRack",
                 pulse_seconds: int = 2,
                 jwt_signing_key: str = "",
                 ssl_context=None,
                 read_redirect: bool = True,
                 scrub_mbps: float = 32.0,
                 scrub_interval: float = 3600.0,
                 fsync: bool = False,
                 max_concurrent: int = 0,
                 queue_depth: int | None = None,
                 shutdown_grace: float = 30.0,
                 disk_reserve_mb: float = 0.0,
                 idle_timeout: float = 120.0,
                 ec_codec: str = "rs",
                 slo_read_p99: float | None = None,
                 slo_availability: float | None = None,
                 replicate_peer: str | None = None,
                 replicate_collections: str = "",
                 replicate_interval: float = 0.5,
                 tier_cache_mb: float = 64.0,
                 tier_promote_hits: int = 0,
                 tier_promote_window: float = 60.0,
                 transport: str | None = None,
                 sendfile_min: int | None = None,
                 tenant_rules: str = "",
                 geo_cluster_id: str = "",
                 replicate_compress: bool = False):
        # Seed master list; heartbeats follow leader hints and rotate
        # seeds on failure (volume_grpc_client_to_master.go:60-85).
        self.masters = list(master_url) if isinstance(master_url, list) \
            else [master_url]
        self.master_url = self.masters[0]
        self._master_idx = 0
        # Write-path guard (security/guard.go): when a signing key is
        # configured, needle writes/deletes require a master-minted JWT.
        from ..utils.security import Guard
        self.guard = Guard(signing_key=jwt_signing_key)
        self._hb_seq = 0
        # Process generation: lets the master distinguish a restarted
        # volume server (seq starts over) from out-of-order arrivals.
        self._hb_epoch = random.getrandbits(63)
        self._hb_lock = threading.Lock()
        self.data_center = data_center
        self.rack = rack
        self.pulse_seconds = pulse_seconds
        # -read.redirect (volume.go:79, default true): GETs of volumes
        # not hosted here 301 to a current holder instead of 404ing.
        self.read_redirect = read_redirect
        # Tenancy & QoS (-tenant.rules): quota rules feed per-tenant
        # token buckets + DRR weights in the admission plane, and the
        # usage ledger below reports per-(tenant, collection) stored
        # bytes/objects to the master on every heartbeat.
        from ..tenancy import TenantUsage, load_rules
        self.tenant_policy = load_rules(tenant_rules) \
            if tenant_rules else None
        self.usage = TenantUsage()
        # Overload protection (-max.concurrent): bounded read/write
        # lanes + the lower-priority internal lane; 0 = no shedding
        # (in-flight is still tracked for graceful drain).
        self.server = rpc.JsonHttpServer(
            host, port, ssl_context=ssl_context,
            idle_timeout=idle_timeout,
            transport=transport,
            admission=rpc.AdmissionControl(
                max_concurrent, queue_depth=queue_depth,
                tenant_policy=self.tenant_policy))
        # -read.sendfile.min: smallest whole-needle GET served via the
        # zero-copy slice path (0 disables, None = class default).
        self.sendfile_min = self.SENDFILE_MIN if sendfile_min is None \
            else int(sendfile_min)
        self.store = Store(directories, max_volume_counts,
                           ip=host, port=self.server.port,
                           disk_reserve_bytes=int(disk_reserve_mb
                                                  * 1024 * 1024))
        # Graceful lifecycle (-shutdown.grace): draining mode refuses
        # new writes, finishes in-flight work, then says goodbye so the
        # master unregisters without a dead-sweep window.
        self.shutdown_grace = shutdown_grace
        self.draining = False
        self._drain_lock = threading.Lock()
        # -ec.codec: default erasure codec for /admin/ec/generate
        # ("rs" wire-compatible default; "lrc" for 5-read repair).
        # Validated now so a typo fails at startup, not mid-encode.
        self.ec_codec = get_codec(ec_codec).name
        self.ec_volumes: dict[int, EcVolume] = {}
        self._ec_recv_lock = threading.Lock()
        self._ec_recv_vlocks: dict[int, threading.Lock] = {}
        # vid -> {sid: (shipped_at, crcs)} entries that arrived via
        # receive_ecc and have not yet been claimed by their
        # receive_shard.  Kept SEPARATE from the on-disk .ecc sidecar:
        # a sidecar entry might be a stale leftover from a prior encode
        # generation (same shard size, so the block count matches), and
        # trusting it for a fresh push would make the first scrub
        # quarantine a healthy shard.  Only an entry the encoder
        # shipped THIS time may stand in for fingerprinting the pushed
        # body; entries expire after _PENDING_ECC_TTL (a fragment whose
        # shard push failed must not haunt a later re-encode that
        # happens to match its block count), and a restart in between
        # just loses the map — receive_shard falls back safely.
        self._ec_pending_ecc: \
            dict[int, dict[int, tuple[float, list[int]]]] = {}
        # vid -> (fetched_at, ttl, shard->urls).  TTL is tiered by how
        # complete the last lookup was (store_ec.go:221-229): a lookup
        # that can't even serve reads retries quickly, a full set is
        # trusted for a long time.
        self._ec_loc_cache: dict[
            int, tuple[float, float, dict[int, list[str]]]] = {}
        # vid -> (fetched_at, /dir/lookup response): the volume-location
        # cache every misdirected read and replication fan-out shares
        # (operation/lookup.go keeps the same cache for ~10 minutes;
        # 60s here keeps rebalance staleness short on this plane).
        self._vol_loc_cache: dict[int, tuple[float, dict]] = {}
        self._ec_read_pool: concurrent.futures.ThreadPoolExecutor | None = None
        self._ec_pool_lock = threading.Lock()
        self._reap_partial_files()
        self._load_ec_volumes()
        # -fsync: force per-write durability (every POST behaves like
        # ?fsync=true — zero-loss acks for users who want them).
        self.fsync_writes = fsync
        # Background integrity sweep + self-healing (storage/scrub.py):
        # repairs route through this server because they need master
        # lookups (replica fetch) and the EC shard fan-out (decode).
        self.scrub = ScrubDaemon(
            self.store, self.ec_volumes, node=self.url(),
            mbps=scrub_mbps, interval=scrub_interval,
            repair_needle=self._repair_needle_from_replica,
            repair_ec_block=self._repair_ec_block,
            on_change=lambda: self._send_heartbeat(full=True))
        # Cross-cluster mirroring (-replicate.peer names the STANDBY
        # cluster's master): a background shipper tails every local
        # volume's durable change log and streams batches to the peer;
        # the receive side (the standby's _replication_apply) applies
        # idempotently against per-volume applied-seq watermarks.
        # Geo active/active (-geo.cluster.id): names THIS cluster in
        # the lease plane.  Per-volume `.lease` sidecars make exactly
        # one cluster the write home; non-holders forward writes and
        # the apply path fences stale epochs (replication/lease.py).
        self.geo_cluster_id = geo_cluster_id
        self.leases = None
        if geo_cluster_id:
            from ..replication.lease import LeaseTable
            self.leases = LeaseTable(self.store, geo_cluster_id)
        self.shipper = None
        if replicate_peer:
            from ..replication.shipper import ReplicationShipper
            self.shipper = ReplicationShipper(
                self.store, replicate_peer, node=self.url(),
                collections=replicate_collections,
                interval=replicate_interval,
                cluster_id=geo_cluster_id,
                compress=replicate_compress, leases=self.leases)
        self._replication_applied: dict[int, object] = {}
        self._replication_apply_lock = threading.Lock()
        s = self.server
        s.route("GET", "/admin/status", self._admin_status)
        s.route("POST", "/admin/status", self._admin_status)
        s.route("GET", "/ui", self._ui)
        from ..utils.pprof import enable_pprof_routes
        enable_pprof_routes(s)
        from ..trace import setup_server_tracing
        setup_server_tracing(s, "volumeServer")
        from ..fault.routes import setup_fault_routes
        setup_fault_routes(s)
        from ..events import setup_event_routes
        setup_event_routes(s)
        s.route("POST", "/admin/assign_volume", self._admin_assign_volume)
        s.route("POST", "/admin/delete_volume", self._admin_delete_volume)
        s.route("POST", "/admin/readonly", self._admin_readonly)
        s.route("POST", "/admin/configure_replication",
                self._admin_configure_replication)
        s.route("POST", "/admin/vacuum", self._admin_vacuum)
        s.route("POST", "/admin/scrub", self._admin_scrub)
        s.route("GET", "/admin/scrub/status", self._admin_scrub_status)
        s.route("POST", "/admin/scrub/repair", self._admin_scrub_repair)
        s.route("GET", "/admin/needle_raw", self._admin_needle_raw)
        s.route("POST", "/admin/ec/generate", self._ec_generate)
        s.route("POST", "/admin/ec/mount", self._ec_mount)
        s.route("POST", "/admin/ec/unmount", self._ec_unmount)
        s.route("POST", "/admin/ec/rebuild", self._ec_rebuild)
        s.route("POST", "/admin/ec/delete_shards", self._ec_delete_shards)
        s.route("GET", "/admin/ec/shard_read", self._ec_shard_read)
        s.route("GET", "/admin/ec/shard_file", self._ec_shard_file)
        s.route("POST", "/admin/ec/copy_shard", self._ec_copy_shard)
        s.route("POST", "/admin/ec/receive_shard", self._ec_receive_shard)
        s.route("POST", "/admin/ec/receive_file", self._ec_receive_file)
        s.route("POST", "/admin/ec/receive_ecc", self._ec_receive_ecc)
        s.route("POST", "/admin/ec/to_volume", self._ec_to_volume)
        s.route("POST", "/query", self._query)
        s.route("GET", "/admin/volume_tail", self._volume_tail)
        s.route("POST", "/admin/leave", self._admin_leave)
        s.route("POST", "/admin/drain", self._admin_drain)
        s.route("POST", "/admin/replication/apply",
                self._replication_apply)
        s.route("POST", "/admin/replication/pause",
                self._replication_pause)
        s.route("POST", "/admin/replication/resume",
                self._replication_resume)
        s.route("GET", "/debug/replication", self._debug_replication)
        s.route("GET", "/admin/lease/status", self._lease_status)
        s.route("POST", "/admin/lease/acquire", self._lease_acquire)
        s.route("POST", "/admin/lease/move", self._lease_move)
        s.route("POST", "/admin/tier_upload", self._tier_upload)
        s.route("POST", "/admin/tier_download", self._tier_download)
        s.route("GET", "/debug/tier", self._debug_tier)
        # Tier plane (-tier.cache.mb / -tier.promote.*): the shared
        # remote block cache budget, and the auto-promotion policy —
        # `hits` tiered reads inside `window` seconds schedule a
        # tier_download back to local disk (0 hits = disabled).
        from ..storage.remote_cache import CACHE as _tier_cache
        _tier_cache.configure(int(tier_cache_mb * (1 << 20)))
        self.tier_promote_hits = tier_promote_hits
        self.tier_promote_window = tier_promote_window
        self._promoting: set[int] = set()
        self._promote_lock = threading.Lock()
        self._setup_metrics()
        # SLO plane: /debug/slow exemplars + /debug/slo state, declared
        # objectives (-slo.read.p99 / -slo.availability) feeding the
        # burn engine; heartbeats carry heartbeat_view() so the master
        # folds this node into /cluster/healthz.
        from ..stats.slo import setup_slo_routes
        setup_slo_routes(s)
        self.server.slo.set_objectives(slo_read_p99, slo_availability)
        # Lock-contention surface: /debug/locks — the volume write
        # lock, ecc sidecar lock, and admission-lane locks all report
        # here with their current holders/waiters.
        from ..stats.contention import setup_contention_routes
        setup_contention_routes(s)
        # Heavy hitters (stats/hotkeys.py): hot volumes / needles /
        # client IPs on the read+write data paths, for /debug/hot and
        # the shell's cluster.hot — the cache/packing target list.
        from ..stats.hotkeys import HotKeyTracker
        self.hot = HotKeyTracker()
        s.route("GET", "/debug/hot", self._debug_hot)
        s.route("GET", "/debug/tenants", self._debug_tenants)
        # Device roofline plane (stats/roofline.py): per-kernel
        # achieved-fraction table, pipeline occupancy gantts, probed
        # peaks and device memory stats.
        s.route("GET", "/debug/device", self._debug_device)
        s.route("GET", "/admin/volume_file", self._volume_file)
        s.route("POST", "/admin/copy_volume", self._copy_volume)
        s.route("GET", "/admin/volume/checksums", self._volume_checksums)
        s.route("POST", "/admin/volume/receive", self._volume_receive)
        s.route("POST", "/admin/mount", self._admin_mount)
        s.route("POST", "/admin/unmount", self._admin_unmount)
        s.prefix_route("GET", "/", self._get_needle)
        s.prefix_route("HEAD", "/", self._head_needle)
        s.prefix_route("POST", "/", self._post_needle)
        s.prefix_route("PUT", "/", self._post_needle)
        s.prefix_route("DELETE", "/", self._delete_needle)
        self._stop = threading.Event()
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           daemon=True,
                                           name=f"hb:{self.server.port}")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self.server.start()
        self._send_heartbeat(full=True)
        self._hb_thread.start()
        self.scrub.start()
        if self.shipper is not None:
            self.shipper.start()

    def stop(self) -> None:
        self._stop.set()
        if self.shipper is not None:
            self.shipper.stop()
        self.scrub.stop()
        self.server.stop()
        with self._ec_pool_lock:
            if self._ec_read_pool is not None:
                self._ec_read_pool.shutdown(wait=False)
                self._ec_read_pool = None
        for ev in self.ec_volumes.values():
            ev.close()
        self.store.close()

    def url(self) -> str:
        return f"{self.server.host}:{self.server.port}"

    # -- metrics (stats/metrics.go volume-server vectors) --------------------

    def _setup_metrics(self) -> None:
        from ..stats.sysstats import disk_status, memory_status
        reg = self.server.enable_metrics("volumeServer")

        def _iter_volumes():
            for loc in self.store.locations:
                yield from list(loc.volumes.values())

        def volumes_by_collection() -> dict:
            out: dict[tuple, float] = {}
            for v in _iter_volumes():
                k = (v.collection or "default", "volume")
                out[k] = out.get(k, 0) + 1
            if self.ec_volumes:
                out[("default", "ec_shard_volume")] = \
                    float(len(self.ec_volumes))
            return out

        def disk_sizes() -> dict:
            out: dict[tuple, float] = {}
            for v in _iter_volumes():
                k = (v.collection or "default", "normal")
                out[k] = out.get(k, 0) + v.content_size()
            return out

        reg.gauge("SeaweedFS_volumeServer_volumes",
                  "volumes managed by this server",
                  ("collection", "type"), callback=volumes_by_collection)
        reg.gauge("SeaweedFS_volumeServer_max_volumes",
                  "maximum volume slots",
                  callback=lambda: float(sum(
                      l.max_volume_count for l in self.store.locations)))
        reg.gauge("SeaweedFS_volumeServer_total_disk_size",
                  "stored bytes by collection",
                  ("collection", "type"), callback=disk_sizes)
        reg.gauge("SeaweedFS_disk_free_bytes", "free disk bytes",
                  ("dir",), callback=lambda: {
                      (l.directory,): disk_status(l.directory)["free"]
                      for l in self.store.locations})
        # The rest of the reference DiskStatus fields (disk.go): total
        # capacity, used bytes, and fill percentage per directory — the
        # same numbers the heartbeat feeds the master's health rollup.
        reg.gauge("SeaweedFS_disk_all_bytes", "total disk bytes",
                  ("dir",), callback=lambda: {
                      (l.directory,): disk_status(l.directory)["all"]
                      for l in self.store.locations})
        reg.gauge("SeaweedFS_disk_used_bytes", "used disk bytes",
                  ("dir",), callback=lambda: {
                      (l.directory,): disk_status(l.directory)["used"]
                      for l in self.store.locations})
        reg.gauge("SeaweedFS_disk_percent_used",
                  "disk fill percentage", ("dir",), callback=lambda: {
                      (l.directory,):
                      disk_status(l.directory)["percent_used"]
                      for l in self.store.locations})
        reg.gauge("SeaweedFS_memory_rss_bytes", "resident set size",
                  callback=lambda: float(memory_status()["rss"]))
        # Free-space reserve breaches (-disk.reserve): 1 while the
        # directory's free bytes sit below the reserve (its volumes are
        # readonly), 0 otherwise.
        reg.gauge("SeaweedFS_disk_reserve_breached",
                  "1 while the dir's free space is below -disk.reserve",
                  ("dir",), callback=lambda: {
                      (l.directory,):
                      1.0 if l.directory in self.store.low_disk_dirs
                      else 0.0
                      for l in self.store.locations})
        # EC pipeline stage instruments are process-global singletons
        # (every coder/reconstruction path observes into them); exposing
        # them here puts kernel/staging/fan-out time on this server's
        # /metrics scrape.
        from ..stats.metrics import ec_stage_bytes, ec_stage_seconds
        # register_once, not register: process-global singletons must
        # never land twice in one registry (an in-process re-init would
        # emit a duplicate exposition family and fail promcheck — the
        # regression in tests/test_slo.py).
        reg.register_once(ec_stage_seconds)
        reg.register_once(ec_stage_bytes)
        # Device roofline instruments (stats/roofline.py): per-kernel
        # fenced seconds / analytic bytes / GF(2) work, plus the
        # streamed-pipeline occupancy gauge — process-global
        # singletons, register_once for the same promcheck reason.
        for m in (_roofline.kernel_seconds_total,
                  _roofline.kernel_bytes_total,
                  _roofline.kernel_work_total,
                  _roofline.device_occupancy):
            reg.register_once(m)
        # Scrub + self-healing instruments (process-global singletons,
        # storage/scrub.py) on this server's scrape.
        from ..stats.metrics import (scrub_bytes_total,
                                     scrub_checked_total,
                                     scrub_corrupt_total,
                                     scrub_sweeps_total)
        for m in (scrub_checked_total, scrub_bytes_total,
                  scrub_corrupt_total, scrub_sweeps_total,
                  needle_repairs_total, ec_repair_read_bytes_total):
            reg.register_once(m)
        # Cross-cluster replication instruments (process-global
        # singletons the shipper observes into, replication/shipper.py).
        from ..stats.metrics import (replication_lag_seconds,
                                     replication_lag_seconds_total,
                                     replication_resends_total,
                                     replication_shipped_bytes_total)
        for m in (replication_shipped_bytes_total,
                  replication_resends_total,
                  replication_lag_seconds_total,
                  replication_lag_seconds):
            reg.register_once(m)
        # Tiering instruments: the shared remote block cache's
        # served-byte counters + mover/expiry totals (process-global
        # singletons), plus live gauges over the cache itself —
        # occupancy against the -tier.cache.mb budget and the remote
        # fetch latency quantiles (a WindowedSketch, so the gauges
        # track the last five minutes, not process lifetime).
        from ..stats.metrics import (lifecycle_actions_total,
                                     tier_cache_hit_bytes_total,
                                     tier_cache_miss_bytes_total,
                                     tier_moved_bytes_total,
                                     ttl_expired_bytes_total)
        for m in (tier_cache_hit_bytes_total,
                  tier_cache_miss_bytes_total, tier_moved_bytes_total,
                  ttl_expired_bytes_total, lifecycle_actions_total):
            reg.register_once(m)
        from ..storage.remote_cache import CACHE as _tier_cache
        reg.gauge("SeaweedFS_tier_cache_used_bytes",
                  "remote block cache occupancy",
                  callback=lambda: float(_tier_cache.used_bytes()))
        reg.gauge("SeaweedFS_tier_cache_max_bytes",
                  "remote block cache budget (-tier.cache.mb)",
                  callback=lambda: float(_tier_cache.max_bytes))

        def tier_fetch_quantiles() -> dict:
            out = {}
            for q, lbl in ((0.5, "0.5"), (0.99, "0.99")):
                v = _tier_cache.fetch_latency.quantile(q)
                out[(lbl,)] = v if v is not None else 0.0
            return out

        reg.gauge("SeaweedFS_tier_read_seconds",
                  "remote backend block-fetch latency quantiles "
                  "(5-minute window)", ("quantile",),
                  callback=tier_fetch_quantiles)
        # Tenancy plane: live per-tenant stored usage on this node —
        # the same numbers the heartbeat reports into the master's
        # rollup, scrapeable without a /debug/tenants hit.
        reg.gauge("SeaweedFS_tenant_stored_bytes",
                  "stored bytes by tenant on this server", ("tenant",),
                  callback=lambda: {
                      (t,): float(e["bytes"])
                      for t, e in self.usage.stored_totals().items()})
        reg.gauge("SeaweedFS_tenant_stored_objects",
                  "stored objects by tenant on this server",
                  ("tenant",), callback=lambda: {
                      (t,): float(e["objects"])
                      for t, e in self.usage.stored_totals().items()})

    # -- heartbeats ---------------------------------------------------------

    def _disk_statuses(self) -> list[dict]:
        """Per-directory DiskStatus for the heartbeat: the master's
        health rollup watches percent_used without a per-node scrape."""
        from ..stats.sysstats import disk_status
        out = []
        for loc in self.store.locations:
            try:
                out.append(disk_status(loc.directory))
            except OSError:
                continue
        return out

    def _ec_shard_infos(self) -> list[dict]:
        out = []
        for vid, ev in self.ec_volumes.items():
            bits = ShardBits(0)
            for sid in ev.shards:
                bits = bits.add_shard_id(sid)
            # The codec id rides every heartbeat so the master (and
            # through it the rebuild planner) knows each EC volume's
            # shard scheme without touching a .vif.
            out.append({"id": vid, "collection": "",
                        "shard_bits": int(bits),
                        "codec": ev.codec.name})
        return out

    def _send_heartbeat(self, full: bool = False,
                        _hops: int = 0) -> None:
        from .master import vinfo_to_dict
        # A master we haven't registered with yet (leader switch / seed
        # rotation) needs the full picture, not a delta.
        full = full or getattr(self, "_need_full", False)
        # Free-space reserve enforcement rides the heartbeat cadence:
        # volumes on a breached location flip readonly here, BEFORE the
        # snapshot below reports them, so the master learns the
        # readonly state and the low-disk flag in the same beat.
        if self.store.check_disk_reserve():
            full = True  # readonly flips must reach the master now
        # Heartbeats are POSTed from two threads (pulse loop + the
        # post-allocate beat); the sequence number lets the master drop
        # any snapshot that arrives after a newer one, or a stale full
        # sync would erase a just-allocated volume from the topology.
        # Snapshot collection rides under the same lock so seq order
        # matches content order (the reference gets this for free from
        # its single bidi heartbeat stream, volume_grpc_client_to_master).
        with self._hb_lock:
            self._hb_seq += 1
            hb: dict = {
                "ip": self.server.host, "port": self.server.port,
                "public_url": self.store.public_url,
                "data_center": self.data_center, "rack": self.rack,
                "seq": self._hb_seq, "seq_epoch": self._hb_epoch,
                "max_volume_count": sum(l.max_volume_count
                                        for l in self.store.locations),
                "ec_shards": self._ec_shard_infos(),
                "disks": self._disk_statuses(),
                # Detected-but-unrepaired EC shard corruption (scrub):
                # the master's healthz reports these volumes degraded.
                "ec_corrupt": self.scrub.ec_corrupt_counts(),
                # Lifecycle + capacity flags: the master's _assign
                # steers away from draining/low-disk nodes and healthz
                # reports them without a per-node scrape.
                "draining": self.draining,
                "low_disk": bool(self.store.low_disk_dirs),
                # SLO state (stats/slo.py): burn verdict + mergeable
                # aggregate read/write quantile sketches — the master
                # folds every node into one cluster-wide tail on
                # /cluster/healthz and degrades on fast burn.
                "slo": self.server.slo.heartbeat_view(),
                # Per-(tenant, collection) stored usage, ABSOLUTE
                # values (idempotent): the master's UsageRollup
                # replaces this node's rows wholesale each beat, so a
                # dropped beat or failover never double-counts.
                "tenants": self.usage.heartbeat_view(),
                # Wire-flow ledger rows for THIS server (absolute
                # totals, idempotent like the tenant rollup): the
                # master replaces this node's cells wholesale each
                # beat and derives rates from successive samples.
                "flows": {
                    "rows": _flows.LEDGER.snapshot(local=self.url()),
                    "budgets":
                        _flows.LEDGER.budget_status(local=self.url()),
                },
                # Device roofline rollup (stats/roofline.py): absolute
                # per-kernel rows + pipeline occupancy summary — the
                # master's /cluster/device and its occupancy-collapse
                # healthz warning.
                "device": _roofline.LEDGER.heartbeat_view(),
            }
            if self.shipper is not None:
                # Per-volume replication lag (seq delta + seconds) +
                # pairing config: the master folds this into
                # /cluster/healthz and its lag-SLO verdict.
                hb["replication"] = self.shipper.lag_view()
            if self.leases is not None:
                # Geo lease rows (holder cluster + fencing epoch per
                # mirrored volume): the master's /cluster/mirror
                # rollup and healthz geo section.
                hb["leases"] = {"cluster_id": self.geo_cluster_id,
                                "volumes": self.leases.snapshot()}
            if full:
                hb["volumes"] = [
                    vinfo_to_dict(v) for v in
                    self.store.collect_heartbeat()["volumes"]]
            else:
                new, deleted = self.store.drain_deltas()
                if not new and not deleted:
                    hb["new_volumes"], hb["deleted_volumes"] = [], []
                else:
                    hb["new_volumes"] = [vinfo_to_dict(v) for v in new]
                    hb["deleted_volumes"] = [vinfo_to_dict(v)
                                             for v in deleted]
        try:
            if _fault.ARMED:
                _fault.hit("master.heartbeat", master=self.master_url,
                           server=self.url())
            out = rpc.call(f"{self.master_url}/heartbeat", "POST",
                           json.dumps(hb).encode())
            if isinstance(out, dict) and out.get("is_leader") is False:
                hint = out.get("leader")
                self._need_full = True
                if hint and hint != self.master_url:
                    # Redial the leader and re-register there.
                    self.master_url = hint
                    if _hops < 2:  # election churn: retry next tick
                        self._send_heartbeat(_hops=_hops + 1)
                else:
                    # Leaderless (or self-referential) answer: this
                    # master may be partitioned from the quorum — try
                    # the next seed rather than spinning here.
                    self._rotate_master()
            elif full:
                self._need_full = False
        except Exception:  # noqa: BLE001 — master down: rotate to the
            # next seed and re-register on the next tick.
            self._need_full = True
            self._rotate_master()

    def _rotate_master(self) -> None:
        if len(self.masters) > 1:
            self._master_idx = (self._master_idx + 1) % \
                len(self.masters)
            self.master_url = self.masters[self._master_idx]

    def _heartbeat_loop(self) -> None:
        # Flow identity for this daemon thread: several servers can
        # share one process (tests), so the process-wide default is
        # not enough — outbound beats must attribute to THIS node.
        _flows.bind_thread(self.url(), "volume")
        ticks = 0
        while not self._stop.wait(self.pulse_seconds):
            ticks += 1
            # Periodic full sync like the reference's EC beat (17x pulse).
            self._send_heartbeat(full=(ticks % 17 == 0))
            try:
                self._lifecycle_tick()
            except Exception:  # noqa: BLE001 — never kill the heartbeat
                pass

    # -- holder-side lifecycle (TTL retirement + auto-promotion) -------------

    def _lifecycle_tick(self) -> None:
        """Piggybacks on the heartbeat cadence: retire TTL volumes whose
        newest write is past expiry (every needle inside is already a
        404 — the files are pure garbage), and promote tiered volumes
        the block cache says turned hot again."""
        from ..storage import expiry as _expiry
        from ..storage.remote_cache import CACHE
        for loc in self.store.locations:
            for v in list(loc.volumes.values()):
                ttl = v.super_block.ttl
                if ttl.minutes() > 0 and _expiry.volume_expired(
                        ttl, getattr(v, "modified_at", 0),
                        # Grace past nominal expiry: clock skew between
                        # writers plus a couple of pulses so the master
                        # steers away first.
                        grace=max(0.1 * ttl.minutes() * 60,
                                  2.0 * self.pulse_seconds)):
                    self._retire_expired_volume(v)
                    continue
                if v.remote_file is not None and \
                        self.tier_promote_hits > 0:
                    hits = CACHE.hits_in_window(
                        v.remote_file.backend.spec, v.remote_file.key,
                        self.tier_promote_window)
                    if hits >= self.tier_promote_hits:
                        self._schedule_promotion(v.vid)

    def _retire_expired_volume(self, v) -> None:
        """Whole-volume TTL retirement (the reference's volume-level
        TTL vacuum): drop the remote object if tiered, delete the local
        files, tell the master via a full heartbeat."""
        size = v.dat_size()
        tiered = v.remote_file is not None
        if tiered:
            # Best-effort remote delete BEFORE the local unmount: the
            # .vif (removed by delete_volume) is the only pointer to
            # the object, and a leaked remote .dat is paid-for garbage.
            from ..storage.tier import _tier_credentials, load_vif
            info = load_vif(v.file_name())
            if info and info.get("files"):
                fdesc = info["files"][0]
                try:
                    from ..storage.backend import backend_for_spec
                    ak, sk = _tier_credentials()
                    backend_for_spec(fdesc["backend_spec"], ak,
                                     sk).delete(fdesc["key"])
                except Exception:  # noqa: BLE001 — retirement proceeds
                    pass
        try:
            self.store.delete_volume(v.vid)
        except VolumeError:
            return
        from ..stats.metrics import ttl_expired_bytes_total
        ttl_expired_bytes_total.inc(size, via="volume_retire")
        self.usage.drop_volume(v.vid)
        emit_event("volume.expired", node=self.url(), vid=v.vid,
                   collection=v.collection, bytes=size, tiered=tiered,
                   ttl=str(v.super_block.ttl))
        try:
            self._send_heartbeat(full=True)
        except Exception:  # noqa: BLE001
            pass

    def _schedule_promotion(self, vid: int) -> None:
        """Sustained cache hits inside the window: bring the .dat back
        local in the background (one promotion per volume at a time)."""
        with self._promote_lock:
            if vid in self._promoting:
                return
            self._promoting.add(vid)
        threading.Thread(target=self._promote_volume, args=(vid,),
                         name=f"promote:{vid}", daemon=True).start()

    def _promote_volume(self, vid: int) -> None:
        from ..stats.metrics import lifecycle_actions_total
        from ..storage.tier import _tier_credentials, \
            move_dat_from_remote
        try:
            v = self.store.find_volume(vid)
            if v is None or v.remote_file is None:
                return
            ak, sk = _tier_credentials()
            try:
                move_dat_from_remote(v, access_key=ak, secret_key=sk)
            except Exception:  # noqa: BLE001 — retried next window
                lifecycle_actions_total.inc(action="promote",
                                            outcome="error")
                return
            lifecycle_actions_total.inc(action="promote", outcome="ok")
            emit_event("lifecycle.promote", node=self.url(), vid=vid,
                       collection=v.collection, bytes=v.dat_size())
            try:
                self._send_heartbeat(full=True)
            except Exception:  # noqa: BLE001
                pass
        finally:
            with self._promote_lock:
                self._promoting.discard(vid)

    def _debug_tier(self, query: dict, body: bytes) -> dict:
        """Tier state of every volume here + the shared cache's live
        numbers — the data behind `volume.tier.status`."""
        from ..storage.remote_cache import CACHE
        from ..storage.tier import load_vif
        vols = []
        for loc in self.store.locations:
            for v in list(loc.volumes.values()):
                ent = {"volume": v.vid, "collection": v.collection,
                       "tiered": v.remote_file is not None,
                       "ttl": str(v.super_block.ttl),
                       "modified_at": getattr(v, "modified_at", 0)}
                if v.remote_file is not None:
                    info = load_vif(v.file_name()) or {}
                    files = info.get("files") or [{}]
                    ent["remote"] = {
                        "backend_spec": files[0].get("backend_spec"),
                        "key": files[0].get("key"),
                        "file_size": files[0].get("file_size")}
                    ent["hits_in_window"] = CACHE.hits_in_window(
                        v.remote_file.backend.spec, v.remote_file.key,
                        self.tier_promote_window)
                vols.append(ent)
        return {"volumes": vols, "cache": CACHE.stats(),
                "promote": {"hits": self.tier_promote_hits,
                            "window": self.tier_promote_window}}

    # -- public needle handlers ---------------------------------------------

    def _parse_fid_path(self, path: str) -> tuple[int, int, int]:
        fid = urllib.parse.unquote(path.lstrip("/"))
        return t.parse_file_id(fid)

    _VOL_LOOKUP_TTL = 60.0
    _VOL_LOOKUP_NEG_TTL = 5.0

    def _lookup_volume(self, vid: int) -> dict:
        """Cached master /dir/lookup (operation/lookup.go's vid cache)
        shared by the misdirected-read redirect and the replication
        fan-out — neither may hammer the master per request.  A
        definitive negative answer (the master does not know the
        volume) is negative-cached briefly, so clients hammering stale
        fids don't turn every local 404 into a master round-trip."""
        now = time.time()
        hit = self._vol_loc_cache.get(vid)
        if hit and now < hit[0]:
            return hit[1]
        # Cache miss = one master round-trip; on a trace this is where
        # read-redirect / replication fan-out latency hides.
        with trace_span("volume.loc_lookup", vid=vid):
            try:
                resp = rpc.call(
                    f"{self.master_url}/dir/lookup?volumeId={vid}")
            except rpc.RpcError:
                self._vol_loc_cache[vid] = (
                    now + self._VOL_LOOKUP_NEG_TTL, {})
                raise
        self._vol_loc_cache[vid] = (now + self._VOL_LOOKUP_TTL, resp)
        return resp

    def _read_redirect_or_404(self, vid: int, path: str, query: dict):
        """Non-local volume on the read path: 301 to a current holder
        when -read.redirect is on (GetOrHeadHandler,
        volume_server_handlers_read.go:62-83; default true,
        volume.go:79), else 404 like a redirect-less server.  EC-only
        volumes redirect to a shard holder (any holder serves reads by
        distributed reconstruction), like the reference's topology
        lookup falling back to EC locations."""
        if self.read_redirect:
            urls: list[str] = []
            try:
                out = self._lookup_volume(vid)
                for loc in out.get("locations", []):
                    urls.append(loc.get("publicUrl") or loc.get("url"))
                for dns in out.get("ecShards", {}).values():
                    for d in dns:
                        urls.append(d.get("publicUrl") or d.get("url"))
            except Exception:  # noqa: BLE001 — master down: plain 404
                pass
            scheme = "https" if self.server.ssl_context else "http"
            for url in urls:
                if url and url != self.url():
                    target = f"{scheme}://{url}{path}"
                    if query.get("collection"):
                        target += "?collection=" + urllib.parse.quote(
                            query["collection"])
                    return (301, b"", {"Location": target})
        raise rpc.RpcError(404, f"volume {vid} not on this server")

    def _head_needle(self, path: str, query: dict, body: bytes):
        """Existence/size probe without the body (fsck, clients)."""
        vid, key, cookie = self._parse_fid_path(path)
        v = self.store.find_volume(vid)
        if v is None and vid not in self.ec_volumes:
            return self._read_redirect_or_404(vid, path, query)
        if v is not None:
            try:
                n = self.store.read_needle(vid, key, cookie)
            except NotFoundError as e:
                raise rpc.RpcError(404, str(e)) from None
            except TierReadError as e:
                raise rpc.RpcError(503, str(e),
                                   headers={"Retry-After": "1"}) \
                    from None
            except CorruptNeedleError as e:
                # A probe must answer what IS here: 503 flags a rotten
                # local copy so fsck/replica-repair treat this holder
                # as unhealthy without transferring a body.
                raise rpc.RpcError(503, str(e)) from None
            except VolumeError as e:
                raise rpc.RpcError(403, str(e)) from None
            size = len(n.data)
            # HEAD shares GET's handler in the reference
            # (GetOrHeadHandler): same ETag/Last-Modified/Content-Type/
            # Content-Disposition and the same 304 short-circuits, so a
            # cache-validation flow can start from a HEAD.
            hdrs, not_modified = self._conditional_headers(
                query, f"{n.checksum:08x}",
                n.name if n.has_name() else b"",
                n.mime if n.has_mime() else b"",
                int(n.last_modified) if n.has_last_modified_date()
                else 0)
            if not_modified:
                return (304, b"", hdrs)
            hdrs["Accept-Ranges"] = "bytes"
            if n.is_compressed() and size >= 4:
                # HEAD must mirror GET's negotiation: a gzip-accepting
                # client would receive the stored bytes (report that
                # length + encoding), anyone else the inflated body —
                # sized by the gzip ISIZE trailer (last 4 bytes, LE)
                # without actually inflating the needle.
                if "gzip" in query.get("_accept_encoding", ""):
                    hdrs["Content-Encoding"] = "gzip"
                else:
                    size = int.from_bytes(n.data[-4:], "little")
            hdrs["Content-Length"] = str(size)
            return (200, b"", hdrs)
        # EC probe: locate-only (.ecx binary search + .ecj check) —
        # reports 404 for absent/deleted needles without reconstructing
        # any data.
        ev = self.ec_volumes[vid]
        self._ensure_ec_version(ev)
        try:
            ev.locate_needle(key)
        except NeedleNotFound as e:
            raise rpc.RpcError(404, str(e)) from None
        return (200, b"", {})

    # Payloads at least this large go out via the zero-copy sendfile
    # path (CRC-checked preads + os.sendfile) — the DEFAULT whole-
    # needle GET path, not a large-object special case: one page is
    # the break-even where the extra metadata preads cost less than
    # the userspace copy they avoid.  Records needing the parse path
    # (compressed, TTL'd, tiered, v1 layout, resize) decline the slice
    # and fall through unchanged; tune/disable with -read.sendfile.min.
    SENDFILE_MIN = 4096

    @staticmethod
    def _principal(query: dict) -> tuple[str, str]:
        """(tenant, originating client) the rpc middleware resolved —
        `_client` carries the X-Weed-Client a proxying filer forwarded,
        so hot-key attribution names the real caller, not the proxy."""
        return (query.get("_tenant", ""),
                query.get("_client", "") or
                query.get("_remote_addr", ""))

    def _get_needle(self, path: str, query: dict, body: bytes):
        vid, key, cookie = self._parse_fid_path(path)
        tenant, client = self._principal(query)
        self.hot.read(vid, key, client, tenant)
        if _fault.ARMED:
            _fault.hit("volume.read", vid=vid, server=self.url())
        v = self.store.find_volume(vid)
        if v is None:
            ev = self.ec_volumes.get(vid)
            if ev is None:
                return self._read_redirect_or_404(vid, path, query)
            n = self._ec_read(ev, key, cookie)
        else:
            # Lock-free size peek decides the path so the dominant
            # small-read case pays zero extra lookups (a stale peek
            # only mis-routes to the other path, which re-validates).
            ent = v.nm.get(key)
            if ent is not None and self.sendfile_min > 0 and \
                    ent[1] >= self.sendfile_min and \
                    "width" not in query and "height" not in query:
                # Zero-copy fast path for large plain needles: CRC is
                # verified by streaming preads, then the responder
                # os.sendfile's the payload straight from the .dat
                # (VERDICT r4 #1; the reference serves the same bytes
                # after its own CRC check,
                # volume_server_handlers_read.go:28).
                try:
                    sl = v.read_needle_slice(key, cookie,
                                             min_size=self.sendfile_min)
                except NotFoundError as e:
                    raise rpc.RpcError(404, str(e)) from None
                except (CorruptNeedleError, OSError) as e:
                    # Degraded read: heal in line and serve the
                    # repaired bytes rather than erroring.
                    n = self._degraded_read(v, vid, key, cookie, e)
                    return self._serve_needle(n, query)
                except VolumeError as e:
                    raise rpc.RpcError(403, str(e)) from None
                if sl is not None:
                    cond, not_modified = self._conditional_headers(
                        query, sl.etag, sl.name, sl.mime,
                        sl.last_modified)
                    if not_modified:
                        sl.close()
                        return (304, b"", cond)
                    cond.setdefault("Content-Type",
                                    "application/octet-stream")
                    cond["Accept-Ranges"] = "bytes"
                    try:
                        rng = rpc.parse_byte_range(
                            query.get("_range_header", ""), sl.size)
                    except rpc.RpcError:  # 416: the slice owns an fd
                        sl.close()
                        raise
                    if rng is not None:
                        # CRC was verified over the whole payload;
                        # sendfile just the requested window
                        # (processRangeRequest single-range path).
                        lo, hi = rng
                        total = sl.size
                        sl.offset += lo
                        sl.size = hi - lo + 1
                        self.usage.note_request(tenant,
                                                read_bytes=sl.size)
                        return (206, sl, {
                            **cond,
                            "Content-Length": str(sl.size),
                            "Content-Range":
                            f"bytes {lo}-{hi}/{total}"})
                    self.usage.note_request(tenant, read_bytes=sl.size)
                    return (200, sl,
                            {**cond,
                             "Content-Length": str(sl.size)})
            try:
                n = self.store.read_needle(vid, key, cookie)
            except NotFoundError as e:
                if key in v.repair_tickets:
                    # Quarantined (tombstoned) corrupt needle: a
                    # replica may still hold it — degraded read.
                    n = self._degraded_read(v, vid, key, cookie, e)
                else:
                    raise rpc.RpcError(404, str(e)) from None
            except TierReadError as e:
                # Remote tier unreachable (WAN partition / backend
                # down): the local bytes are gone BY DESIGN, so
                # degraded-read repair has nothing to heal — answer a
                # bounded, retryable 503 with a pacing hint.
                raise rpc.RpcError(503, str(e),
                                   headers={"Retry-After": "1"}) \
                    from None
            except (CorruptNeedleError, OSError) as e:
                # CRC failure or a dying sector on the read path: the
                # same self-healing repair the scrub uses, in line —
                # the client gets the repaired bytes, not an error.
                n = self._degraded_read(v, vid, key, cookie, e)
            except VolumeError as e:
                raise rpc.RpcError(403, str(e)) from None
        return self._serve_needle(n, query)

    def _serve_needle(self, n: Needle, query: dict):
        """Post-read pipeline shared by the replicated and EC paths:
        gzip negotiation, optional image resize, then Range shaping on
        the outgoing representation (processRangeRequest,
        weed/server/common.go:233 via
        volume_server_handlers_read.go:255-264) — storage layout must
        never change read behavior."""
        self.usage.note_request(query.get("_tenant", ""),
                                read_bytes=len(n.data))
        cond, not_modified = self._conditional_headers(
            query, f"{n.checksum:08x}", n.name if n.has_name() else b"",
            n.mime if n.has_mime() else b"",
            int(n.last_modified) if n.has_last_modified_date() else 0)
        if not_modified:
            return (304, b"", cond)
        if n.is_compressed():
            # Stored gzipped (volume_server_handlers_read.go): hand the
            # raw bytes to readers that accept gzip, decompress for the
            # rest.  Resize always needs the plain image bytes.
            from ..utils.compression import ungzip_data
            if "gzip" in query.get("_accept_encoding", "") and \
                    "width" not in query and "height" not in query:
                return self._maybe_range(
                    query, n.data,
                    {**cond, "Content-Encoding": "gzip"})
            n.data = ungzip_data(n.data)
        if "width" in query or "height" in query:
            # On-the-fly resize for image reads
            # (volume_server_handlers_read.go:219-243).  Malformed
            # dimensions degrade to 0 = unresized, like the reference's
            # atoi — never a 500 on a valid needle read.
            from ..images import resized

            def _dim(name: str) -> int:
                try:
                    return max(0, int(query.get(name, 0) or 0))
                except ValueError:
                    return 0
            data, mime = resized(n.data, _dim("width"), _dim("height"),
                                 query.get("mode", ""))
            if mime:
                cond = {**cond, "Content-Type": mime}
            return self._maybe_range(query, data, cond)
        return self._maybe_range(query, n.data, cond)

    @staticmethod
    def _conditional_headers(query: dict, etag: str, name: bytes,
                             mime: bytes, last_modified: int):
        """Caching/content headers for a needle GET + the 304
        short-circuit (volume_server_handlers_read.go:113-129 and
        adjustHeaderContentDisposition, common.go:221): ETag is the
        quoted 8-hex checksum, Last-Modified honors If-Modified-Since,
        If-None-Match matches the quoted etag, needle mime wins unless
        it is octet-stream, and a named needle gets inline/attachment
        disposition (?dl=true).  Returns (headers, not_modified)."""
        from email.utils import formatdate, parsedate_to_datetime
        # The stored CRC as an explicit header on HEAD and GET alike:
        # volume.fsck -crc and replica repair compare content identity
        # across holders without bodies (and without unquoting ETags).
        hdrs = {"ETag": f'"{etag}"', "X-Needle-Checksum": etag}
        if last_modified:
            hdrs["Last-Modified"] = formatdate(last_modified,
                                               usegmt=True)
            ims = query.get("_if_modified_since", "")
            if ims:
                try:
                    dt = parsedate_to_datetime(ims)
                    if dt.tzinfo is None:
                        # Zone-less dates (obsolete asctime form) are
                        # GMT per RFC 7231; naive .timestamp() would
                        # apply the server's local offset.
                        from datetime import timezone
                        dt = dt.replace(tzinfo=timezone.utc)
                    t_ims = dt.timestamp()
                except (TypeError, ValueError):
                    t_ims = None
                if t_ims is not None and t_ims >= last_modified:
                    return hdrs, True
        if query.get("_if_none_match", "") == f'"{etag}"':
            return hdrs, True
        if mime and not mime.startswith(b"application/octet-stream"):
            hdrs["Content-Type"] = mime.decode("utf-8", "replace")
        if name:
            disp = "inline"
            if query.get("dl", "").lower() in ("true", "1"):
                disp = "attachment"
            fname = (name.decode("utf-8", "replace")
                     .replace("\\", "\\\\").replace('"', '\\"'))
            hdrs["Content-Disposition"] = \
                f'{disp}; filename="{fname}"'
        return hdrs, False

    @staticmethod
    def _maybe_range(query: dict, data: bytes, hdrs: dict):
        """Range applies to the response representation (what's being
        sent after gzip/resize decisions), like the reference where
        processRangeRequest wraps the final writeFn."""
        hdrs = {"Accept-Ranges": "bytes", **hdrs}
        rng = rpc.parse_byte_range(query.get("_range_header", ""),
                                   len(data))
        if rng is None:
            return (200, data, hdrs)
        lo, hi = rng
        hdrs["Content-Range"] = f"bytes {lo}-{hi}/{len(data)}"
        return (206, data[lo:hi + 1], hdrs)

    def _ec_read(self, ev: EcVolume, key: int, cookie: int) -> Needle:
        """EC read path with the full distributed ladder (store_ec.go):
        local shard -> remote shard via peers -> on-the-fly reconstruction
        gathering >=10 shard intervals from the cluster.  Returns the
        parsed needle; response shaping lives in _serve_needle."""
        self._ensure_ec_version(ev)
        try:
            _offset, _size, intervals = ev.locate_needle(key)
        except NeedleNotFound as e:
            raise rpc.RpcError(404, str(e)) from None
        try:
            blob = b"".join(self._read_ec_interval(ev, iv)
                            for iv in intervals)
        except Exception as e:  # noqa: BLE001
            raise rpc.RpcError(500, f"{type(e).__name__}: {e}") from None
        n = Needle.from_bytes(blob, ev.version)
        if n.cookie != cookie:
            raise rpc.RpcError(403, "cookie mismatch")
        return n

    def _ensure_ec_version(self, ev: EcVolume) -> None:
        """Resolve the volume version over the cluster when local detection
        can't (no .vif, no local .ec00, <10 local shards): read the
        superblock head of shard 0 from a peer."""
        if ev._version is not None:
            return
        try:
            ev._version = ev._detect_version()
            return
        except Exception:  # noqa: BLE001 — fall through to remote
            pass
        from ..core.super_block import SuperBlock
        for url in self._ec_shard_locations(ev.vid).get(0, []):
            if url == self.url():
                continue
            try:
                head = rpc.call(
                    f"http://{url}/admin/ec/shard_read?volume={ev.vid}"
                    f"&shard=0&offset=0&size=64",
                    headers={**rpc.PRIORITY_LOW,
                             **_flows.tag("ec.gather")})
                ev._version = SuperBlock.from_bytes(bytes(head)).version
                return
            except Exception:  # noqa: BLE001
                continue
        raise rpc.RpcError(
            500, f"cannot determine version of ec volume {ev.vid}")

    @staticmethod
    def _loc_ttl(locs: dict[int, list[str]]) -> float:
        """Freshness tier for a shard-location lookup result, mirroring
        the reference's cachedLookupEcShardLocations tiers
        (store_ec.go:221-229): a set too small to serve reads (<10
        shards) is retried after 11s, an incomplete set after 7m, and a
        full 14-shard map is trusted for 37m."""
        n = len(locs)
        if n < 10:
            return 11.0
        if n < TOTAL_SHARDS:
            return 7 * 60.0
        return 37 * 60.0

    def _ec_shard_locations(self, vid: int,
                            refresh: bool = False) -> dict[int, list[str]]:
        """Shard id -> server urls, cached with tiered freshness."""
        now = time.time()
        hit = self._ec_loc_cache.get(vid)
        if hit and not refresh and now - hit[0] < hit[1]:
            return hit[2]
        locs: dict[int, list[str]] = {}
        try:
            resp = rpc.call(f"{self.master_url}/dir/lookup?volumeId={vid}")
            for sid_str, dns in resp.get("ecShards", {}).items():
                locs[int(sid_str)] = [d["url"] for d in dns]
        except Exception:  # noqa: BLE001 — stale cache beats failing
            if hit:
                return hit[2]
        self._ec_loc_cache[vid] = (now, self._loc_ttl(locs), locs)
        return locs

    def _ec_pool(self) -> concurrent.futures.ThreadPoolExecutor:
        """Shared fan-out pool for degraded EC reads.  Tasks never submit
        nested work, so a bounded pool cannot deadlock."""
        with self._ec_pool_lock:
            if self._ec_read_pool is None:
                self._ec_read_pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=32, thread_name_prefix="ec-read")
            return self._ec_read_pool

    def _read_ec_interval(self, ev: EcVolume, interval) -> bytes:
        sid, off = interval.to_shard_id_and_offset(
            ev.large_block_size, ev.small_block_size)
        size = interval.size
        # 1. local shard
        shard = ev.shards.get(sid)
        if shard is not None:
            buf = shard.read_at(off, size)
            if len(buf) == size:
                return buf
        # 2. remote shard holders (failover across every holder, like
        #    readRemoteEcShardInterval walking sourceDataNodes)
        locations = self._ec_shard_locations(ev.vid)
        with trace_span("ec.shard_fetch", vid=ev.vid, shard=sid,
                        size=size):
            data = self._fetch_shard_interval(ev, locations, sid, off,
                                              size)
        if data is not None:
            return data
        # 3. reconstruct from >=10 other shard intervals.
        return self._reconstruct_shard_interval(ev, sid, off, size)

    def _reconstruct_shard_interval(self, ev: EcVolume, sid: int,
                                    off: int, size: int) -> bytes:
        """One shard interval through the decode path, codec-aware:
        gather the SAME byte range from the codec's planned MINIMAL
        survivor set — the local group for an in-group LRC loss (5
        reads), the first data_shards survivors for RS — widening to
        more siblings only when a planned read fails, and stopping the
        widened fan-out as soon as the erasure pattern solves (the old
        "any >=10" ladder, generalized to pick the cheapest survivor
        set).  Reads fan out in parallel (store_ec.go:322-376 launches
        one goroutine per shard); every gathered byte lands in
        SeaweedFS_ec_repair_read_bytes_total{codec=}.  Shared by the
        degraded read ladder and the scrub's corrupt-block repair."""
        locations = self._ec_shard_locations(ev.vid)
        codec = ev.codec
        with trace_span("ec.reconstruct", vid=ev.vid, shard=sid,
                        size=size, codec=codec.name) as rspan:
            # Pool threads have no thread-local trace context — hand
            # them this span's context explicitly.
            tp = rspan.traceparent() or None
            pool = self._ec_pool()
            t_gather = time.perf_counter()
            candidates = [s for s in range(codec.total_shards)
                          if s != sid]
            have: dict[int, bytes] = {}

            def solvable() -> bool:
                try:
                    codec.decode_matrix(tuple(have), (sid,))
                    return True
                except ValueError:
                    return False

            try:
                plan = codec.repair_plan(tuple(candidates), [sid])[0]
            except ValueError:
                raise rpc.RpcError(
                    500, f"shard {sid} of ec volume {ev.vid} is "
                         f"unrecoverable under codec {codec.name}"
                ) from None
            futs = {
                pool.submit(
                    self._fetch_shard_interval, ev, locations, other,
                    off, size, tp):
                other for other in plan.reads
            }
            for f in concurrent.futures.as_completed(futs):
                buf = f.result()
                if buf is not None:
                    have[futs[f]] = buf
            plan_ok = len(have) == len(plan.reads)
            if not plan_ok and not solvable():
                # A planned read failed: widen to every remaining
                # sibling, stopping as soon as the pattern solves.
                futs = {
                    pool.submit(
                        self._fetch_shard_interval, ev, locations,
                        other, off, size, tp):
                    other for other in candidates
                    if other not in plan.reads
                }
                for f in concurrent.futures.as_completed(futs):
                    buf = f.result()
                    if buf is not None:
                        have[futs[f]] = buf
                        if solvable():
                            break
                for f in futs:
                    f.cancel()
            # Network fan-out cost, separate from the GF solve below.
            gathered_bytes = sum(len(b) for b in have.values())
            observe_ec_stage("shard_gather",
                             time.perf_counter() - t_gather,
                             gathered_bytes)
            ec_repair_read_bytes_total.inc(gathered_bytes,
                                           codec=codec.name)
            if not solvable():
                # The location map let us down — drop it so the next
                # read refreshes immediately instead of waiting out the
                # TTL.
                self._ec_loc_cache.pop(ev.vid, None)
                raise rpc.RpcError(
                    500, f"cannot reconstruct shard {sid}: only "
                         f"{len(have)} shard intervals reachable")
            if plan_ok and plan.local:
                # Degraded read / repair served entirely from the
                # shard's locality group — the LRC payoff.
                emit_event("ec.repair.local", node=self.url(),
                           vid=ev.vid, shard=sid, codec=codec.name,
                           reads=len(have), bytes=gathered_bytes)
            import jax
            import numpy as np
            arrs = {k: np.frombuffer(v, dtype=np.uint8)
                    for k, v in have.items()}
            # Execution-fenced device time: block_until_ready is a
            # no-op passthrough for numpy/native coders and fences the
            # async dispatch for jax/pallas ones, so the histogram
            # records real solve time, not dispatch time.
            t_dev = time.perf_counter()
            rec = jax.block_until_ready(
                ev.coder.reconstruct(arrs, wanted=[sid]))
            observe_ec_stage("reconstruct_device",
                             time.perf_counter() - t_dev, size)
            t_stage = time.perf_counter()
            out = np.asarray(rec[sid]).tobytes()
            observe_ec_stage("host_staging",
                             time.perf_counter() - t_stage, size)
            rspan.set(gathered=len(have))
            return out

    def _fetch_shard_interval(self, ev: EcVolume,
                              locations: dict[int, list[str]],
                              sid: int, off: int, size: int,
                              traceparent: str | None = None
                              ) -> bytes | None:
        """One shard's interval: local file first, then every remote
        holder in turn.  Returns None when no source can serve it.
        `traceparent` carries the caller's trace context across the
        fan-out pool's thread boundary."""
        # Fan-out pool threads carry no flow identity of their own:
        # bind to this server so the gather's out-bytes attribute here
        # (idempotent; handler threads rebind per request anyway).
        _flows.bind_thread(self.url(), "volume")
        local = ev.shards.get(sid)
        if local is not None:
            buf = local.read_at(off, size)
            if len(buf) == size:
                return buf
        me = self.url()
        # Shard gathers are internal traffic (low-priority lane at the
        # holder): a rebuild/degraded-read storm must not starve the
        # holder's user reads.  Flow-attributed as ec.gather — pool
        # worker threads carry no purpose context, so the header rides
        # explicitly.
        hdrs = {**rpc.PRIORITY_LOW, **_flows.tag("ec.gather")}
        if traceparent:
            hdrs["traceparent"] = traceparent
        for url in locations.get(sid, []):
            if url == me:
                continue
            try:
                if _fault.ARMED:
                    _fault.hit("ec.fetch_shard", holder=url,
                               vid=ev.vid, shard=sid)
                data = rpc.call(
                    f"http://{url}/admin/ec/shard_read?volume={ev.vid}"
                    f"&shard={sid}&offset={off}&size={size}",
                    headers=hdrs)
                if len(data) == size:
                    return bytes(data)
            except Exception:  # noqa: BLE001 — try next holder
                continue
        return None

    # -- self-healing repair (the scrub daemon calls back here) --------------

    def _degraded_read(self, v, vid: int, key: int,
                       cookie: int | None, err: Exception) -> Needle:
        """Read-path fallback: a CRC-failing (or unreadable, or
        quarantined) needle triggers the same repair the scrub uses,
        inline, and the request is served the repaired bytes — a
        degraded read, not an error (store_ec.go's degraded ladder
        applied to replication)."""
        emit_event("needle.corrupt", node=self.url(), severity="error",
                   vid=vid, key=f"{key:x}", kind="needle", path="read",
                   error=str(err)[:200])
        n = self._repair_needle_from_replica(v, key)
        if n is None:
            if isinstance(err, CorruptNeedleError):
                # Proven rot with no healthy source: quarantine so the
                # bad bytes are never served, and report degraded.
                if v.quarantine_needle(key, node=self.url()):
                    self._send_heartbeat(full=True)
            raise rpc.RpcError(
                500, f"needle {key:x} corrupt/unreadable and no "
                     f"replica could repair it: {err}")
        if cookie is not None and n.cookie != cookie:
            raise rpc.RpcError(403,
                               f"cookie mismatch for needle {key:x}")
        return n

    def _repair_needle_from_replica(self, v, key: int) -> Needle | None:
        """Fetch the raw CRC-verified record of one needle from a
        healthy sibling replica (/admin/needle_raw — which never
        serves rotten bytes) and rewrite it in place, closing the
        repair ticket.  Returns the healed Needle, or None when no
        replica could supply a sound copy."""
        vid = v.vid
        # May run on the scrub daemon's thread: bind the flow identity.
        _flows.bind_thread(self.url(), "volume")
        try:
            lookup = self._lookup_volume(vid)
        except Exception:  # noqa: BLE001 — master down: cannot locate
            return None
        me = self.url()
        for loc in lookup.get("locations", []):
            url = loc.get("url")
            if not url or url == me:
                continue
            try:
                blob = rpc.call(f"http://{url}/admin/needle_raw?"
                                f"volume={vid}&key={key}",
                                headers={**rpc.PRIORITY_LOW,
                                         **_flows.tag("repair.fetch")})
                n = Needle.from_bytes(bytes(blob), v.version)
            except Exception:  # noqa: BLE001 — next replica
                continue
            if n.id != key:
                continue
            v.repair_needle(n)
            needle_repairs_total.inc(source="replica")
            emit_event("needle.repaired", node=me, vid=vid,
                       key=f"{key:x}", source="replica", replica=url)
            return n
        return None

    def _repair_ec_block(self, ev: EcVolume, sid: int, offset: int,
                         size: int, block_index: int,
                         want_crc: int) -> bool:
        """Reconstruct one corrupt shard block through the EC decode
        path (>=10 sibling shard intervals -> one GF solve on the
        device coder) and pwrite it back in place — ONLY if the
        reconstruction reproduces the recorded checksum.  A wrong
        solve (a second, still-undetected corrupt source shard) must
        leave the original bytes untouched: overwriting a 1-bit flip
        with fresh garbage would destroy evidence a later repair
        round could still use."""
        from ..core.crc import crc32c
        try:
            data = self._reconstruct_shard_interval(ev, sid, offset,
                                                    size)
        except Exception:  # noqa: BLE001 — not enough healthy shards
            return False
        shard = ev.shards.get(sid)
        if shard is None or len(data) != size or \
                crc32c(data) != want_crc:
            return False
        with open(shard.path, "r+b") as f:
            os.pwrite(f.fileno(), data, offset)
            os.fsync(f.fileno())
        needle_repairs_total.inc(source="ec")
        emit_event("needle.repaired", node=self.url(), vid=ev.vid,
                   shard=sid, block=block_index, source="ec",
                   bytes=size)
        return True

    def _admin_scrub(self, query: dict, body: bytes) -> dict:
        """POST /admin/scrub {volume?, repair?}: run one integrity
        sweep now (volume.scrub shell command, tests).  The follow-up
        full heartbeat republishes corrupt counts so /cluster/healthz
        reflects the sweep immediately."""
        req = json.loads(body) if body else {}
        out = self.scrub.scrub_all(repair=bool(req.get("repair")),
                                   vid=req.get("volume"))
        self._send_heartbeat(full=True)
        return out

    def _admin_scrub_status(self, query: dict, body: bytes) -> dict:
        volumes = []
        for loc in self.store.locations:
            for v in loc.volumes.values():
                volumes.append({
                    "id": v.vid, "last_scrub": v.last_scrub,
                    "corrupt_count": v.corrupt_count(),
                    "tickets": sorted(f"{k:x}"
                                      for k in v.repair_tickets)})
        return {"volumes": volumes,
                "ec_corrupt": {str(vid): [list(b) for b in blocks]
                               for vid, blocks in
                               self.scrub.ec_corrupt_snapshot().items()}}

    def _admin_scrub_repair(self, query: dict, body: bytes) -> dict:
        """POST /admin/scrub/repair {volume, key}: targeted repair of
        one needle from a replica — volume.check.disk drives this to
        sync a replica that diverged (missing/rotten needle)."""
        req = json.loads(body)
        v = self.store.find_volume(req["volume"])
        if v is None:
            raise rpc.RpcError(404,
                               f"volume {req['volume']} not here")
        key = int(req["key"])
        n = self._repair_needle_from_replica(v, key)
        if n is None:
            raise rpc.RpcError(
                500, f"needle {key:x}: no replica could supply a "
                     f"healthy copy")
        self._send_heartbeat(full=True)
        return {"volume": v.vid, "key": f"{key:x}",
                "size": len(n.data)}

    def _admin_needle_raw(self, query: dict, body: bytes):
        """GET /admin/needle_raw?volume=&key=: the raw CRC-verified
        record bytes of one live needle — what a sibling pulls to heal
        its copy.  Never serves rotten bytes: a local CRC failure is a
        503, so replica repair cannot propagate corruption."""
        vid = int(query["volume"])
        v = self.store.find_volume(vid)
        if v is None:
            raise rpc.RpcError(404, f"volume {vid} not on this server")
        try:
            blob = v.read_needle_blob(int(query["key"]))
        except NotFoundError as e:
            raise rpc.RpcError(404, str(e)) from None
        except (CorruptNeedleError, OSError) as e:
            raise rpc.RpcError(503, str(e)) from None
        return (200, blob,
                {"Content-Type": "application/octet-stream",
                 "X-Volume-Version": str(v.version)})

    # -- cross-cluster replication (standby receive + surfaces) --------------

    def _replication_watermark(self, v):
        """The volume's durable applied-seq watermark (standby side)."""
        from ..replication.rlog import Watermark
        with self._replication_apply_lock:
            wm = self._replication_applied.get(v.vid)
            if wm is None:
                wm = Watermark(v.file_name() + ".rap")
                self._replication_applied[v.vid] = wm
        return wm

    def _replication_apply(self, query: dict, body: bytes) -> dict:
        """POST /admin/replication/apply — one shipped change-log
        batch from the primary.  Idempotent by (needle id, cookie,
        seq): records at or below the durable applied watermark are
        skipped, so duplicated delivery and replayed batches are
        no-ops; records apply in seq order, so a WRITE followed by its
        DELETE converges to the tombstone (a delete never resurrects).
        The ack `{"acked_seq": N}` goes out only after the watermark
        is persisted — the primary advancing on it can never skip a
        record this side might not remember applying.

        Accepted while draining: like ?type=replicate traffic, an
        inbound mirror batch is the tail of writes the PRIMARY already
        committed and acked.

        Geo active/active adds three gates (all 4xx — the sender must
        not treat them as a WAN failure): a zlib `codec` batch is
        inflated first and its raw/wire sizes ride the ack; a batch
        stamped `(cluster_id, epoch)` is fenced against the local
        `.lease` (stale epochs are the old holder talking — 409); and
        a batch whose first NEW seq leaves a gap above the applied
        watermark is refused UNACKED (409), because acking it would
        let reordered delivery skip the missing records forever."""
        import base64
        import zlib
        req = json.loads(body)
        vid = int(req["volume"])
        records = req.get("records", [])
        raw_bytes = wire_bytes = 0
        if req.get("codec") == "zlib":
            comp = base64.b64decode(req.get("records_z") or "")
            wire_bytes = len(comp)
            try:
                raw = zlib.decompress(comp)
            except zlib.error as e:
                raise rpc.RpcError(
                    400, f"volume {vid}: bad zlib batch: {e}") \
                    from None
            raw_bytes = len(raw)
            records = json.loads(raw)
        v = self.store.find_volume(vid)
        if v is None:
            # First batch for a volume the standby doesn't host yet:
            # create it (the assign_volume path) and heartbeat so the
            # peer master's /dir/lookup resolves it from now on.  No
            # rlog here — standby mutations arrive FROM a mirror and
            # must not ship back.
            try:
                v = self.store.add_volume(
                    vid, req.get("collection", ""),
                    req.get("replication", "000"), req.get("ttl", ""),
                    version=int(req.get("version", CURRENT_VERSION)))
            except VolumeError:
                v = self.store.find_volume(vid)
                if v is None:
                    raise rpc.RpcError(
                        500, f"cannot host mirrored volume {vid}") \
                        from None
            try:
                self._send_heartbeat(full=True)
            except Exception:  # noqa: BLE001 — master down: lookup
                pass           # resolves after the next pulse
        sender = str(req.get("cluster_id") or "")
        if sender and self.leases is not None:
            # Epoch fence: the geo safety invariant's receive half.
            # A stale-epoch batch is a partitioned old holder still
            # talking — refuse it so two clusters can never both
            # commit a write for this volume.
            reason = self.leases.check_batch(
                vid, sender, int(req.get("epoch", 0)))
            if reason is not None:
                emit_event("lease.fence", node=self.url(),
                           severity="warn", vid=vid, sender=sender,
                           epoch=int(req.get("epoch", 0)),
                           reason=reason)
                raise rpc.RpcError(409, f"volume {vid}: {reason}")
        wm = self._replication_watermark(v)
        applied = skipped = 0
        last = wm.value
        recs_sorted = sorted(records, key=lambda r: r["seq"])
        fresh = [r for r in recs_sorted if int(r["seq"]) > last]
        if fresh and int(fresh[0]["seq"]) > last + 1:
            # Gap above the watermark: batch n+1 arrived before batch
            # n (wan.reorder, or a lost prefix).  Refuse WITHOUT
            # acking — the sender's watermark holds and it re-ships
            # in order.
            raise rpc.RpcError(
                409, f"volume {vid}: gap — first new seq "
                     f"{fresh[0]['seq']} > applied {last} + 1 "
                     f"(reordered batch refused unacked)")
        for rec in recs_sorted:
            seq = int(rec["seq"])
            if seq <= last:
                skipped += 1
                continue
            op = int(rec["op"])
            if op == 1 and rec.get("blob"):  # OP_WRITE
                blob = base64.b64decode(rec["blob"])
                try:
                    n = Needle.from_bytes(blob, v.version)  # CRC gate
                except ValueError as e:
                    raise rpc.RpcError(
                        400, f"volume {vid} seq {seq}: {e}") from None
                v.write_needle(n, journal=False)
            elif op == 2:  # OP_DELETE — tombstones ALWAYS apply
                v.delete_needle(int(rec["needle_id"]), journal=False)
            # OP_VACUUM and blobless WRITEs advance the watermark only.
            last = seq
            applied += 1
        wm.set(last)
        out = {"acked_seq": last, "applied": applied,
               "skipped": skipped}
        if req.get("codec") == "zlib":
            # Per-batch compression accounting rides the ack: the
            # sender's shipped{raw,wire} totals and the geo bench's
            # compressed-vs-raw WAN spend both come from here.
            out["raw_bytes"] = raw_bytes
            out["wire_bytes"] = wire_bytes
        return out

    def _replication_pause(self, query: dict, body: bytes) -> dict:
        if self.shipper is None:
            raise rpc.RpcError(400, "no -replicate.peer configured")
        self.shipper.paused = True
        return {"paused": True}

    def _replication_resume(self, query: dict, body: bytes) -> dict:
        if self.shipper is None:
            raise rpc.RpcError(400, "no -replicate.peer configured")
        self.shipper.paused = False
        self.shipper.kick()
        return {"paused": False}

    def _debug_replication(self, query: dict, body: bytes) -> dict:
        """GET /debug/replication — both sides of the mirror on one
        surface: the shipper's per-volume watermarks/lag (primary
        role) and the per-volume applied seqs (standby role)."""
        doc: dict = {"node": self.url(), "role": []}
        if self.shipper is not None:
            doc["role"].append("primary")
            doc["shipper"] = self.shipper.status()
            doc["rlog"] = {}
            for loc in self.store.locations:
                for v in list(loc.volumes.values()):
                    if v.rlog is not None:
                        doc["rlog"][str(v.vid)] = v.rlog.status()
        with self._replication_apply_lock:
            applied = {str(vid): wm.value for vid, wm in
                       self._replication_applied.items()}
        if applied:
            doc["role"].append("standby")
        doc["applied"] = applied
        if self.leases is not None:
            doc["cluster_id"] = self.geo_cluster_id
            doc["leases"] = self.leases.snapshot()
        return doc

    def _lease_status(self, query: dict, body: bytes) -> dict:
        """GET /admin/lease/status[?volume=V] — this node's lease
        table: per-volume `{cluster_id, epoch, acquired_ts,
        holder_is_local, moving}` rows.  The peer's shipper reads this
        on a 409 fence to adopt the authoritative epoch."""
        if self.leases is None:
            return {"node": self.url(), "cluster_id": None,
                    "leases": {}}
        rows = self.leases.snapshot()
        if query.get("volume"):
            want = str(int(query["volume"]))
            rows = {k: v for k, v in rows.items() if k == want}
        return {"node": self.url(),
                "cluster_id": self.geo_cluster_id, "leases": rows}

    def _lease_acquire(self, query: dict, body: bytes) -> dict:
        """POST /admin/lease/acquire {volume, cluster_id?, epoch?} —
        fence `cluster_id` (default: this cluster) as the volume's
        holder.  Epoch defaults to one past what this node knows, so a
        bare acquire always fences prior holders; an explicit epoch is
        the transfer protocol's second half (the new holder adopting
        the epoch the old holder demoted at).  Monotonic: a stale
        epoch is a no-op returning the current lease."""
        if self.leases is None:
            raise rpc.RpcError(
                400, "no -geo.cluster.id configured on this node")
        req = json.loads(body) if body else {}
        vid = int(req.get("volume", query.get("volume", 0)) or 0)
        v = self.store.find_volume(vid)
        if v is None:
            raise rpc.RpcError(404, f"volume {vid} not on this server")
        v.enable_rlog()  # geo volumes always journal
        cluster = str(req.get("cluster_id") or self.geo_cluster_id)
        epoch = int(req["epoch"]) if "epoch" in req \
            else self.leases.epoch(vid) + 1
        lease = self.leases.fence(vid, cluster, epoch)
        emit_event("lease.acquire", node=self.url(), vid=vid,
                   cluster_id=lease.cluster_id, epoch=lease.epoch)
        try:
            self._send_heartbeat(full=True)
        except Exception:  # noqa: BLE001 — master down: the rollup
            pass           # catches up on the next pulse
        out = lease.to_doc()
        out["volume"] = vid
        out["holder_is_local"] = \
            lease.cluster_id == self.geo_cluster_id
        return out

    def _lease_move(self, query: dict, body: bytes) -> dict:
        """POST /admin/lease/move {volume, to, timeout?} — transfer
        the write lease to cluster `to`.  The order IS the safety
        argument: (1) refuse new local writes (`begin_move`), (2)
        drain — kick the shipper until the rlog has nothing pending,
        (3) DEMOTE FIRST: fence ourselves out by writing `to` at
        epoch+1 into our own sidecar, (4) best-effort tell the peer to
        acquire at that exact epoch.  A partition between (3) and (4)
        leaves NO holder — writes 503 everywhere until heal (the peer
        also learns the new epoch from the next shipped batch) —
        fail-closed, never split-brained.  A drain timeout aborts
        BEFORE step 3: the lease did not move."""
        if self.leases is None:
            raise rpc.RpcError(
                400, "no -geo.cluster.id configured on this node")
        if self.shipper is None:
            raise rpc.RpcError(
                400, "no -replicate.peer configured (cannot drain or "
                     "reach the target cluster)")
        req = json.loads(body) if body else {}
        vid = int(req.get("volume", 0) or 0)
        to = str(req.get("to") or "")
        if not to or to == self.geo_cluster_id:
            raise rpc.RpcError(
                400, f"bad target cluster {to!r} (want the peer's "
                     f"-geo.cluster.id, not our own)")
        v = self.store.find_volume(vid)
        if v is None:
            raise rpc.RpcError(404, f"volume {vid} not on this server")
        if not self.leases.is_holder(vid):
            raise rpc.RpcError(
                409, f"volume {vid}: lease held by "
                     f"{self.leases.holder(vid)} at epoch "
                     f"{self.leases.epoch(vid)} — not ours to move")
        v.enable_rlog()
        old_epoch = self.leases.epoch(vid)
        timeout = float(req.get("timeout", 10.0) or 10.0)
        deadline = time.monotonic() + timeout
        self.leases.begin_move(vid)
        try:
            # Drain: every committed write must reach the new holder
            # BEFORE it takes over, or the epoch fence would orphan
            # the tail.  begin_move already refuses new writes, so
            # pending() is strictly decreasing from here.
            while v.rlog is not None and v.rlog.pending() > 0:
                if time.monotonic() > deadline:
                    raise rpc.RpcError(
                        503, f"volume {vid}: drain timed out with "
                             f"{v.rlog.pending()} records pending — "
                             f"lease NOT moved",
                        headers={"Retry-After": "1"})
                self.shipper.kick()
                time.sleep(0.02)
        except rpc.RpcError:
            self.leases.abort_move(vid)
            raise
        target = self.shipper._resolve_target(vid)
        new_epoch = old_epoch + 1
        # DEMOTE FIRST (fence() also clears the moving flag): from
        # this instant we forward writes instead of committing them.
        self.leases.fence(vid, to, new_epoch)
        peer_acquired = False
        if target is not None:
            try:
                rpc.call_json(
                    f"http://{target}/admin/lease/acquire",
                    payload={"volume": vid, "cluster_id": to,
                             "epoch": new_epoch})
                peer_acquired = True
            except (rpc.RpcError, OSError, ConnectionError):
                pass  # the peer adopts the epoch from the data path
        emit_event("lease.move", node=self.url(), vid=vid,
                   to=to, epoch=new_epoch,
                   peer_acquired=peer_acquired)
        try:
            self._send_heartbeat(full=True)
        except Exception:  # noqa: BLE001
            pass
        out = {"volume": vid, "to": to, "epoch": new_epoch,
               "peer_acquired": peer_acquired}
        if not peer_acquired:
            out["warning"] = (
                "target cluster not reachable for the explicit "
                "acquire; it adopts the new epoch from the next "
                "shipped batch (writes 503 there until then)")
        return out

    def _debug_hot(self, query: dict, body: bytes) -> dict:
        """GET /debug/hot — heavy-hitter snapshot: top-k hot volumes,
        needles, and client IPs by read/write (stats/hotkeys.py).
        ?k=N sizes the lists; ?reset=1 clears the counters (a new
        observation window starts)."""
        try:
            k = int(query.get("k", 16) or 16)
        except ValueError:
            raise rpc.RpcError(400, "k must be a number") from None
        if query.get("reset") == "1":
            self.hot.clear()
        out = self.hot.snapshot(k=k)
        out["node"] = self.url()
        return out

    def _debug_tenants(self, query: dict, body: bytes) -> dict:
        """GET /debug/tenants — this node's live per-tenant ledger:
        stored bytes/objects by (tenant, collection) plus the sliding
        req/s and read/write bytes/s meters."""
        out = self.usage.snapshot()
        out["node"] = self.url()
        out["admission"] = self.server.admission.snapshot()
        return out

    def _debug_device(self, query: dict, body: bytes) -> dict:
        """GET /debug/device — the device roofline plane: probed
        peaks, per-kernel achieved-fraction table, recent invocations,
        pipeline occupancy gantts with bubble attribution, the
        analytic-vs-measured byte conservation verdict, and
        jax.local_devices() memory stats."""
        return _roofline.debug_doc(self.url(), "volume")

    def _ui(self, query: dict, body: bytes):
        """Status page (the reference's volume UI, server/volume_ui)."""
        from html import escape as esc
        rows = []
        for loc in self.store.locations:
            for v in list(loc.volumes.values()):
                rows.append(
                    f"<tr><td>{v.vid}</td>"
                    f"<td>{esc(v.collection) or '-'}</td>"
                    f"<td>{v.content_size() / 1e6:.1f}MB</td>"
                    f"<td>{v.file_count()}</td>"
                    f"<td>{'ro' if v.readonly else 'rw'}</td></tr>")
        ec_rows = "".join(
            f"<tr><td>{vid}</td><td>{sorted(ev.shards)}</td></tr>"
            for vid, ev in sorted(self.ec_volumes.items()))
        html = (
            "<!doctype html><title>seaweedfs-tpu volume</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "table{border-collapse:collapse}td,th{border:1px solid #ccc;"
            "padding:4px 8px}</style>"
            f"<h1>Volume server {self.url()}</h1>"
            f"<p>master: {esc(self.master_url)} &middot; "
            f"rack: {esc(self.rack)} &middot; "
            f"dc: {esc(self.data_center)}</p>"
            "<h2>Volumes</h2><table><tr><th>id</th><th>collection</th>"
            "<th>size</th><th>files</th><th>mode</th></tr>"
            + "".join(rows) + "</table>"
            + ("<h2>EC volumes</h2><table><tr><th>id</th>"
               "<th>local shards</th></tr>" + ec_rows + "</table>"
               if ec_rows else "")
            + "<p><a href='/admin/status'>JSON status</a> &middot; "
              "<a href='/metrics'>metrics</a></p>")
        return (200, html.encode(),
                {"Content-Type": "text/html; charset=utf-8"})

    def _check_write_jwt(self, path: str, query: dict) -> None:
        """JWT gate on the write path (volume_server_handlers.go
        maybeCheckJwtAuthorization).  Replicated writes are NOT exempt:
        the fan-out forwards the original client's jwt query param and
        each replica re-verifies it, matching store_replicate.go which
        forwards the JWT and still runs the auth check on replicas."""
        if not self.guard.signing_key:
            return
        from ..utils.security import JwtError
        fid = urllib.parse.unquote(path.lstrip("/"))
        try:
            self.guard.check_jwt(query.get("jwt", ""), fid)
        except JwtError as e:
            raise rpc.RpcError(401, f"jwt: {e}") from None

    def _refuse_if_draining(self, query: dict) -> None:
        """Draining servers take no NEW writes: 503 + Retry-After
        rides the client's RetryPolicy/re-assign machinery, and the
        master is already steering assignments away.  Replica fan-outs
        (?type=replicate) stay accepted — they are the tail of an
        operation a sibling already committed, and refusing a
        tombstone's propagation would leave this node resurrecting the
        needle after its restart.  Reads keep flowing until the
        process exits."""
        if self.draining and query.get("type") != "replicate":
            raise rpc.RpcError(
                503, f"volume server {self.url()} is draining",
                headers={"Retry-After": "1"})

    def _forward_if_not_holder(self, path: str, query: dict,
                               body: bytes, method: str,
                               vid: int) -> dict | None:
        """Geo write fencing at the door: a write landing at a
        non-holder cluster NEVER commits locally — it forwards to the
        lease holder's volume server (resolved through the peer
        master, like a shipped batch) and relays the holder's answer.
        Intra-cluster replica fan-outs (?type=replicate) are exempt:
        they are the tail of a write the local holder-check already
        admitted.  A forward that cannot reach a writable holder
        fails CLOSED with 503 + Retry-After — during a partition or a
        mid-move window the volume is unavailable for writes, never
        split-brained."""
        if self.leases is None or query.get("type") == "replicate" \
                or self.leases.is_holder(vid):
            return None
        holder = self.leases.holder(vid)
        if query.get("geo") == "fwd":
            # Already a forward (both sides think the other holds —
            # a contested or mid-move lease): refuse instead of
            # bouncing the write between clusters forever.
            raise rpc.RpcError(
                503, f"volume {vid}: no writable lease holder "
                     f"(lease contested or mid-move, epoch "
                     f"{self.leases.epoch(vid)})",
                headers={"Retry-After": "1"})
        target = self.shipper._resolve_target(vid) \
            if self.shipper is not None else None
        if target is None:
            raise rpc.RpcError(
                503, f"volume {vid}: lease held by cluster "
                     f"{holder}, no route to it from here",
                headers={"Retry-After": "1"})
        fwd = {k: v for k, v in query.items()
               if not k.startswith("_")}
        fwd["geo"] = "fwd"
        qs = urllib.parse.urlencode(fwd)
        hdrs = dict(_flows.tag("replicate.fanout"))
        if "gzip" in query.get("_content_encoding", ""):
            hdrs["Content-Encoding"] = "gzip"
        try:
            out = rpc.call(f"http://{target}{path}?{qs}", method,
                           body, headers=hdrs)
        except rpc.RpcError as e:
            if e.status < 500:
                raise  # the holder's own verdict (quota, jwt, 404…)
            raise rpc.RpcError(
                503, f"volume {vid}: lease holder {holder} "
                     f"unreachable ({e.message})",
                headers={"Retry-After": "1"}) from None
        return out if isinstance(out, dict) else {}

    def _post_needle(self, path: str, query: dict, body: bytes) -> dict:
        self._check_write_jwt(path, query)
        self._refuse_if_draining(query)
        vid, key, cookie = self._parse_fid_path(path)
        tenant, client = self._principal(query)
        self.hot.write(vid, key, client, tenant)
        if _fault.ARMED:
            _fault.hit("volume.write", vid=vid, server=self.url())
        v = self.store.find_volume(vid)
        if v is None:
            raise rpc.RpcError(404, f"volume {vid} not on this server")
        fwd = self._forward_if_not_holder(path, query, body, "POST",
                                          vid)
        if fwd is not None:
            return fwd
        mime = query.get("mime", query.get("_content_type", ""))
        gzipped = "gzip" in query.get("_content_encoding", "")
        if mime == "image/jpeg" and not gzipped and \
                query.get("type") != "replicate":
            # EXIF auto-orientation on JPEG upload (needle.go:100-105);
            # replicas receive the already-fixed bytes.
            from ..images import fix_jpeg_orientation
            body = fix_jpeg_orientation(body)
        n = Needle(cookie=cookie, id=key, data=body)
        if gzipped:
            # Pre-compressed upload (needle_parse_upload.go): store the
            # gzip bytes as-is and remember it in the needle flags so
            # reads can negotiate.
            n.set_is_compressed()
        if "name" in query:
            n.set_name(query["name"].encode())
        if "mime" in query:
            n.set_mime(query["mime"].encode())
        if query.get("ttl"):
            # Stamp the assign-time ?ttl on the needle itself
            # (needle_parse_upload.go): expiry then survives a copy
            # into a volume whose superblock says something else.
            from ..core.ttl import TTL as _TTL
            try:
                n.set_ttl(_TTL.parse(query["ttl"]))
            except ValueError:
                pass
        n.set_last_modified(int(time.time()))
        # Rollback applies only to a BRAND-NEW needle: for an overwrite
        # of an existing fid, deleting would tombstone the prior
        # committed version everywhere — turning a failed update into
        # data loss.  (Lock-free peek, same as the read path's.)
        existed = v.nm.get(key) is not None
        # Like store_replicate.go:37-44: writes hit the OS page cache
        # only, unless the request opts into durability with
        # ?fsync=true (the flag is forwarded to replicas in _replicate
        # so every copy honors it).
        try:
            _offset, size = self.store.write_needle(
                vid, n, fsync=self.fsync_writes or
                query.get("fsync") == "true")
        except DiskFullError as e:
            # ENOSPC: the volume rolled the partial record back and
            # flipped readonly.  Flip the rest of the breached
            # location's volumes too (the reserve check sees free==0)
            # and heartbeat so the master re-steers immediately; the
            # client re-assigns on the 500.
            self.store.check_disk_reserve()
            try:
                self._send_heartbeat(full=True)
            except Exception:  # noqa: BLE001
                pass
            raise rpc.RpcError(500, str(e)) from None
        if query.get("type") != "replicate":
            try:
                self._replicate(path, query, body, "POST", vid=vid,
                                v=v, undo_new=not existed)
            except Exception:
                # All-or-fail means ALL-or-fail: a partial fan-out must
                # not leak the locally-committed copy (the client was
                # told the write failed and will re-assign; an orphan
                # here would survive as an unowned needle).  _replicate
                # already undid the siblings that succeeded.
                if not existed:
                    try:
                        self.store.delete_needle(vid, key)
                    except Exception:  # noqa: BLE001 — best effort
                        pass
                raise
        # Usage accounting: replica copies account on their own server
        # (the ?type=replicate leg lands here too), so the master's
        # rollup counts bytes the way the disks do — per copy.  An
        # overwrite keeps the object count; the superseded bytes are
        # reclaimed by the delete/vacuum decrement path.
        self.usage.add(tenant, v.collection, len(body),
                       nobjects=0 if existed else 1, vid=vid)
        self.usage.note_request(tenant, written_bytes=len(body))
        return {"size": len(body), "eTag": f"{n.checksum:08x}"}

    def _delete_needle(self, path: str, query: dict, body: bytes) -> dict:
        self._check_write_jwt(path, query)
        self._refuse_if_draining(query)
        vid, key, _cookie = self._parse_fid_path(path)
        tenant, client = self._principal(query)
        self.hot.write(vid, key, client, tenant)
        v = self.store.find_volume(vid)
        if v is None:
            raise rpc.RpcError(404, f"volume {vid} not on this server")
        fwd = self._forward_if_not_holder(path, query, b"", "DELETE",
                                          vid)
        if fwd is not None:
            return fwd
        freed = self.store.delete_needle(vid, key)
        if freed > 0:
            # Deletes decrement at tombstone time (not vacuum time):
            # quota headroom comes back the moment the user deletes,
            # even though the disk bytes wait for compaction.
            self.usage.remove(tenant, v.collection, freed, 1, vid=vid)
        self.usage.note_request(tenant)
        if query.get("type") != "replicate":
            self._replicate(path, query, b"", "DELETE")
        return {"size": freed}

    def _replicate(self, path: str, query: dict, body: bytes,
                   method: str, vid: int | None = None, v=None,
                   undo_new: bool = False) -> None:
        """Fan out to sibling replicas (all-or-fail, store_replicate.go).
        Callers that already resolved the fid/volume pass them in so the
        single-copy fast path costs no extra parse or lookup.
        undo_new=True (a POST of a needle that did not exist before)
        deletes the copies that DID land when the fan-out partially
        fails, so a failed write leaves zero orphans."""
        if vid is None:
            vid = self._parse_fid_path(path)[0]
            v = self.store.find_volume(vid)
        if v is not None and \
                v.super_block.replica_placement.copy_count() == 1:
            # Single-copy volumes have no siblings; skip the master
            # lookup entirely (store_replicate.go consults the volume's
            # own replica placement the same way) — this is one master
            # RPC saved per write on the hot path.
            return
        try:
            lookup = self._lookup_volume(vid)
        except Exception:  # noqa: BLE001 — master unreachable: the local
            return         # write stands; repair catches divergence later
        errors = []
        ok_urls = []
        threads = []
        me = self.url()
        # Preserve the original query (name/mime/...) so replica needle
        # bytes are identical to the primary's.  Reserved _keys carry
        # request headers, not client parameters — strip them.
        fwd = {k: v for k, v in query.items() if not k.startswith("_")}
        fwd["type"] = "replicate"
        qs = urllib.parse.urlencode(fwd)
        # A pre-compressed body must reach replicas with the same
        # Content-Encoding so their needle flags match the primary's.
        hdrs = {"Content-Encoding": "gzip"} \
            if "gzip" in query.get("_content_encoding", "") else None

        with trace_span("volume.replicate", vid=vid,
                        method=method) as rspan:
            # Sends run on fresh threads where the thread-local trace
            # context is empty: capture the fan-out span's context here
            # and pass it explicitly so each replica's server span
            # parents under it.
            tp = rspan.traceparent()
            # Replication fan-out is internal traffic: the sibling's
            # admission control routes it through the low-priority
            # lane so a replication surge can't starve its user reads.
            send_hdrs = dict(hdrs or {}, **rpc.PRIORITY_LOW,
                             **_flows.tag("replicate.fanout"))
            if tp:
                send_hdrs["traceparent"] = tp

            def send(url):
                # Fresh thread: no flow identity — bind so the
                # fan-out bytes attribute to this server.
                _flows.bind_thread(me, "volume")
                try:
                    if _fault.ARMED:
                        _fault.hit("volume.replicate", replica=url,
                                   vid=vid)
                    rpc.call(f"http://{url}{path}?{qs}", method, body,
                             headers=send_hdrs or None)
                    ok_urls.append(url)
                except Exception as e:  # noqa: BLE001
                    errors.append(f"{url}: {e}")

            for loc in lookup.get("locations", []):
                if loc["url"] == me:
                    continue
                th = threading.Thread(target=send, args=(loc["url"],))
                th.start()
                threads.append(th)
            for th in threads:
                th.join()
            rspan.set(replicas=len(threads), errors=len(errors))
            if errors:
                # A cached location just failed: evict so the next write
                # re-resolves immediately instead of failing for the TTL.
                self._vol_loc_cache.pop(vid, None)
                if method == "POST" and undo_new:
                    # The failed NEW write is being undone everywhere —
                    # siblings below, the local copy by the caller.
                    emit_event("replication.rollback", node=me,
                               severity="warn", vid=vid,
                               committed_siblings=len(ok_urls),
                               failed=len(errors))
                if method == "POST" and ok_urls and undo_new:
                    # Partial fan-out of a NEW needle: undo the sibling
                    # copies that DID land, so an all-or-fail failure
                    # leaves zero orphaned needles anywhere (the caller
                    # undoes the local copy).  Best effort — a sibling
                    # that just took the write is alive enough to take
                    # the delete.  Overwrites are never undone: a
                    # delete would tombstone the prior version.
                    for url in ok_urls:
                        try:
                            rpc.call(f"http://{url}{path}?{qs}",
                                     "DELETE",
                                     headers=_flows.tag(
                                         "replicate.fanout"))
                        except Exception:  # noqa: BLE001
                            pass
                raise rpc.RpcError(500, "replication failed: " +
                                   "; ".join(errors))

    # -- admin handlers ------------------------------------------------------

    def _admin_status(self, query: dict, body: bytes) -> dict:
        volumes = []
        for loc in self.store.locations:
            for v in loc.volumes.values():
                volumes.append({
                    "id": v.vid, "collection": v.collection,
                    "size": v.dat_size(), "file_count": v.file_count(),
                    "garbage_ratio": v.garbage_ratio(),
                    "read_only": v.readonly,
                })
        from ..stats.sysstats import proc_cpu_seconds
        return {"volumes": volumes,
                "ec_volumes": [
                    {"id": vid, "shards": sorted(ev.shards)}
                    for vid, ev in self.ec_volumes.items()],
                "cpu_seconds": proc_cpu_seconds(),
                "pid": os.getpid()}

    def _admin_assign_volume(self, query: dict, body: bytes) -> dict:
        req = json.loads(body)
        self.store.add_volume(
            req["volume"], req.get("collection", ""),
            req.get("replication", "000"), req.get("ttl", ""))
        self._send_heartbeat()
        return {}

    def _admin_delete_volume(self, query: dict, body: bytes) -> dict:
        req = json.loads(body)
        self.store.delete_volume(req["volume"])
        # Whole-volume teardown: subtract everything the volume still
        # held from the tenant ledger (the per-needle decrement path
        # never saw these).
        self.usage.drop_volume(req["volume"])
        self._send_heartbeat()
        return {}

    def _admin_leave(self, query: dict, body: bytes) -> dict:
        """VolumeServerLeave: stop heartbeating so the master's dead-node
        sweep drains this server (reads keep being served until the
        process actually stops)."""
        self._stop.set()
        return {"leaving": True}

    # -- graceful lifecycle ---------------------------------------------------

    def _admin_drain(self, query: dict, body: bytes) -> dict:
        """POST /admin/drain [{grace}]: enter draining mode and block
        until in-flight requests finish (or grace expires), then say
        goodbye to the master.  The route is admission-exempt, so the
        drain request itself never deadlocks the in-flight wait."""
        req = json.loads(body) if body else {}
        grace = float(req.get("grace", self.shutdown_grace))
        return self.drain(grace)

    def drain(self, grace: float | None = None) -> dict:
        """Graceful shutdown, phase one (SIGTERM / /admin/drain /
        cluster.drain): refuse new writes with 503 + Retry-After (the
        client's RetryPolicy fails over / re-assigns), finish in-flight
        requests up to `grace` seconds, then send a goodbye heartbeat
        so the master unregisters this node IMMEDIATELY — no heartbeat
        blackout, no dead-sweep window.  Reads keep being served until
        the process actually exits (stop())."""
        grace = self.shutdown_grace if grace is None else grace
        with self._drain_lock:
            if self.draining:
                return {"draining": True, "already": True}
            self.draining = True
        emit_event("node.draining", node=self.url(), severity="warn",
                   grace=grace)
        try:
            # Publish the draining flag right away: the master stops
            # assigning writes here while we wait out the in-flight.
            self._send_heartbeat(full=True)
        except Exception:  # noqa: BLE001 — master down: drain anyway
            pass
        adm = self.server.admission
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline:
            if adm.inflight_total() == 0:
                break
            time.sleep(0.02)
        # Stop the pulse loop BEFORE the goodbye so a periodic beat
        # can't race it and re-register this node post-goodbye (the
        # master also ignores stale beats from a goodbyed epoch).
        self._stop.set()
        self._send_goodbye()
        return {"draining": True,
                "inflight": adm.inflight_total()}

    def _send_goodbye(self) -> None:
        """Final heartbeat: the master unregisters this node now
        instead of waiting for the dead-node sweep to notice the
        heartbeat blackout."""
        hb = {"ip": self.server.host, "port": self.server.port,
              "goodbye": True, "seq_epoch": self._hb_epoch}
        try:
            rpc.call(f"{self.master_url}/heartbeat", "POST",
                     json.dumps(hb).encode(), timeout=5.0)
        except Exception:  # noqa: BLE001 — master down: its dead-node
            pass           # sweep remains the fallback

    def _admin_readonly(self, query: dict, body: bytes) -> dict:
        req = json.loads(body)
        self.store.mark_volume_readonly(req["volume"],
                                        req.get("readonly", True))
        emit_event("volume.readonly", node=self.url(),
                   vid=req["volume"],
                   readonly=req.get("readonly", True))
        self._send_heartbeat(full=True)
        return {}

    def _admin_configure_replication(self, query: dict,
                                     body: bytes) -> dict:
        """VolumeConfigure (volume_grpc_admin.go:104): rewrite the
        superblock's replica placement; the follow-up full heartbeat
        re-registers the volume under its new layout."""
        req = json.loads(body)
        try:
            self.store.configure_volume(req["volume"],
                                        req["replication"])
        except (VolumeError, ValueError) as e:
            raise rpc.RpcError(400, str(e)) from None
        self._send_heartbeat(full=True)
        return {}

    def _admin_vacuum(self, query: dict, body: bytes) -> dict:
        req = json.loads(body)
        v = self.store.find_volume(req["volume"])
        if v is None:
            raise rpc.RpcError(404, f"volume {req['volume']} not here")
        before = v.garbage_ratio()
        vacuum_volume(v)
        return {"garbage_ratio_before": before,
                "garbage_ratio_after": v.garbage_ratio()}

    # -- EC admin ------------------------------------------------------------

    _VOLUME_EXT = re.compile(r"\.(ec\d\d|ecx|ecj|vif|dat)$")

    def _volume_base(self, vid: int) -> str:
        v = self.store.find_volume(vid)
        if v is not None:
            return v.file_name()
        # Look for loose files (shards without a mounted volume),
        # accepting only well-formed volume extensions — a glob like
        # `1.ec*` also matches in-flight temp files (`1.ec01.part`),
        # and deriving the base from one corrupts every later write.
        for loc in self.store.locations:
            for name in (str(vid), f"*_{vid}"):
                import glob as _glob
                hits = _glob.glob(os.path.join(loc.directory,
                                               name + ".ec*")) + \
                    _glob.glob(os.path.join(loc.directory, name + ".ecx")) \
                    + _glob.glob(os.path.join(loc.directory, name + ".dat"))
                for hit in hits:
                    m = self._VOLUME_EXT.search(hit)
                    if m:
                        return hit[:m.start()]
        return os.path.join(self.store.locations[0].directory, str(vid))

    def _ec_total_shards(self, vid: int, base: str | None = None) -> int:
        """Shard-file count of an EC volume, codec-derived (mounted
        EcVolume first, then the on-disk .vif) — a mixed-codec cluster
        must not assume RS(10,4)'s 14 everywhere."""
        ev = self.ec_volumes.get(vid)
        if ev is not None:
            return ev.codec.total_shards
        from ..ec.volume_info import ec_codec_name
        try:
            return get_codec(
                ec_codec_name(base or self._volume_base(vid))).total_shards
        except ValueError:
            return TOTAL_SHARDS

    def _ec_generate(self, query: dict, body: bytes) -> dict:
        """VolumeEcShardsGenerate: .dat -> shard files + .ecx + .vif.
        The codec comes from the request ("codec": "lrc"), else the
        server's -ec.codec default; it is persisted in the .vif so
        every later mount/rebuild picks the matching matrices."""
        req = json.loads(body)
        vid = req["volume"]
        codec = get_codec(req.get("codec") or self.ec_codec)
        v = self.store.find_volume(vid)
        if v is None:
            raise rpc.RpcError(404, f"volume {vid} not here")
        v.set_readonly(True)
        emit_event("volume.readonly", node=self.url(), vid=vid,
                   readonly=True, reason="ec.generate")
        v.sync()
        base = v.file_name()
        dat_bytes = v.dat_size()
        emit_event("ec.encode.start", node=self.url(), vid=vid,
                   dat_bytes=dat_bytes, codec=codec.name)
        t0 = time.perf_counter()
        try:
            write_sorted_file_from_idx(base)
            write_ec_files(base, codec=codec.name)
        except Exception as e:
            emit_event("ec.encode.finish", node=self.url(),
                       severity="error", vid=vid,
                       seconds=round(time.perf_counter() - t0, 6),
                       error=f"{type(e).__name__}: {e}")
            raise
        from ..ec.volume_info import save_volume_info
        save_volume_info(base, v.version, codec=codec.name)
        emit_event("ec.encode.finish", node=self.url(), vid=vid,
                   seconds=round(time.perf_counter() - t0, 6),
                   dat_bytes=dat_bytes, shards=codec.total_shards,
                   codec=codec.name)
        return {"shards": list(range(codec.total_shards)),
                "codec": codec.name}

    def _ec_mount(self, query: dict, body: bytes) -> dict:
        req = json.loads(body)
        vid = req["volume"]
        base = self._volume_base(vid)
        ev = self.ec_volumes.get(vid)
        if ev is None:
            ev = EcVolume(base, vid=vid)
            self.ec_volumes[vid] = ev
        else:
            ev.load_local_shards()
        self._send_heartbeat()
        return {"shards": sorted(ev.shards)}

    def _ec_unmount(self, query: dict, body: bytes) -> dict:
        req = json.loads(body)
        ev = self.ec_volumes.pop(req["volume"], None)
        if ev is not None:
            ev.close()
        self._send_heartbeat()
        return {}

    def _ec_rebuild(self, query: dict, body: bytes) -> dict:
        req = json.loads(body)
        vid = req["volume"]
        base = self._volume_base(vid)
        emit_event("ec.rebuild.start", node=self.url(), vid=vid)
        t0 = time.perf_counter()
        try:
            generated = rebuild_ec_files(base)
        except Exception as e:
            emit_event("ec.rebuild.finish", node=self.url(),
                       severity="error", vid=vid,
                       seconds=round(time.perf_counter() - t0, 6),
                       error=f"{type(e).__name__}: {e}")
            raise
        emit_event("ec.rebuild.finish", node=self.url(), vid=vid,
                   seconds=round(time.perf_counter() - t0, 6),
                   rebuilt=generated)
        return {"rebuilt_shards": generated}

    def _ec_delete_shards(self, query: dict, body: bytes) -> dict:
        req = json.loads(body)
        vid, shard_ids = req["volume"], req["shards"]
        base = self._volume_base(vid)
        ev = self.ec_volumes.get(vid)
        from ..ec.integrity import ShardChecksums, ecc_lock
        with ecc_lock(base):
            ecc = ShardChecksums.load(base)
            for sid in shard_ids:
                ecc.drop_shard(sid)
            ecc.save()
        for sid in shard_ids:
            if ev is not None and sid in ev.shards:
                ev.shards.pop(sid).close()
            try:
                os.remove(base + to_ext(sid))
            except FileNotFoundError:
                pass
        # Last shard gone: unmount and drop the index sidecars too, else a
        # restart re-registers a phantom zero-shard EC volume from the
        # stale .ecx (VolumeEcShardsDelete does the same cleanup).
        if not any(os.path.exists(base + to_ext(s))
                   for s in range(self._ec_total_shards(vid, base))):
            ev = self.ec_volumes.pop(vid, None)
            if ev is not None:
                ev.close()
            for ext in (".ecx", ".ecj", ".vif", ".ecc"):
                try:
                    os.remove(base + ext)
                except FileNotFoundError:
                    pass
        self._send_heartbeat()
        return {}

    def _ec_shard_read(self, query: dict, body: bytes):
        """VolumeEcShardRead: raw bytes from one local shard."""
        vid = int(query["volume"])
        sid = int(query["shard"])
        offset = int(query.get("offset", 0))
        size = int(query.get("size", 0))
        ev = self.ec_volumes.get(vid)
        if ev is None or sid not in ev.shards:
            raise rpc.RpcError(404, f"shard {vid}.{sid} not here")
        return ev.shards[sid].read_at(offset, size)

    def _ec_shard_file(self, query: dict, body: bytes):
        """Stream a whole shard (or .ecx/.ecj) file — the CopyFile RPC."""
        vid = int(query["volume"])
        base = self._volume_base(vid)
        ext = query.get("ext") or to_ext(int(query["shard"]))
        if ext not in (".ecx", ".ecj", ".vif") and not ext.startswith(".ec"):
            raise rpc.RpcError(400, f"bad ext {ext}")
        path = base + ext
        if not os.path.exists(path):
            raise rpc.RpcError(404, f"{os.path.basename(path)} not here")
        return open(path, "rb")  # streamed by the server in 1MB chunks

    def _ec_copy_shard(self, query: dict, body: bytes) -> dict:
        """VolumeEcShardsCopy: pull shard files from a source server."""
        req = json.loads(body)
        vid = req["volume"]
        source = req["source"]  # host:port
        shard_ids = req["shards"]
        base = self._volume_base(vid)
        os.makedirs(os.path.dirname(base) or ".", exist_ok=True)
        from ..ec.integrity import ShardChecksums, ecc_lock
        for sid in shard_ids:
            rpc.call_to_file(f"http://{source}/admin/ec/shard_file?"
                             f"volume={vid}&shard={sid}",
                             base + to_ext(sid),
                             headers={**rpc.PRIORITY_LOW,
                                      **_flows.tag("ec.gather")})
        with ecc_lock(base):
            ecc = ShardChecksums.load(base)
            for sid in shard_ids:
                # The pull replaced the shard bytes: any recorded
                # checksum is stale — drop it so the next scrub
                # fingerprints the fresh copy (trust-on-first-scrub).
                ecc.drop_shard(sid)
            ecc.save()
        if req.get("copy_ecx", False):
            for ext in (".ecx", ".ecj", ".vif"):
                try:
                    rpc.call_to_file(
                        f"http://{source}/admin/ec/shard_file?"
                        f"volume={vid}&ext={ext}", base + ext,
                        headers=_flows.tag("ec.gather"))
                except rpc.RpcError:
                    try:
                        os.remove(base + ext)  # don't leave a 0-byte file
                    except FileNotFoundError:
                        pass
        return {}

    def _ec_receive_shard(self, query: dict, body: bytes) -> dict:
        """Push-mode shard install: the batched mesh rebuild
        (parallel/cluster_rebuild.py) decodes centrally and scatters
        rebuilt shards here — the inverse of copy_shard's pull.  Pulls
        the .ecx/.vif sidecars from ?ecx_source= when absent so the
        shard is servable once mounted."""
        vid = int(query["volume"])
        sid = int(query["shard"])
        base = self._volume_base(vid)
        if not 0 <= sid < self._ec_total_shards(vid, base):
            raise rpc.RpcError(400, f"bad shard id {sid}")
        os.makedirs(os.path.dirname(base) or ".", exist_ok=True)
        # Temp names must not collide with _volume_base's discovery
        # globs (`<vid>.ec*`) or concurrent receives would mis-derive
        # the base path from a half-written sibling.
        tmp = f"{base}.rcv{sid}.tmp"
        with open(tmp, "wb") as f:
            f.write(body)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, base + to_ext(sid))
        # Per-volume serialization: the shared .ecc sidecar update is
        # load-modify-save, and concurrent receives for the same
        # volume must not lose each other's entries (receives for
        # OTHER volumes shouldn't stall behind this).
        with self._ec_recv_lock:
            vlock = self._ec_recv_vlocks.setdefault(
                vid, threading.Lock())
        from ..ec.integrity import (BlockCrcAccumulator,
                                    ShardChecksums, ecc_lock)
        with self._ec_recv_lock:
            pend = self._ec_pending_ecc.get(vid, {}).pop(sid, None)
            if not self._ec_pending_ecc.get(vid):
                self._ec_pending_ecc.pop(vid, None)
        if pend is not None:
            shipped_at, crcs = pend
            pend = crcs if (time.monotonic() - shipped_at
                            < _PENDING_ECC_TTL) else None
        with vlock, ecc_lock(base):
            ecc = ShardChecksums.load(base)
            nblocks = -(-len(body) // ecc.block) if body else 0
            if pend is not None and len(pend) == nblocks:
                # The encoder shipped this shard's kernel-computed CRCs
                # for THIS push (receive_ecc) — strictly better than
                # fingerprinting the pushed body here: they describe
                # the INTENDED bytes, so even wire corruption on the
                # push itself is detectable by the first scrub.  Skip
                # the CPU pass over the payload.  (receive_ecc already
                # merged them into the sidecar; re-assert in case a
                # concurrent writer dropped them.)
                if ecc.get(sid) != pend:
                    ecc.set_shard(sid, pend)
                    ecc.save()
            else:
                # Fingerprint the pushed bytes so the scrub can verify
                # this shard from its first sweep (the body IS the
                # intended content; ec/integrity.py).  This also
                # OVERWRITES any stale sidecar entry a prior encode
                # generation left behind.
                acc = BlockCrcAccumulator(ecc.block)
                acc.feed(body)
                ecc.set_shard(sid, acc.finalize())
                ecc.save()
        source = query.get("ecx_source", "")
        if source:
            with vlock:
                if not os.path.exists(base + ".ecx"):
                    for ext in (".ecx", ".vif", ".ecj"):
                        try:
                            # Sidecars are best-effort: the shard itself
                            # is already durably installed, and a missing
                            # .vif/.ecj is normal.  call_to_file is
                            # atomic (tmp + rename), so failures leave
                            # nothing behind.
                            rpc.call_to_file(
                                f"http://{source}/admin/ec/shard_file?"
                                f"volume={vid}&ext={ext}", base + ext,
                                headers=_flows.tag("ec.gather"))
                        except (rpc.RpcError, OSError):
                            pass
        return {"volume": vid, "shard": sid, "bytes": len(body)}

    def _ec_receive_file(self, query: dict, body: bytes) -> dict:
        """Push-mode sidecar install (.ecx/.vif): the batched mesh
        encode (parallel/cluster_encode.py) builds the sorted index
        centrally and pushes it to every shard holder — for a fresh
        encode there is no existing holder a receive_shard ecx_source
        pull could reach."""
        vid = int(query["volume"])
        ext = query.get("ext", ".ecx")
        if ext not in (".ecx", ".vif"):
            raise rpc.RpcError(400, f"bad ext {ext}")
        base = self._volume_base(vid)
        os.makedirs(os.path.dirname(base) or ".", exist_ok=True)
        # Unique temp per request (cf. receive_shard's per-shard temp
        # names): concurrent .ecx/.vif pushes — or a push racing its
        # own retry — must never interleave in one staging file.
        tmp = (f"{base}.rcvx{ext.lstrip('.')}"
               f".{threading.get_ident()}.tmp")
        try:
            with open(tmp, "wb") as f:
                f.write(body)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, base + ext)
        finally:
            try:
                os.remove(tmp)
            except FileNotFoundError:
                pass
        return {"volume": vid, "ext": ext, "bytes": len(body)}

    def _ec_receive_ecc(self, query: dict, body: bytes) -> dict:
        """Merge kernel-computed `.ecc` entries pushed by the batched
        mesh encode/rebuild BEFORE the shards arrive: the CRCs come
        from the encode kernel's fused CRC32-C output (ops/crc_fold.py)
        — the *intended* bytes — so receive_shard can skip its CPU
        re-read of each pushed payload and divergence anywhere past the
        device (wire, disk) is detectable by the first scrub."""
        vid = int(query["volume"])
        try:
            doc = json.loads(body)
            block = int(doc.get("block", 0))
            raw = doc["shards"]
            if not isinstance(raw, dict):
                raise ValueError("shards must be an object")
            shards = {}
            for sid, crcs in raw.items():
                if not isinstance(crcs, list):
                    # A bare hex string would char-iterate into eight
                    # bogus one-digit CRCs — refuse, don't mangle.
                    raise ValueError(f"shard {sid}: crcs must be a list")
                vals = [int(c, 16) for c in crcs]
                if any(not 0 <= v <= 0xFFFFFFFF for v in vals):
                    # A >32-bit value can never equal a recomputed
                    # crc32c: merged into the sidecar it would make the
                    # first scrub quarantine a healthy shard.
                    raise ValueError(f"shard {sid}: crc out of range")
                shards[int(sid)] = vals
        except (ValueError, KeyError, TypeError, AttributeError) as e:
            raise rpc.RpcError(400, f"bad .ecc fragment: {e}")
        base = self._volume_base(vid)
        total = self._ec_total_shards(vid, base)
        bad = [sid for sid in shards if not 0 <= sid < total]
        if bad:
            raise rpc.RpcError(400, f"bad shard ids {bad}")
        from ..ec.integrity import ShardChecksums, ecc_lock
        os.makedirs(os.path.dirname(base) or ".", exist_ok=True)
        with ecc_lock(base):
            ecc = ShardChecksums.load(base)
            if block and ecc.shards and block != ecc.block:
                raise rpc.RpcError(
                    409, f"block {block} != existing {ecc.block}")
            if block and not ecc.shards:
                ecc.block = block
            for sid, crcs in shards.items():
                ecc.set_shard(sid, crcs)
            ecc.save()
        # Mark the entries claimable by this generation's receive_shard
        # (see _ec_pending_ecc) — a shard push with no pending entry
        # fingerprints its body instead of trusting the sidecar.  Prune
        # expired leftovers (failed pushes) while we hold the lock so
        # the map stays bounded.
        now = time.monotonic()
        with self._ec_recv_lock:
            for v in list(self._ec_pending_ecc):
                entries = self._ec_pending_ecc[v]
                for s in [s for s, (ts, _c) in entries.items()
                          if now - ts >= _PENDING_ECC_TTL]:
                    del entries[s]
                if not entries:
                    del self._ec_pending_ecc[v]
            self._ec_pending_ecc.setdefault(vid, {}).update(
                {sid: (now, crcs) for sid, crcs in shards.items()})
        return {"volume": vid, "shards": sorted(shards), "merged": True}

    def _ec_to_volume(self, query: dict, body: bytes) -> dict:
        """VolumeEcShardsToVolume: local data shards (.ec00-.ec09) + .ecx
        back into a normal .dat/.idx volume, then mount it
        (server/volume_grpc_erasure_coding.go:330)."""
        req = json.loads(body)
        vid = req["volume"]
        ev = self.ec_volumes.get(vid)
        base = (ev.base_file_name if ev is not None
                else self._volume_base(vid))
        missing = [s for s in range(10)
                   if not os.path.exists(base + to_ext(s))]
        if missing:
            raise rpc.RpcError(
                409, f"data shards {missing} not on this server; "
                     "copy them here first")
        from ..ec.decoder import (find_dat_file_size, write_dat_file,
                                  write_idx_file_from_ec_index)
        if ev is not None:
            self.ec_volumes.pop(vid).close()
        dat_size = find_dat_file_size(base)
        write_dat_file(base, dat_size)
        write_idx_file_from_ec_index(base)
        v = self.store.mount_volume(vid)
        self._send_heartbeat(full=True)
        return {"volume": vid, "size": v.dat_size()}

    def _volume_tail(self, query: dict, body: bytes):
        """VolumeTailSender (volume_server.proto, volume_backup.go): raw
        .dat bytes of records appended after ?since_ns=, capped at
        ?max_bytes=.  The X-Last-Append-Ns header carries the newest
        timestamp in the returned window for resuming."""
        from ..storage.volume_backup import (last_append_in_blob,
                                             read_incremental)
        vid = int(query["volume"])
        since = int(query.get("since_ns", 0))
        max_bytes = int(query.get("max_bytes", 64 * 1024 * 1024))
        v = self.store.find_volume(vid)
        if v is None:
            raise rpc.RpcError(404, f"volume {vid} not on this server")
        delta = read_incremental(v, since, max_bytes)
        last = last_append_in_blob(delta, v.version) if delta else since
        return (200, delta, {"Content-Type": "application/octet-stream",
                             "X-Volume-Version": str(v.version),
                             "X-Last-Append-Ns": str(last)})

    def _tier_upload(self, query: dict, body: bytes) -> dict:
        """VolumeTierMoveDatToRemote (volume_grpc_tier_upload.go): the
        volume must be readonly; its .dat moves to the backend spec."""
        from ..storage.tier import move_dat_to_remote
        req = json.loads(body)
        vid = int(req["volume"])
        v = self.store.find_volume(vid)
        if v is None:
            raise rpc.RpcError(404, f"volume {vid} not on this server")
        try:
            info = move_dat_to_remote(
                v, req["dest"], keep_local=req.get("keep_local", False),
                access_key=req.get("access_key", ""),
                secret_key=req.get("secret_key", ""))
        except VolumeError as e:
            raise rpc.RpcError(400, str(e)) from None
        return {"volume": vid, "remote": info["files"][0]}

    def _tier_download(self, query: dict, body: bytes) -> dict:
        """VolumeTierMoveDatFromRemote: bring the .dat back local."""
        from ..storage.tier import move_dat_from_remote
        req = json.loads(body)
        vid = int(req["volume"])
        v = self.store.find_volume(vid)
        if v is None:
            raise rpc.RpcError(404, f"volume {vid} not on this server")
        try:
            move_dat_from_remote(
                v, keep_remote=req.get("keep_remote", False),
                access_key=req.get("access_key", ""),
                secret_key=req.get("secret_key", ""))
        except VolumeError as e:
            raise rpc.RpcError(400, str(e)) from None
        return {"volume": vid, "local": True}

    def _query(self, query: dict, body: bytes):
        """The volume Query RPC (pb/volume_server.proto:92,
        server/volume_grpc_query.go): run a SELECT over one stored
        object's bytes.  Body: {fid, query, input_format, csv_header,
        csv_delimiter, output_format}."""
        from ..query import run_query
        from ..query.sql import SqlError
        req = json.loads(body)
        vid, key, cookie = t.parse_file_id(req["fid"])
        v = self.store.find_volume(vid)
        if v is None:
            raise rpc.RpcError(404, f"volume {vid} not on this server")
        try:
            n = self.store.read_needle(vid, key, cookie)
        except NotFoundError as e:
            raise rpc.RpcError(404, str(e)) from None
        try:
            out = run_query(
                n.data, req["query"],
                input_format=req.get("input_format", "json"),
                csv_header=req.get("csv_header", True),
                csv_delimiter=req.get("csv_delimiter", ","),
                output_format=req.get("output_format", "json"))
        except (SqlError, ValueError) as e:
            raise rpc.RpcError(400, str(e)) from None
        return (200, out, {"Content-Type": "application/octet-stream"})

    def _volume_file(self, query: dict, body: bytes):
        """Stream a whole .dat/.idx/.vif file — the VolumeCopy/CopyFile RPC
        for normal volumes (server/volume_grpc_copy.go)."""
        vid = int(query["volume"])
        ext = query.get("ext", ".dat")
        if ext not in (".dat", ".idx", ".vif"):
            raise rpc.RpcError(400, f"bad ext {ext}")
        v = self.store.find_volume(vid)
        base = v.file_name() if v is not None else self._volume_base(vid)
        if v is not None:
            v.sync()
        path = base + ext
        if not os.path.exists(path):
            raise rpc.RpcError(404, f"{os.path.basename(path)} not here")
        return open(path, "rb")  # streamed by the server in 1MB chunks

    def _copy_volume(self, query: dict, body: bytes) -> dict:
        """VolumeCopy: pull .idx then .dat from a source server, then
        mount.  The shell freezes the source first; .idx-before-.dat
        ordering additionally guarantees the copied index never references
        bytes beyond the copied data snapshot."""
        req = json.loads(body)
        vid, source = req["volume"], req["source"]
        if self.store.has_volume(vid):
            raise rpc.RpcError(409, f"volume {vid} already here")
        loc = self.store.free_location()
        if loc is None:
            raise rpc.RpcError(507, "no free disk location on this server")
        collection = req.get("collection", "")
        name = f"{collection}_{vid}" if collection else str(vid)
        base = os.path.join(loc.directory, name)
        # A volume copy restores replication — wire-accounted as
        # repair.fetch (healthy-copy bytes pulled to heal placement).
        for ext in (".idx", ".dat"):
            rpc.call_to_file(f"http://{source}/admin/volume_file?"
                             f"volume={vid}&ext={ext}", base + ext,
                             headers={**rpc.PRIORITY_LOW,
                                      **_flows.tag("repair.fetch")})
        v = self.store.mount_volume(vid)
        self._send_heartbeat()
        return {"volume": vid, "size": v.dat_size()}

    def _volume_checksums(self, query: dict, body: bytes) -> dict:
        """GET /admin/volume/checksums?volume=N — the fsck-style
        needle -> CRC map for one local volume (live needles only,
        CRC-verified while scanning).  The durability autopilot's
        receive path compares the source's map against the copied
        files before registering the new replica."""
        vid = int(query["volume"])
        v = self.store.find_volume(vid)
        if v is None:
            raise rpc.RpcError(404, f"volume {vid} not here")
        v.sync()
        base = v.file_name()
        return {"volume": vid,
                "checksums": _needle_checksum_map(base + ".dat",
                                                  base + ".idx")}

    def _volume_receive(self, query: dict, body: bytes) -> dict:
        """POST /admin/volume/receive — crash-safe, verified volume
        copy for automatic re-replication.  Like /admin/copy_volume
        but: files land as .part tmps and are os.replace()d only after
        the rebuilt needle->CRC map matches the source's fsck map
        byte-for-byte, so an executor dying mid-copy leaves only tmp
        files the startup reaper removes, and a corrupt wire transfer
        can never register as a replica."""
        req = json.loads(body)
        vid, source = req["volume"], req["source"]
        if self.store.has_volume(vid):
            raise rpc.RpcError(409, f"volume {vid} already here")
        loc = self.store.free_location()
        if loc is None:
            raise rpc.RpcError(507, "no free disk location on this server")
        collection = req.get("collection", "")
        name = f"{collection}_{vid}" if collection else str(vid)
        base = os.path.join(loc.directory, name)
        tmps = {ext: base + ext + ".part" for ext in (".idx", ".dat")}
        try:
            # .idx before .dat: the copied index never references
            # bytes beyond the copied data snapshot.  Repair traffic
            # rides the low-priority lane, wire-accounted repair.fetch.
            for ext in (".idx", ".dat"):
                rpc.call_to_file(f"http://{source}/admin/volume_file?"
                                 f"volume={vid}&ext={ext}", tmps[ext],
                                 headers={**rpc.PRIORITY_LOW,
                                          **_flows.tag("repair.fetch")})
            want = rpc.call(
                f"http://{source}/admin/volume/checksums?volume={vid}",
                timeout=120.0)["checksums"]
            got = _needle_checksum_map(tmps[".dat"], tmps[".idx"])
            if got != want:
                raise rpc.RpcError(
                    422, f"volume {vid}: copied needle checksums "
                    f"diverge from source ({len(got)} local vs "
                    f"{len(want)} source live needles)")
        except Exception:
            for tmp in tmps.values():
                try:
                    os.remove(tmp)
                except OSError:
                    pass
            raise
        for ext in (".idx", ".dat"):
            os.replace(tmps[ext], base + ext)
        v = self.store.mount_volume(vid)
        self._send_heartbeat()
        return {"volume": vid, "size": v.dat_size(),
                "needles": len(want)}

    def _admin_mount(self, query: dict, body: bytes) -> dict:
        req = json.loads(body)
        self.store.mount_volume(req["volume"])
        self._send_heartbeat()
        return {}

    def _admin_unmount(self, query: dict, body: bytes) -> dict:
        req = json.loads(body)
        self.store.unmount_volume(req["volume"])
        self._send_heartbeat(full=True)
        return {}

    def _reap_partial_files(self) -> None:
        """Crash-safety sweep at startup: remove interrupted transfer
        tmps (.part from /admin/volume/receive, .dl.tmp from streaming
        downloads).  A repair executor dying mid-copy leaves ONLY
        these — never a half-registered volume — so reaping them is
        the whole recovery story on the receiver side."""
        import glob as _glob
        for loc in self.store.locations:
            for pat in ("*.part", "*.dl.tmp"):
                for path in _glob.glob(os.path.join(loc.directory,
                                                    pat)):
                    try:
                        os.remove(path)
                    except OSError:
                        pass

    def _load_ec_volumes(self) -> None:
        """Discover local EC shards at startup (disk_location_ec.go)."""
        import glob as _glob
        import re
        for loc in self.store.locations:
            for path in _glob.glob(os.path.join(loc.directory, "*.ecx")):
                name = os.path.basename(path)[:-4]
                m = re.match(r"^(?:.+_)?(\d+)$", name)
                if not m:
                    continue
                vid = int(m.group(1))
                if vid not in self.ec_volumes:
                    base = path[:-4]
                    try:
                        self.ec_volumes[vid] = EcVolume(base, vid=vid)
                    except Exception:  # noqa: BLE001 — incomplete shard set
                        continue


def _needle_checksum_map(dat_path: str, idx_path: str) -> dict:
    """fsck-style content map for one volume file pair: live needle id
    (hex) -> stored CRC (hex, CRC-verified against the data while
    scanning).  Keyed by needle and node-address-free, so two holders
    of the same volume converged exactly when their maps are equal —
    the registration gate for /admin/volume/receive."""
    from ..storage.needle_map import MemoryNeedleMap
    from ..storage.volume_scanner import scan_volume_file
    live = MemoryNeedleMap.load(idx_path)
    out: dict[str, str] = {}
    for needle, _offset, _total in scan_volume_file(dat_path,
                                                    check_crc=True):
        key = f"{needle.id:x}"
        if needle.size == 0:  # tombstone: the needle is deleted
            out.pop(key, None)
        elif needle.id in live:
            out[key] = f"{needle.checksum & 0xFFFFFFFF:08x}"
    return out
