"""Master server: topology keeper, id assigner, growth/vacuum orchestrator.

HTTP surface mirrors the reference master's API
(weed/server/master_server.go, master_grpc_server_volume.go):

  POST /heartbeat            volume-server full/delta state (SendHeartbeat)
  GET  /dir/assign           Assign: grow-on-demand then PickForWrite
  GET  /dir/lookup?volumeId= locations for a volume (or EC shards)
  GET  /dir/status           topology snapshot
  POST /vol/grow             explicit growth
  POST /vol/vacuum           force a vacuum scan
  GET  /col/list, POST /col/delete
  GET  /cluster/status
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse

from ..core.replica_placement import ReplicaPlacement
from ..core.ttl import TTL
from ..storage.store import VolumeInfo
from ..stats import flows as _flows
from ..topology.topology import Topology, VolumeGrowOption
from ..topology.volume_growth import VolumeGrowth
from . import rpc


def _vinfo_from_dict(d: dict) -> VolumeInfo:
    return VolumeInfo(
        id=d["id"], collection=d.get("collection", ""),
        size=d.get("size", 0), file_count=d.get("file_count", 0),
        delete_count=d.get("delete_count", 0),
        deleted_byte_count=d.get("deleted_byte_count", 0),
        read_only=d.get("read_only", False),
        replica_placement=d.get("replica_placement", 0),
        ttl=d.get("ttl", 0), compact_revision=d.get("compact_revision", 0),
        max_file_key=d.get("max_file_key", 0),
        version=d.get("version", 3),
        corrupt_count=d.get("corrupt_count", 0),
        modified_at=d.get("modified_at", 0),
        tiered=d.get("tiered", False))


def vinfo_to_dict(v: VolumeInfo) -> dict:
    return {
        "id": v.id, "collection": v.collection, "size": v.size,
        "file_count": v.file_count, "delete_count": v.delete_count,
        "deleted_byte_count": v.deleted_byte_count,
        "read_only": v.read_only,
        "replica_placement": v.replica_placement, "ttl": v.ttl,
        "compact_revision": v.compact_revision,
        "max_file_key": v.max_file_key, "version": v.version,
        "corrupt_count": v.corrupt_count,
        "modified_at": v.modified_at, "tiered": v.tiered,
    }


class MasterServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 volume_size_limit_mb: int = 30 * 1024,
                 default_replication: str = "000",
                 pulse_seconds: int = 5,
                 garbage_threshold: float = 0.3,
                 meta_dir: str | None = None,
                 peers: list[str] | None = None,
                 jwt_signing_key: str = "",
                 jwt_expires_seconds: int = 10,
                 ssl_context=None,
                 admin_scripts: str = "",
                 admin_script_interval: float = 17 * 60,
                 max_concurrent: int = 0,
                 idle_timeout: float = 120.0,
                 transport: str | None = None,
                 slo_read_p99: float | None = None,
                 slo_availability: float | None = None,
                 replication_lag_slo: float | None = None,
                 lifecycle_rules: str = "",
                 lifecycle_interval: float = 60.0,
                 lifecycle_mbps: float = 32.0,
                 tenant_rules: str = "",
                 geo_cluster_id: str = "",
                 geo_vid_stride: int = 1,
                 geo_vid_offset: int = 0,
                 steer_peer: str | None = None,
                 steer_reads: bool = False,
                 steer_refresh: float = 2.0,
                 filer_shards: int = 0,
                 repair_enabled: bool = False,
                 repair_delay: float | None = None,
                 repair_concurrent: int = 2):
        # Write-path JWT (security/jwt.go): when configured, Assign
        # responses carry an `auth` token volume servers require on
        # needle writes/deletes.
        self.jwt_signing_key = jwt_signing_key
        self.jwt_expires_seconds = jwt_expires_seconds
        # Admin-script cron (master_server.go:187-263 startAdminScripts):
        # master.toml maintenance scripts — one shell command per line —
        # run on the leader every interval, wrapped in lock/unlock, so
        # the EC lifecycle (ec.encode/rebuild/balance, volume.balance)
        # runs unattended.
        self.admin_scripts = [ln.strip() for ln in admin_scripts.split("\n")
                              if ln.strip()]
        self.admin_script_interval = admin_script_interval
        # (started_at, line, ok, output-or-error) — observability for
        # tests and the status endpoint.
        self.admin_script_runs: list[tuple[float, str, bool, str]] = []
        # Location push channels (/cluster/watch): the KeepConnected
        # analog (pb/master.proto:10-13, master_grpc_server.go:178) —
        # long-lived streams that receive volume-location changes the
        # moment heartbeats land, so clients invalidate their vid maps
        # without polling.
        self._watchers: list = []
        self._watchers_lock = threading.Lock()
        # Filer metadata-HA plane (-filer.shards=N; 0 keeps it off):
        # filers register + heartbeat like volume servers, and the
        # master owns the shard map — which filer is primary for each
        # namespace shard, at which fencing epoch, with which
        # followers.  Persisted so a master restart cannot regress an
        # epoch (that would un-fence a deposed primary).
        self.filer_shards = int(filer_shards)
        self._filers: dict[str, dict] = {}   # url -> row
        self._filer_lock = threading.RLock()
        self._shard_map: dict[int, dict] = {}
        self._shard_map_version = 0
        self._shard_map_path = f"{meta_dir}/filer_shards.json" \
            if meta_dir else None
        self._load_shard_map()
        if meta_dir:
            import os
            os.makedirs(meta_dir, exist_ok=True)
        seq_path = f"{meta_dir}/seq.dat" if meta_dir else None
        from ..topology.sequence import MemorySequencer
        # Active/active regions must mint volume ids from disjoint
        # residue classes (-geo.vid.stride / -geo.vid.offset): a vid
        # collision would make the regions' lease planes fence each
        # other's unrelated volumes.
        self.topo = Topology(
            volume_size_limit=volume_size_limit_mb * 1024 * 1024,
            sequencer=MemorySequencer(seq_path),
            pulse_seconds=pulse_seconds,
            vid_stride=geo_vid_stride, vid_offset=geo_vid_offset)
        self.vg = VolumeGrowth()
        self.default_replication = default_replication
        self.garbage_threshold = garbage_threshold
        # Cross-cluster mirroring lag SLO (-replicate.lag.slo,
        # seconds): healthz degrades (503) while any mirrored volume's
        # oldest unacked change-log record is older than this, and
        # recovers when the standby catches up.
        self.replication_lag_slo = replication_lag_slo
        # Tenancy & QoS plane (-tenant.rules): declarative per-tenant
        # quotas.  Stored-usage rules (max_bytes/max_objects) are
        # enforced HERE at assign time against the heartbeat-fed
        # rollup; rate rules feed this master's own admission buckets.
        # The rollup snapshots to <meta_dir>/tenants.json so a restart
        # answers quota checks before heartbeats repopulate it.
        from ..tenancy import QuotaPolicy, UsageRollup
        from ..tenancy import load_rules as load_tenant_rules
        self.tenant_policy = load_tenant_rules(tenant_rules) \
            if tenant_rules else QuotaPolicy()
        self.usage_rollup = UsageRollup(
            f"{meta_dir}/tenants.json" if meta_dir else None)
        self._last_quota_emit: dict[str, float] = {}
        # Overload protection (-max.concurrent): bounded assignment/
        # lookup concurrency with 429 sheds; /heartbeat, healthz, and
        # the watch streams are admission-exempt.
        self.server = rpc.JsonHttpServer(
            host, port, ssl_context=ssl_context,
            idle_timeout=idle_timeout, transport=transport,
            admission=rpc.AdmissionControl(
                max_concurrent,
                tenant_policy=self.tenant_policy
                if self.tenant_policy.rules else None))
        s = self.server
        s.route("POST", "/heartbeat", self._heartbeat)
        s.route("GET", "/dir/assign", self._assign)
        s.route("POST", "/dir/assign", self._assign)
        s.route("GET", "/dir/lookup", self._lookup)
        s.route("GET", "/dir/status", self._status)
        s.route("GET", "/cluster/watch", self._cluster_watch)
        s.route("POST", "/cluster/raft/add",
                lambda q, b: self._raft_membership(
                    dict(q, _action="add"), b))
        s.route("POST", "/cluster/raft/remove",
                lambda q, b: self._raft_membership(
                    dict(q, _action="remove"), b))
        s.route("GET", "/ui", self._ui)
        from ..utils.pprof import enable_pprof_routes
        enable_pprof_routes(s)
        from ..trace import setup_server_tracing
        setup_server_tracing(s, "master")
        from ..fault.routes import setup_fault_routes
        setup_fault_routes(s)
        from ..events import events_enabled, setup_event_routes
        setup_event_routes(s)
        s.route("GET", "/cluster/healthz", self._healthz)
        s.route("GET", "/cluster/mirror", self._cluster_mirror)
        if events_enabled():
            # The aggregation endpoint honors the same kill switch as
            # /debug/events — -events=false unmounts both surfaces.
            s.route("GET", "/cluster/events", self._cluster_events)
        s.route("POST", "/vol/grow", self._grow)
        s.route("POST", "/vol/vacuum", self._vacuum)
        s.route("GET", "/col/list", self._col_list)
        s.route("POST", "/col/delete", self._col_delete)
        s.route("GET", "/cluster/status", self._cluster_status)
        s.route("GET", "/vol/list", self._vol_list)
        s.route("POST", "/admin/lease", self._admin_lease)
        s.route("POST", "/admin/release", self._admin_release)
        s.route("GET", "/cluster/lifecycle", self._cluster_lifecycle)
        s.route("POST", "/cluster/lifecycle/run",
                self._cluster_lifecycle_run)
        s.route("GET", "/cluster/tenants", self._cluster_tenants)
        s.route("GET", "/cluster/flows", self._cluster_flows)
        s.route("GET", "/cluster/device", self._cluster_device)
        s.route("POST", "/filer/heartbeat", self._filer_heartbeat)
        s.route("GET", "/cluster/filer/shards",
                self._cluster_filer_shards)
        s.route("POST", "/cluster/filer/shards/move",
                self._filer_shard_move)
        s.route("GET", "/cluster/repair", self._cluster_repair)
        s.route("POST", "/cluster/repair/run", self._cluster_repair_run)
        s.route("POST", "/cluster/repair/pause",
                lambda q, b: self._cluster_repair_switch(q, b, True))
        s.route("POST", "/cluster/repair/resume",
                lambda q, b: self._cluster_repair_switch(q, b, False))
        reg = s.enable_metrics("master")
        # Device roofline instruments (process-global singletons): the
        # master runs no EC kernels itself in the deployed topology,
        # but in-process multi-role stacks do, and register_once keeps
        # the scrape single-family either way.
        from ..stats import roofline as _roofline
        for m in (_roofline.kernel_seconds_total,
                  _roofline.kernel_bytes_total,
                  _roofline.kernel_work_total,
                  _roofline.device_occupancy):
            reg.register_once(m)
        # SLO plane: declared objectives drive the burn engine behind
        # /cluster/healthz; /debug/slow + /debug/slo expose exemplars
        # and live quantiles like on the other roles.
        from ..stats.slo import setup_slo_routes
        setup_slo_routes(s)
        # Lock-contention surface: /debug/locks (holders/waiters with
        # stacks + per-lock wait/hold counters).
        from ..stats.contention import setup_contention_routes
        setup_contention_routes(s)
        s.slo.set_objectives(slo_read_p99, slo_availability)
        reg.gauge("SeaweedFS_master_volume_count",
                  "registered volume replicas cluster-wide",
                  callback=lambda: float(self.topo.volume_count))
        reg.gauge("SeaweedFS_master_ec_shard_count",
                  "registered EC shards cluster-wide",
                  callback=lambda: float(self.topo.ec_shard_count))
        reg.gauge("SeaweedFS_master_data_node_count",
                  "live data nodes",
                  callback=lambda: float(len(list(self.topo.leaves()))))
        reg.gauge("SeaweedFS_master_max_volume_id",
                  "volume id high-water mark",
                  callback=lambda: float(self.topo.max_volume_id))
        reg.gauge("SeaweedFS_master_is_leader", "1 on the raft leader",
                  callback=lambda: 1.0 if self.is_leader() else 0.0)
        reg.gauge("SeaweedFS_node_health",
                  "per data node: 1 = heartbeat fresh, 0 = stale",
                  ("node",), callback=self._node_health_values)
        # Durability autopilot instruments (process-global singletons
        # in repair_daemon; register_once keeps multi-master-in-process
        # scrapes single-family).
        from . import repair_daemon as _repair_mod
        reg.register_once(_repair_mod.repairs_total)
        reg.register_once(_repair_mod.repair_seconds)
        reg.gauge("SeaweedFS_repair_queue_depth",
                  "queued automatic repairs by surviving-redundancy "
                  "risk (0 = last replica / decode minimum)",
                  ("risk",),
                  callback=lambda: self.repair.queue_depth_by_risk())
        reg.gauge("SeaweedFS_master_tenant_bytes",
                  "cluster-wide stored bytes by tenant (heartbeat "
                  "rollup, replicas counted per copy)", ("tenant",),
                  callback=lambda: {
                      (t,): float(e["bytes"]) for t, e in
                      self.usage_rollup.totals().items()})
        reg.gauge("SeaweedFS_master_tenant_objects",
                  "cluster-wide stored objects by tenant", ("tenant",),
                  callback=lambda: {
                      (t,): float(e["objects"]) for t, e in
                      self.usage_rollup.totals().items()})
        # Geo locality steering (-replicate.steer): when this region's
        # replica of a mirrored volume is lagging past the lag SLO (or
        # a tenant's home= hint points at the peer region), /dir/lookup
        # reorders its locations list so clients read from the peer
        # cluster's replica first.  Lookup-time only — clients already
        # re-lookup on 429/503, so no read path changes are needed.
        self.geo_cluster_id = geo_cluster_id
        self.steer_peer = steer_peer
        self.steer_reads = steer_reads and bool(steer_peer)
        self.steer_refresh = steer_refresh
        self._steer_lock = threading.Lock()
        self._steer_mirror: tuple[float, dict] = (0.0, {})
        self._steer_locs: dict[int, tuple[float, list]] = {}
        self._grow_lock = threading.Lock()
        self._hb_apply_lock = threading.Lock()  # guards the lock table
        self._hb_node_locks: dict[str, threading.Lock] = {}
        # Nodes currently registered via heartbeat: a key leaving this
        # set (dead-node sweep) emits heartbeat.lost, re-entering emits
        # heartbeat.recovered — the journal's liveness timeline.
        self._hb_known: set[str] = set()
        # node_key -> seq_epoch of the process that said goodbye:
        # straggler heartbeats from that generation are ignored so a
        # drained server can't be resurrected by an in-flight beat
        # racing its own goodbye (a restarted process has a new epoch).
        self._goodbye_epochs: dict[str, int] = {}
        # Exclusive admin lock (wdclient/exclusive_locks): one shell at a
        # time may run mutating maintenance commands.
        self._admin_lock = threading.Lock()
        self._admin_token: int | None = None
        self._admin_holder = ""
        self._admin_expires = 0.0
        self._admin_lock_ttl = 10.0
        self._stop = threading.Event()
        self._sweeper = threading.Thread(target=self._sweep_loop,
                                         daemon=True, name="master-sweep")
        # Data-lifecycle plane (-lifecycle.rules): the policy daemon
        # scans heartbeat stats + /debug/hot coldness and drives
        # tiering/expiry.  Always constructed (healthz and the shell
        # verb report a disabled plane); the loop only starts with
        # rules loaded.
        from ..lifecycle import LifecycleDaemon, Policy, load_rules
        policy = load_rules(lifecycle_rules) if lifecycle_rules \
            else Policy([])
        self.lifecycle = LifecycleDaemon(self, policy,
                                         interval=lifecycle_interval,
                                         mbps=lifecycle_mbps)
        # Durability autopilot (-repair): leader-only daemon that
        # converges the cluster back to declared redundancy after node
        # loss.  Always constructed (the /cluster/repair surfaces and
        # the shell's run-once path report/work on a disarmed plane);
        # only an armed daemon enqueues from the sweep tick.
        from .repair_daemon import RepairDaemon
        self.repair = RepairDaemon(self, enabled=repair_enabled,
                                   delay=repair_delay,
                                   concurrent=repair_concurrent)
        # Multi-master HA: a raft node rides on this HTTP server; the
        # leader owns id issuance, followers proxy mutating requests
        # (server/raft_server.go, master_server.go:155).
        self.raft = None
        self._seq_ceiling = 0  # raft-committed file-id ceiling
        self._raft_id = f"http://{self.server.host}:{self.server.port}"
        self._id_lock = threading.Lock()
        if peers:
            from .raft import RaftNode
            norm = [p if p.startswith("http") else f"http://{p}"
                    for p in peers]
            # Raft identities are scheme-normalized http:// addresses
            # regardless of TLS: -peers lists are written as host:port,
            # and whether the wire is encrypted is the transport's
            # decision (rpc.set_client_ssl_context force_https), not
            # part of a node's identity.
            me = self._raft_id
            if me not in norm:
                # A textual alias of this node left in the peer list
                # would grant phantom self-votes (split brain) and
                # self-deposing heartbeats — refuse instead of guessing.
                raise ValueError(
                    f"-peers must include this master's advertised "
                    f"address {me} (got {norm}); set -ip/-port to match")
            self.raft = RaftNode(
                me, norm, apply_fn=self._raft_apply,
                snapshot_fn=self._raft_snapshot,
                restore_fn=self._raft_restore,
                state_path=f"{meta_dir}/raft.json" if meta_dir else None)
            self.raft.mount(self.server)
            self.topo.next_volume_id_hook = self._next_volume_id_raft
            # HA file-id issuance: swap in the consensus-backed block
            # sequencer (the etcd-sequencer analog) so a failover can
            # never re-issue a committed id range.
            from ..topology.sequence import RaftSequencer
            self.topo.sequencer = RaftSequencer(self._alloc_seq_block)

    # -- raft ----------------------------------------------------------------

    def _raft_apply(self, cmd: dict) -> None:
        if cmd.get("op") == "max_volume_id":
            self.topo.set_max_volume_id(cmd["value"])
        elif cmd.get("op") == "seq_ceiling":
            self._seq_ceiling = max(self._seq_ceiling, cmd["value"])

    def _alloc_seq_block(self, min_start: int, n: int) -> int:
        """Commit a file-id block [start, start+n) through the raft log
        (RaftSequencer's alloc_fn).  Same fencing discipline as volume
        ids: barrier first so a fresh leader sees every inherited
        ceiling before computing the next one."""
        from .raft import NotLeader
        with self._id_lock:
            if not self.raft.is_leader():
                raise NotLeader(self.raft.leader())
            self.raft.barrier()
            start = max(self._seq_ceiling, min_start)
            self.raft.propose({"op": "seq_ceiling", "value": start + n})
            return start

    def _raft_snapshot(self) -> dict:
        """State-machine snapshot for raft log compaction: the
        replicated state is the two id watermarks."""
        with self.topo._lock:
            return {"max_volume_id": max(self.topo._max_volume_id,
                                         self.topo.max_volume_id),
                    "seq_ceiling": self._seq_ceiling}

    def _raft_restore(self, state: dict) -> None:
        if state.get("max_volume_id"):
            self.topo.set_max_volume_id(state["max_volume_id"])
        if state.get("seq_ceiling"):
            self._seq_ceiling = max(self._seq_ceiling,
                                    state["seq_ceiling"])

    def _raft_membership(self, query: dict, body: bytes) -> dict:
        """POST /cluster/raft/{add,remove}?peer=host:port — one-server-
        at-a-time membership change on the leader."""
        if self.raft is None:
            raise rpc.RpcError(400, "raft is not enabled (-peers)")
        peer = query.get("peer", "")
        if not peer:
            raise rpc.RpcError(400, "missing ?peer=host:port")
        if not peer.startswith("http"):
            peer = f"http://{peer}"
        from .raft import NotLeader
        try:
            if query.get("_action") == "remove":
                self.raft.remove_server(peer)
            else:
                self.raft.add_server(peer)
        except NotLeader as e:
            raise rpc.RpcError(
                503, f"not the leader (leader={e.leader})") from None
        except (RuntimeError, ValueError) as e:
            raise rpc.RpcError(409, str(e)) from None
        return {"peers": sorted(self.raft.peers + [self.raft.id])}

    def _next_volume_id_raft(self) -> int:
        from .raft import NotLeader
        with self._id_lock:
            if not self.raft.is_leader():
                raise NotLeader(self.raft.leader())
            # Read-your-own-log fence: a freshly elected leader must
            # apply inherited entries before computing the next id, or
            # it could re-issue the previous leader's last volume id.
            self.raft.barrier()
            with self.topo._lock:
                target = self.topo.stride_align(
                    max(self.topo._max_volume_id,
                        self.topo.max_volume_id) + 1)
            self.raft.propose({"op": "max_volume_id", "value": target})
            return target

    def is_leader(self) -> bool:
        return self.raft is None or self.raft.is_leader()

    def leader_url(self) -> str:
        if self.raft is None or self.raft.is_leader():
            return self.url()
        return self.raft.leader() or self.url()

    def _proxy_to_leader(self, path: str, query: dict, body: bytes,
                         method: str = "POST"):
        """Forward a mutating request to the current leader
        (master_server.go proxyToLeader)."""
        leader = self.raft.leader() if self.raft else None
        # Compare against the scheme-normalized raft identity, not
        # self.url(): under TLS url() is https:// while raft ids stay
        # http://, and a stale self-leader hint must 503 here instead
        # of proxying the request to ourselves.
        if not leader or leader == self._raft_id:
            raise rpc.RpcError(503, "no leader elected yet; retry")
        if query.get("proxied"):
            # Stale mutual leader hints during an election would bounce
            # the request in a cycle of nested blocking calls.
            raise rpc.RpcError(503, "no stable leader yet; retry")
        import urllib.parse
        fwd = {k: v for k, v in query.items() if not k.startswith("_")}
        fwd["proxied"] = "1"
        qs = urllib.parse.urlencode(fwd)
        url = leader + path + (f"?{qs}" if qs else "")
        try:
            return rpc.call(url, method,
                            body if method != "GET" else None)
        except OSError as e:
            # A dead/unreachable leader hint (it was just killed; the
            # election hasn't converged) is a RETRY-ELSEWHERE answer,
            # not an internal error of THIS follower: surfacing it as a
            # 500 would count toward this live follower's circuit
            # breaker and let a failover window open breakers on every
            # healthy master (clients hammer all seeds during one).
            raise rpc.RpcError(
                503, f"leader {leader} unreachable; retry: "
                     f"{type(e).__name__}: {e}") from None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self.server.start()
        self._sweeper.start()
        if self.raft is not None:
            self.raft.start()
        if self.admin_scripts:
            threading.Thread(target=self._admin_script_loop,
                             daemon=True, name="master-cron").start()
        if self.lifecycle.policy.rules:
            self.lifecycle.start()

    def stop(self) -> None:
        self._stop.set()
        self.lifecycle.stop()
        # Final usage snapshot: quota checks after a restart answer
        # from this until heartbeats repopulate the rollup.
        try:
            self.usage_rollup.save(force=True)
        except OSError:
            pass
        if self.raft is not None:
            self.raft.stop()
        self.server.stop()

    # -- admin-script cron (startAdminScripts) -------------------------------

    def _admin_script_loop(self) -> None:
        while not self._stop.wait(self.admin_script_interval):
            if not self.is_leader():
                continue
            try:
                self.run_admin_scripts()
            except Exception:  # noqa: BLE001 — cron must never die
                pass

    def run_admin_scripts(self) -> list[tuple[float, str, bool, str]]:
        """One cron round: lock, run every configured script line
        through the shell dispatcher, unlock.  Returns this round's
        (ts, line, ok, output) records (also appended to
        admin_script_runs)."""
        from ..shell import CommandEnv, run_command
        from ..utils import glog
        env = CommandEnv(self.url())
        round_runs: list[tuple[float, str, bool, str]] = []
        try:
            lines = list(self.admin_scripts)
            if not any(ln == "lock" for ln in lines):
                lines = ["lock"] + lines + ["unlock"]
            for line in lines:
                ts = time.time()
                try:
                    out = run_command(env, line)
                    round_runs.append((ts, line, True, out))
                except Exception as e:  # noqa: BLE001 — next script
                    glog.warningf("admin script %r: %s", line, e)
                    round_runs.append((ts, line, False, str(e)))
                    if line == "lock":
                        # No exclusive lease (an operator holds it):
                        # running maintenance concurrently with their
                        # session is the exact race the lock prevents.
                        # Abort the round; next tick retries.
                        break
        finally:
            env.close()
            self.admin_script_runs.extend(round_runs)
            del self.admin_script_runs[:-200]
        return round_runs

    def url(self) -> str:
        return self.server.url()

    # -- handlers -----------------------------------------------------------

    def _heartbeat(self, query: dict, body: bytes) -> dict:
        if not self.is_leader():
            # Volume servers register with the leader only; hand back the
            # hint so they redial (volume_grpc_client_to_master.go:60-85).
            # No self-referential fallback: an unknown leader stays None
            # so the volume server rotates seeds instead of spinning here.
            return {"leader": self.raft.leader(), "is_leader": False}
        hb = json.loads(body)
        # Per-node serialization + ordering: concurrent POSTs from one
        # volume server must not let a stale full snapshot erase a
        # just-grown volume, but nodes must not serialize each other.
        node_key = f"{hb['ip']}:{hb['port']}"
        if hb.get("goodbye"):
            # Graceful drain, final beat: unregister NOW — no
            # heartbeat blackout, no dead-sweep window — and remember
            # the goodbyed process generation so a straggler beat from
            # the same (now exiting) process can't re-register it.
            return self._apply_goodbye(node_key, hb)
        with self._hb_apply_lock:
            node_lock = self._hb_node_locks.setdefault(
                node_key, threading.Lock())
            goodbyed = self._goodbye_epochs.get(node_key)
            if goodbyed is not None:
                if goodbyed == hb.get("seq_epoch"):
                    # Straggler from a goodbyed process: acknowledge
                    # without resurrecting the node (a RESTARTED
                    # server has a fresh epoch, registers normally).
                    return {"volume_size_limit":
                            self.topo.volume_size_limit}
                # A different generation is alive on this address: the
                # goodbye record has served its purpose.
                self._goodbye_epochs.pop(node_key, None)
            if node_key not in self._hb_known:
                self._hb_known.add(node_key)
                from ..events import emit as emit_event
                emit_event("heartbeat.recovered", node=node_key,
                           data_center=hb.get("data_center", ""),
                           rack=hb.get("rack", ""))
                # Resurrection fencing: a returning node lifts its
                # drain fence and schedules the dedupe pass that
                # resolves any repair that landed while it was away.
                self.repair.node_returned(node_key)
        with node_lock:
            # Re-check under node_lock: a beat that read the guard
            # before a goodbye landed (and was then preempted) must
            # not re-register the drained node as a ghost — that would
            # restore the exact dead-sweep window goodbyes eliminate.
            goodbyed = self._goodbye_epochs.get(node_key)
            if goodbyed is not None and \
                    goodbyed == hb.get("seq_epoch"):
                return {"volume_size_limit":
                        self.topo.volume_size_limit}
            dn = self.topo.register_data_node(
                hb.get("data_center", "DefaultDataCenter"),
                hb.get("rack", "DefaultRack"),
                hb["ip"], hb["port"], hb.get("public_url", ""),
                hb.get("max_volume_count", 7))
            # Per-directory disk status (all/used/free/percent_used)
            # rides every heartbeat — the health rollup's capacity view.
            if "disks" in hb:
                dn.disk_statuses = hb["disks"]
            if "ec_corrupt" in hb:
                # vid -> unrepaired corrupt shard blocks (scrub): the
                # health rollup reports these EC volumes degraded.
                dn.ec_corrupt = {int(k): v for k, v in
                                 hb["ec_corrupt"].items()}
            # Lifecycle/capacity flags: _assign steers away from
            # draining and reserve-breached nodes.
            dn.draining = bool(hb.get("draining", False))
            dn.low_disk = bool(hb.get("low_disk", False))
            if "slo" in hb:
                # Burn verdict + mergeable quantile sketches: the
                # health rollup degrades on fast burn and folds every
                # node's sketch into the cluster-wide tail.
                dn.slo_state = hb["slo"]
            if "replication" in hb:
                # Per-volume mirroring lag (seq delta + seconds) and
                # pairing config from the node's shipper — the health
                # rollup's lag-SLO input and /cluster/mirror's rows.
                dn.replication = hb["replication"]
            if "leases" in hb:
                # Geo write-lease rows (cluster_id/epoch per mirrored
                # volume): cluster.lease.ls and the mirror rollup read
                # these; steering keys off the mirror lag, not these.
                dn.leases = hb["leases"]
            if "tenants" in hb:
                # Absolute per-(tenant, collection) stored usage:
                # replace this node's rollup rows and write through to
                # the durable snapshot (cadence-gated inside save()).
                self.usage_rollup.update_node(dn.url(), hb["tenants"])
                self.usage_rollup.save()
            if "flows" in hb:
                # Wire-flow ledger rows (absolute totals): keep the
                # previous sample so /cluster/flows can derive rates
                # from successive beats.  The snapshot was serialized
                # BEFORE this heartbeat's bytes went on the wire, so
                # the node's control-sent row lags our live recv
                # counter by exactly the in-flight report; measure
                # that gap now and let the conservation check grant
                # it as slack on this node's control cell.
                me = f"{self.server.host}:{self.server.port}"
                rows = hb["flows"].get("rows", [])
                claimed = sum(r["bytes"] for r in rows
                              if r["peer"] == me
                              and r["purpose"] == "control"
                              and r["direction"] == "out")
                live, _ops = _flows.LEDGER.totals(
                    purpose_="control", direction="in", local=me,
                    peer=dn.url())
                dn.flows_prev = getattr(dn, "flows", None)
                dn.flows = {"ts": time.time(), "rows": rows,
                            "budgets": hb["flows"].get("budgets", {}),
                            "gap": max(0, live - claimed)}
            if "device" in hb:
                # Device roofline rollup (absolute kernel rows +
                # occupancy summary): replaced wholesale each beat,
                # read by /cluster/device and the healthz
                # occupancy-collapse warning.
                dn.device = {"ts": time.time(), **hb["device"]}
            seq = hb.get("seq")
            if seq is not None:
                # The epoch changes when the volume server restarts, so
                # a fresh process's seq=1 isn't mistaken for stale.
                epoch = hb.get("seq_epoch", 0)
                if epoch != getattr(dn, "heartbeat_epoch", None):
                    dn.heartbeat_epoch = epoch
                    dn.last_heartbeat_seq = 0
                if seq <= getattr(dn, "last_heartbeat_seq", 0):
                    return {"volume_size_limit":
                            self.topo.volume_size_limit}
                dn.last_heartbeat_seq = seq
            before = set(dn.volumes) | set(dn.ec_shards)
            if "volumes" in hb:  # full sync
                volumes = [_vinfo_from_dict(v) for v in hb["volumes"]]
                self.topo.sync_data_node_registration(volumes, dn)
            else:  # delta
                self.topo.incremental_sync(
                    [_vinfo_from_dict(v)
                     for v in hb.get("new_volumes", [])],
                    [_vinfo_from_dict(v)
                     for v in hb.get("deleted_volumes", [])],
                    dn)
            if "ec_shards" in hb:
                self.topo.sync_data_node_ec_shards(
                    [(e["id"], e.get("collection", ""), e["shard_bits"],
                      e.get("codec", "rs"))
                     for e in hb["ec_shards"]], dn)
            # Incremental EC deltas (master_grpc_server.go handles the
            # same Heartbeat fields): merge into the node's shard bits.
            for e in hb.get("new_ec_shards", []):
                bits = dn.ec_shards.get(e["id"], 0) | e["shard_bits"]
                self.topo.register_ec_shards(
                    e["id"], e.get("collection", ""), bits, dn)
            for e in hb.get("deleted_ec_shards", []):
                bits = dn.ec_shards.get(e["id"], 0) & ~e["shard_bits"]
                if bits:
                    self.topo.register_ec_shards(
                        e["id"], e.get("collection", ""), bits, dn)
                else:
                    self.topo.unregister_ec_shards(e["id"], dn)
            after = set(dn.volumes) | set(dn.ec_shards)
        if after != before:
            # Push the delta to every /cluster/watch stream — clients
            # drop their stale vid-map entries immediately
            # (master_grpc_server.go:178 broadcast).
            self._broadcast_locations({
                "url": dn.url(), "public_url": dn.public_url,
                "new_vids": sorted(after - before),
                "deleted_vids": sorted(before - after)})
        return {"volume_size_limit": self.topo.volume_size_limit}

    def _apply_goodbye(self, node_key: str, hb: dict) -> dict:
        """Handle a drain goodbye: snapshot the node's holdings,
        unregister it, broadcast the lost vids to /cluster/watch
        streams (clients re-lookup immediately), and record the
        goodbyed epoch so straggler beats can't resurrect it."""
        from ..events import emit as emit_event
        with self._hb_apply_lock:
            node_lock = self._hb_node_locks.setdefault(
                node_key, threading.Lock())
            self._goodbye_epochs[node_key] = hb.get("seq_epoch", 0)
        with node_lock:
            dn = None
            for leaf in list(self.topo.leaves()):
                if leaf.url() == node_key:
                    dn = leaf
                    break
            if dn is None:
                return {"goodbye": True}
            held_volumes = sorted(dn.volumes)
            held_ec = sorted(dn.ec_shards)
            self.topo.unregister_data_node(dn)
            self._hb_known.discard(node_key)
        emit_event("node.drained", node=node_key,
                   volumes=len(held_volumes), ec_shards=len(held_ec))
        # Planned maintenance never repairs: fence every vid this node
        # held until a new generation of the node registers.
        self.repair.node_goodbyed(
            node_key, set(held_volumes) | set(held_ec))
        vids = sorted(set(held_volumes) | set(held_ec))
        if vids:
            self._broadcast_locations({
                "url": dn.url(), "public_url": dn.public_url,
                "new_vids": [], "deleted_vids": vids})
        return {"goodbye": True}

    def _ui(self, query: dict, body: bytes):
        """Status page (the reference's master UI, server/master_ui):
        leader, topology tree with per-node volume counts, admin-cron
        history."""
        from html import escape as esc
        rows = []
        with self.topo._lock:
            for dc in list(self.topo.children.values()):
                for rack in list(dc.children.values()):
                    for dn in list(rack.children.values()):
                        # Everything heartbeat- or client-supplied is
                        # escaped: a hostile collection/rack name must
                        # not script the operator's browser.
                        rows.append(
                            f"<tr><td>{esc(str(dc.id))}</td>"
                            f"<td>{esc(str(rack.id))}</td>"
                            f"<td>{esc(dn.url())}</td>"
                            f"<td>{len(dn.volumes)}</td>"
                            f"<td>{dn.max_volume_count}</td>"
                            f"<td>{len(dn.ec_shards)}</td></tr>")
        cron = "".join(
            f"<tr><td>{time.strftime('%H:%M:%S', time.localtime(ts))}"
            f"</td><td><code>{esc(line)}</code></td>"
            f"<td>{'ok' if ok else 'FAIL'}</td></tr>"
            for ts, line, ok, _out in self.admin_script_runs[-20:])
        html = (
            "<!doctype html><title>seaweedfs-tpu master</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "table{border-collapse:collapse}td,th{border:1px solid #ccc;"
            "padding:4px 8px}</style>"
            f"<h1>Master {self.url()}</h1>"
            f"<p>leader: {self.is_leader()} &middot; "
            f"max volume id: {self.topo.max_volume_id} &middot; "
            f"volume size limit: "
            f"{self.topo.volume_size_limit >> 20}MB</p>"
            "<h2>Topology</h2><table><tr><th>DC</th><th>Rack</th>"
            "<th>Node</th><th>Volumes</th><th>Max</th>"
            "<th>EC shard groups</th></tr>" + "".join(rows) + "</table>"
            + ("<h2>Admin cron (last 20)</h2><table><tr><th>at</th>"
               "<th>command</th><th>result</th></tr>" + cron + "</table>"
               if cron else "")
            + "<p><a href='/dir/status'>JSON status</a></p>")
        return (200, html.encode(),
                {"Content-Type": "text/html; charset=utf-8"})

    # -- location push (KeepConnected analog) --------------------------------

    def _cluster_watch(self, query: dict, body: bytes):
        """Long-lived location push stream: an initial snapshot of
        every node's volumes, then deltas as heartbeats change them
        (master_grpc_server.go KeepConnected broadcasting
        VolumeLocation messages).  Followers refuse: their topology is
        empty and a heartbeating-but-delta-free stream would silently
        disable push invalidation; the client redials (rotating seeds)
        until it finds the leader.  A deposed leader ends its streams
        from the sweep loop for the same reason."""
        if not self.is_leader():
            raise rpc.RpcError(503, "not the leader; redial")
        stream = rpc.EventStream()
        with self._watchers_lock:
            self._watchers.append(stream)
        stream.on_close(lambda: self._drop_watcher(stream))
        with self.topo._lock:
            for dc in list(self.topo.children.values()):
                for rack in list(dc.children.values()):
                    for dn in list(rack.children.values()):
                        vids = sorted(set(dn.volumes)
                                      | set(dn.ec_shards))
                        if vids:
                            stream.push({"url": dn.url(),
                                         "public_url": dn.public_url,
                                         "new_vids": vids,
                                         "deleted_vids": []})
        return (200, stream, {"Content-Type": "application/x-ndjson"})

    def _drop_watcher(self, stream) -> None:
        with self._watchers_lock:
            if stream in self._watchers:
                self._watchers.remove(stream)

    def _broadcast_locations(self, doc: dict) -> None:
        with self._watchers_lock:
            watchers = list(self._watchers)
        for w in watchers:
            try:
                w.push(doc)
            except Exception:  # noqa: BLE001 — a dying stream cleans
                pass           # itself up via on_close

    @staticmethod
    def _locs_blocked(locs) -> bool:
        """True when ANY replica of a candidate volume sits on a node
        that should not take new writes: draining (rolling restart) or
        below its free-space reserve.  A write to such a volume would
        fail at fan-out time — steer the assignment away instead."""
        return any(getattr(dn, "draining", False)
                   or getattr(dn, "low_disk", False) for dn in locs)

    def _steering_exclude(self):
        """The pick_for_write exclude predicate, or None in the steady
        state: filtering every writable volume through the predicate
        is O(writables x replicas) on the assign hot path, so pay it
        only while at least one node is actually draining or below its
        reserve (one O(nodes) scan per assign)."""
        for dn in list(self.topo.leaves()):
            if getattr(dn, "draining", False) or \
                    getattr(dn, "low_disk", False):
                return self._locs_blocked
        return None

    def _option_from_query(self, query: dict) -> VolumeGrowOption:
        return VolumeGrowOption(
            collection=query.get("collection", ""),
            replica_placement=query.get("replication",
                                        self.default_replication),
            ttl=query.get("ttl", ""),
            data_center=query.get("dataCenter", ""),
            rack=query.get("rack", ""),
            data_node=query.get("dataNode", ""))

    def _quota_verdict(self, tenant: str) -> tuple | None:
        """(rule, used_bytes, used_objects, reasons) when the tenant is
        over a stored-usage quota, else None."""
        rule = self.tenant_policy.rule_for(tenant)
        if rule is None or not (rule.max_bytes or rule.max_objects):
            return None
        used_b, used_o = self.usage_rollup.usage_for(tenant)
        reasons = []
        if rule.max_bytes and used_b >= rule.max_bytes:
            reasons.append(f"stored bytes {used_b} >= "
                           f"max_bytes {rule.max_bytes}")
        if rule.max_objects and used_o >= rule.max_objects:
            reasons.append(f"stored objects {used_o} >= "
                           f"max_objects {rule.max_objects}")
        if not reasons:
            return None
        return (rule, used_b, used_o, reasons)

    def _check_assign_quota(self, tenant: str) -> None:
        """Hard byte/object quotas reject at ASSIGN time — before any
        volume server sees a byte — with the same 403 QuotaExceeded
        the filer/S3 front door answers.  Soft rules only journal (one
        `quota.exceeded` row per tenant per >=5s episode) and surface
        on healthz."""
        if not tenant:
            return
        verdict = self._quota_verdict(tenant)
        if verdict is None:
            return
        rule, used_b, used_o, reasons = verdict
        now = time.monotonic()
        if now - self._last_quota_emit.get(tenant, 0.0) >= 5.0:
            self._last_quota_emit[tenant] = now
            from ..events import emit as emit_event
            emit_event("quota.exceeded", node=self.url(),
                       severity="warn", tenant=tenant,
                       soft=rule.soft, used_bytes=used_b,
                       used_objects=used_o, reason="; ".join(reasons))
        if rule.soft:
            return
        raise rpc.RpcError(
            403, f"QuotaExceeded: tenant {tenant!r} over quota "
                 f"({'; '.join(reasons)}); delete data (and let "
                 f"vacuum reclaim) to resume writes")

    def _assign(self, query: dict, body: bytes) -> dict:
        if not self.is_leader():
            return self._proxy_to_leader("/dir/assign", query, body)
        self._check_assign_quota(query.get("_tenant", ""))
        from .raft import NotLeader
        option = self._option_from_query(query)
        count = int(query.get("count", 1))
        layout = self.topo.layout_for(option)
        if layout.active_volume_count(option) == 0:
            with self._grow_lock:
                if layout.active_volume_count(option) == 0:
                    try:
                        grown = self.vg.grow_by_type(
                            self.topo, option, self._allocate_volume)
                    except NotLeader:
                        # Lost leadership mid-grow; hand the request on.
                        return self._proxy_to_leader("/dir/assign",
                                                     query, body)
                    if grown == 0:
                        raise rpc.RpcError(
                            406, "no free volumes and cannot grow")
                    from ..events import emit as emit_event
                    emit_event("volume.grow", node=self.url(),
                               count=grown, reason="assign",
                               collection=option.collection)
        exclude = self._steering_exclude()
        try:
            fid, count, locs = self.topo.pick_for_write(
                count, option, layout, exclude=exclude)
        except NotLeader:
            # The RaftSequencer's block alloc can discover lost
            # leadership (exactly the failover window it exists for):
            # hand the request to the new leader like the grow path.
            return self._proxy_to_leader("/dir/assign", query, body)
        except TimeoutError as e:
            raise rpc.RpcError(
                503, f"file-id allocation not committed: {e}") from None
        except ValueError:
            # Writable volumes exist, but every one has a replica on a
            # draining or reserve-breached node (rolling restart, disk
            # filling up): grow fresh volumes on the healthy nodes and
            # pick again; if the cluster genuinely has nowhere to put
            # a write, hand the client a paced retry.
            with self._grow_lock:
                try:
                    grown = self.vg.grow_by_type(self.topo, option,
                                                 self._allocate_volume)
                except NotLeader:
                    return self._proxy_to_leader("/dir/assign", query,
                                                 body)
                except Exception:  # noqa: BLE001 — no healthy slots
                    grown = 0
            if grown:
                from ..events import emit as emit_event
                emit_event("volume.grow", node=self.url(), count=grown,
                           reason="steering",
                           collection=option.collection)
            try:
                fid, count, locs = self.topo.pick_for_write(
                    count, option, layout, exclude=exclude)
            except (ValueError, TimeoutError):
                raise rpc.RpcError(
                    503, "no writable volumes outside draining/"
                         "low-disk nodes; retry",
                    headers={"Retry-After": "1"}) from None
            except NotLeader:
                return self._proxy_to_leader("/dir/assign", query,
                                             body)
        dn = locs[0]
        out = {"fid": fid, "count": count,
               "url": dn.url(), "publicUrl": dn.public_url,
               "replicas": [{"url": n.url(), "publicUrl": n.public_url}
                            for n in locs[1:]]}
        if self.jwt_signing_key:
            from ..utils.security import gen_jwt
            out["auth"] = gen_jwt(self.jwt_signing_key,
                                  self.jwt_expires_seconds, fid)
        return out

    def _allocate_volume(self, vid: int, option: VolumeGrowOption,
                         server) -> None:
        rpc.call_json(
            f"http://{server.url()}/admin/assign_volume",
            payload={"volume": vid, "collection": option.collection,
                     "replication": option.replica_placement,
                     "ttl": option.ttl})
        # Optimistic registration; the next heartbeat confirms.
        self.topo.register_volume(VolumeInfo(
            id=vid, collection=option.collection, size=0, file_count=0,
            delete_count=0, deleted_byte_count=0, read_only=False,
            replica_placement=ReplicaPlacement.parse(
                option.replica_placement).to_byte(),
            ttl=TTL.parse(option.ttl).to_uint32(),
            compact_revision=0), server)
        from ..events import emit as emit_event
        emit_event("volume.assign", node=server.url(), vid=vid,
                   collection=option.collection,
                   replication=option.replica_placement)

    def _lookup(self, query: dict, body: bytes) -> dict:
        if not self.is_leader():
            # Volume state lives on the leader (heartbeats go there);
            # followers proxy reads too (master_server.go:155).
            return self._proxy_to_leader("/dir/lookup", query, body,
                                         "GET")
        vid_str = query.get("volumeId", "")
        if "," in vid_str:
            vid_str = vid_str.split(",")[0]
        vid = int(vid_str)
        collection = query.get("collection", "")
        locs = self.topo.lookup(collection, vid)
        if locs:
            locations = [{"url": dn.url(), "publicUrl": dn.public_url}
                         for dn in locs]
            # steered=1 marks a peer master's own steering fetch: never
            # steer it back (two masters steering each other would
            # recurse until a timeout).
            if self.steer_reads and query.get("steered") != "1":
                locations = self._steer_locations(vid, query, locations)
            out = {"volumeId": vid, "locations": locations}
            # Write token for delete/update of an existing fid
            # (operation/delete_content.go fetches a lookup jwt).
            if self.jwt_signing_key and query.get("fileId"):
                from ..utils.security import gen_jwt
                out["auth"] = gen_jwt(self.jwt_signing_key,
                                      self.jwt_expires_seconds,
                                      query["fileId"])
            return out
        ec = self.topo.lookup_ec_shards(vid)
        if ec is not None:
            return {"volumeId": vid, "ecCodec": ec.codec, "ecShards": {
                str(sid): [{"url": dn.url(), "publicUrl": dn.public_url}
                           for dn in dns]
                for sid, dns in ec.locations.items() if dns}}
        raise rpc.RpcError(404, f"volume {vid} not found")

    # -- geo locality steering ----------------------------------------------

    def _peer_mirror_rows(self) -> dict:
        """Per-volume mirror rows from the PEER master's
        /cluster/mirror, cached for `steer_refresh` seconds.  The
        peer's shipper lag for a volume IS our local replica's
        staleness (the peer ships volumes it holds to us), so this map
        answers "is my local copy of vid within the lag SLO?"."""
        with self._steer_lock:
            ts, rows = self._steer_mirror
            if time.time() - ts < self.steer_refresh:
                return rows
        try:
            doc = rpc.call(f"http://{self.steer_peer}/cluster/mirror",
                           timeout=2.0)
            rows = {int(r["volume"]): r
                    for r in doc.get("volumes", [])
                    if "volume" in r}
        except (rpc.RpcError, OSError, ConnectionError, ValueError,
                TypeError):
            rows = {}
        with self._steer_lock:
            self._steer_mirror = (time.time(), rows)
        return rows

    def _peer_locations(self, vid: int, collection: str) -> list:
        """The peer cluster's replica locations for `vid`, from the
        peer master's /dir/lookup, cached for `steer_refresh`
        seconds.  Empty on any failure — steering degrades to
        unsteered, it never breaks a lookup."""
        with self._steer_lock:
            hit = self._steer_locs.get(vid)
            if hit is not None and \
                    time.time() - hit[0] < self.steer_refresh:
                return hit[1]
        locs: list = []
        try:
            qs = urllib.parse.urlencode(
                {"volumeId": vid, "collection": collection,
                 "steered": 1})
            doc = rpc.call(
                f"http://{self.steer_peer}/dir/lookup?{qs}",
                timeout=2.0)
            locs = list(doc.get("locations", []))
        except (rpc.RpcError, OSError, ConnectionError,
                ValueError, TypeError):
            locs = []
        with self._steer_lock:
            self._steer_locs[vid] = (time.time(), locs)
        return locs

    def _steer_locations(self, vid: int, query: dict,
                         locations: list) -> list:
        """Reorder a /dir/lookup answer for geo locality: prepend the
        peer cluster's replicas when (a) the requesting tenant's
        quota rule pins a home= region that isn't ours, or (b) our
        local replica is mirrored FROM the peer and its lag exceeds
        the lag SLO (reads here would see stale data).  Clients walk
        the list in order and re-lookup on 429/503, so steering is
        advisory and self-healing; any steering failure returns the
        unsteered list."""
        prefer_peer = False
        tenant = query.get("tenant", "")
        if tenant and self.geo_cluster_id:
            rule = self.tenant_policy.rule_for(tenant)
            if rule is not None and rule.home and \
                    rule.home != self.geo_cluster_id:
                prefer_peer = True
        if not prefer_peer and self.replication_lag_slo is not None:
            row = self._peer_mirror_rows().get(vid)
            if row is not None and \
                    float(row.get("lag_seconds", 0.0) or 0.0) > \
                    self.replication_lag_slo:
                prefer_peer = True
        if not prefer_peer:
            return locations
        peer_locs = self._peer_locations(
            vid, query.get("collection", ""))
        if not peer_locs:
            return locations
        seen = {loc.get("url") for loc in peer_locs}
        return peer_locs + [loc for loc in locations
                            if loc.get("url") not in seen]

    def _status(self, query: dict, body: bytes) -> dict:
        if not self.is_leader() and self.raft.leader():
            return self._proxy_to_leader("/dir/status", query, body,
                                         "GET")
        def node_dict(n):
            out = {"id": n.id, "volumes": n.volume_count,
                   "max": n.max_volume_count, "free": n.free_space(),
                   "ecShards": n.ec_shard_count}
            if n.children:
                out["children"] = [node_dict(c)
                                   for c in n.children.values()]
            return out
        return {"topology": node_dict(self.topo),
                "max_volume_id": self.topo.max_volume_id}

    def _grow(self, query: dict, body: bytes) -> dict:
        if not self.is_leader():
            return self._proxy_to_leader("/vol/grow", query, body)
        option = self._option_from_query(query)
        count = int(query.get("count", 0)) or None
        with self._grow_lock:
            grown = self.vg.grow_by_type(self.topo, option,
                                         self._allocate_volume,
                                         ) if count is None else \
                self._grow_n(option, count)
        if grown:
            from ..events import emit as emit_event
            emit_event("volume.grow", node=self.url(), count=grown,
                       reason="explicit", collection=option.collection)
        return {"count": grown}

    def _grow_n(self, option: VolumeGrowOption, n: int) -> int:
        grown = 0
        for _ in range(n):
            try:
                servers = self.vg.find_empty_slots_for_one_volume(
                    self.topo, option)
            except ValueError:
                break
            vid = self.topo.next_volume_id()
            try:
                for server in servers:
                    self._allocate_volume(vid, option, server)
            except Exception:  # noqa: BLE001 — a dead server shouldn't
                continue       # void the volumes grown so far
            grown += 1
        return grown

    def _col_list(self, query: dict, body: bytes) -> dict:
        if not self.is_leader():
            return self._proxy_to_leader("/col/list", query, body, "GET")
        return {"collections": sorted(self.topo.collections)}

    def _col_delete(self, query: dict, body: bytes) -> dict:
        if not self.is_leader():
            return self._proxy_to_leader("/col/delete", query, body)
        name = query.get("collection", "")
        col = self.topo.collections.get(name)
        if col is None:
            raise rpc.RpcError(404, f"collection {name!r} not found")
        # Tell every server holding its volumes to delete them.
        deleted = 0
        for vl in col.layouts.values():
            for vid, dns in list(vl.vid2location.items()):
                for dn in dns:
                    try:
                        rpc.call_json(
                            f"http://{dn.url()}/admin/delete_volume",
                            payload={"volume": vid})
                        deleted += 1
                    except rpc.RpcError:
                        pass
        self.topo.delete_collection(name)
        return {"deleted_replicas": deleted}

    def _cluster_status(self, query: dict, body: bytes) -> dict:
        from ..stats.sysstats import proc_cpu_seconds
        out = {"leader": self.leader_url(),
               "is_leader": self.is_leader(),
               "volume_size_limit": self.topo.volume_size_limit,
               "cpu_seconds": proc_cpu_seconds(), "pid": os.getpid()}
        if self.raft is not None:
            out["peers"] = [self.url()] + self.raft.peers
            out["raft"] = {"state": self.raft.state,
                           "term": self.raft.current_term,
                           "commit_index": self.raft.commit_index}
        return out

    # -- health rollup + event aggregation -----------------------------------

    def _node_health_values(self) -> dict:
        """SeaweedFS_node_health{node=} callback: 1 while a node's last
        heartbeat is within the dead-node threshold, else 0."""
        now = time.time()
        fresh = 2 * self.topo.pulse_seconds
        return {(dn.url(),): 1.0 if now - dn.last_seen <= fresh else 0.0
                for dn in list(self.topo.leaves())}

    def health_report(self) -> tuple[bool, dict]:
        """Derived cluster health: per-node liveness (heartbeat age,
        outbound breaker state, disk fill) and per-volume/EC-volume
        health (missing shards, readonly, garbage ratio).  Returns
        (healthy, detail) — the /cluster/healthz and cluster.check
        core."""
        from ..codecs import get_codec
        from . import resilience as _res
        now = time.time()
        fresh = 2 * self.topo.pulse_seconds
        problems: list[str] = []
        nodes = []
        volumes = []
        replication_rows = []
        with self.topo._lock:
            leaves = list(self.topo.leaves())
            ec_map = {vid: ({sid: [dn.url() for dn in dns]
                             for sid, dns in loc.locations.items() if dns},
                            loc.codec)
                      for vid, loc in self.topo.ec_shard_map.items()}
        slo_reads: list[dict] = []
        slo_writes: list[dict] = []
        burning_nodes: list[str] = []
        for dn in leaves:
            age = now - dn.last_seen
            alive = age <= fresh
            breaker = _res._breakers.get(dn.url())
            slo_state = getattr(dn, "slo_state", None) or {}
            row = {"node": dn.url(), "heartbeat_age": round(age, 3),
                   "alive": alive,
                   "breaker": breaker.state if breaker else "closed",
                   "volumes": len(dn.volumes),
                   "ec_shards": len(dn.ec_shards),
                   "draining": getattr(dn, "draining", False),
                   "low_disk": getattr(dn, "low_disk", False),
                   "disks": getattr(dn, "disk_statuses", []),
                   "slo": {k: slo_state.get(k, False)
                           for k in ("declared", "fast_burn",
                                     "slow_burn")}}
            nodes.append(row)
            # Heartbeat-fed SLO state: fast burn degrades the cluster
            # (the node is violating a declared objective NOW); its
            # read/write sketches fold into the cluster-wide tail.
            # Gated on liveness — a dead node's FINAL verdict and
            # window must not haunt the "live" rollup forever (its
            # staleness is already its own problem row above).
            if alive and slo_state.get("fast_burn"):
                burning_nodes.append(dn.url())
                problems.append(
                    f"node {dn.url()}: SLO fast burn — a declared "
                    f"objective's error budget is burning at page "
                    f"rate (see /debug/slo on the node)")
            if alive and isinstance(slo_state.get("read"), dict):
                slo_reads.append(slo_state["read"])
            if alive and isinstance(slo_state.get("write"), dict):
                slo_writes.append(slo_state["write"])
            if not alive:
                problems.append(
                    f"node {dn.url()}: heartbeat stale {age:.1f}s")
            if row["low_disk"]:
                problems.append(
                    f"node {dn.url()}: disk reserve breached — "
                    f"volumes readonly until space recovers")
            if row["breaker"] == "open":
                problems.append(f"node {dn.url()}: circuit breaker open")
            for d in row["disks"]:
                if d.get("percent_used", 0) >= 95.0:
                    problems.append(
                        f"node {dn.url()}: disk {d.get('dir', '?')} "
                        f"{d['percent_used']:.1f}% full")
            for vid, cnt in sorted(getattr(dn, "ec_corrupt",
                                           {}).items()):
                problems.append(
                    f"ec volume {vid}: {cnt} corrupt shard block(s) "
                    f"on {dn.url()} unrepaired")
            repl = getattr(dn, "replication", None)
            if alive and repl:
                for vid, rrow in sorted(
                        (repl.get("volumes") or {}).items()):
                    replication_rows.append(dict(
                        rrow, volume=int(vid), node=dn.url(),
                        peer=repl.get("peer", ""),
                        paused=repl.get("paused", False)))
                    lag = float(rrow.get("lag_seconds", 0) or 0)
                    if self.replication_lag_slo is not None and \
                            lag > self.replication_lag_slo:
                        # Mirror lag SLO breach: the standby would
                        # lose up to `lag` seconds of acked writes if
                        # the primary died now — degrade until it
                        # catches back up to the watermark.
                        problems.append(
                            f"volume {vid} on {dn.url()}: replication "
                            f"lag {lag:.1f}s exceeds SLO "
                            f"{self.replication_lag_slo:g}s "
                            f"({rrow.get('lag_seq', 0)} records "
                            f"unacked by {repl.get('peer', '?')})")
            for v in list(dn.volumes.values()):
                ratio = (v.deleted_byte_count / v.size) if v.size else 0.0
                volumes.append({"id": v.id, "node": dn.url(),
                                "collection": v.collection,
                                "read_only": v.read_only,
                                "corrupt": v.corrupt_count,
                                "garbage_ratio": round(ratio, 4)})
                if v.corrupt_count:
                    # Unrepaired corruption = degraded, exactly like
                    # missing EC shards: the data is at reduced
                    # redundancy until the scrub (or an operator
                    # volume.scrub -repair) heals it.
                    problems.append(
                        f"volume {v.id} on {dn.url()}: "
                        f"{v.corrupt_count} corrupt needle(s) "
                        f"quarantined, unrepaired")
        if not leaves:
            problems.append("no live data nodes")
        ec_volumes = []
        for vid, (locs, codec_name) in sorted(ec_map.items()):
            # Shard counts (and decodability) are per-codec in a
            # mixed-codec cluster, not the RS(10,4) constants.
            try:
                codec = get_codec(codec_name)
            except ValueError:  # unknown codec id in a stale heartbeat
                codec = get_codec("rs")
            total = codec.total_shards
            missing = [s for s in range(total) if s not in locs]
            try:
                codec.repair_plan(tuple(locs), missing)
                recoverable = True
            except ValueError:
                recoverable = False
            ec_volumes.append({"id": vid, "present": len(locs),
                               "codec": codec_name, "missing": missing})
            if not recoverable:
                problems.append(
                    f"ec volume {vid}: UNRECOVERABLE — only "
                    f"{len(locs)} of {total} shards survive "
                    f"({codec_name})")
            elif missing:
                problems.append(
                    f"ec volume {vid}: degraded — missing shards "
                    f"{missing}")
        # Cluster-wide SLO rollup: the master's own tracker plus every
        # node's heartbeat sketches, merged (exact bucket addition,
        # stats/sketch.py) into one read tail and one write tail — the
        # number a load balancer or the bench harness cross-checks.
        from ..stats import slo as _slo
        own = self.server.slo
        own_view = own.heartbeat_view()
        if own_view.get("fast_burn"):
            burning_nodes.append(f"master {self.url()}")
            problems.append(
                f"master {self.url()}: SLO fast burn — a declared "
                f"objective's error budget is burning at page rate")
        slo_reads.append(own_view["read"])
        slo_writes.append(own_view["write"])

        def _qs(dicts: list[dict]) -> dict:
            merged = _slo.merge_sketch_dicts(dicts)
            if merged is None or merged.count == 0:
                return {"count": 0}
            return {"count": merged.count,
                    "p50": merged.quantile(0.5),
                    "p95": merged.quantile(0.95),
                    "p99": merged.quantile(0.99)}

        slo_doc = {"read": _qs(slo_reads), "write": _qs(slo_writes),
                   "sources": len(slo_reads),
                   "fast_burn": burning_nodes}
        # Tenancy rollup: a tenant over a HARD stored quota is a
        # healthz problem row (mirroring the 403s being answered);
        # soft breaches stay warnings — they must not flip the whole
        # cluster to 503 for a load balancer.
        tenancy_rows = []
        tenancy_warnings = []
        for t, ent in sorted(self.usage_rollup.totals().items()):
            verdict = self._quota_verdict(t)
            tenancy_rows.append({"tenant": t, "bytes": ent["bytes"],
                                 "objects": ent["objects"],
                                 "over_quota": verdict is not None})
            if verdict is not None:
                rule, _b, _o, reasons = verdict
                if rule.soft:
                    tenancy_warnings.append(
                        f"tenant {t}: soft quota exceeded — "
                        f"{'; '.join(reasons)}")
                else:
                    problems.append(
                        f"tenant {t}: hard quota exceeded — "
                        f"{'; '.join(reasons)} (writes rejected "
                        f"with 403 QuotaExceeded)")
        # Wire-flow budgets: a sustained per-purpose bandwidth breach
        # is a WARNING (like soft quotas) — background traffic running
        # hot must not flip the cluster to 503 for a load balancer,
        # but operators polling healthz should see it.
        flows_warnings = []
        flow_budget_rows = []
        flow_sources = [(dn.url(),
                         (getattr(dn, "flows", None) or {})
                         .get("budgets", {}))
                        for dn in leaves]
        me_flow = f"{self.server.host}:{self.server.port}"
        flow_sources.append(
            (me_flow, _flows.LEDGER.budget_status(local=me_flow)))
        for node, status in flow_sources:
            for purpose_name, st in sorted(status.items()):
                flow_budget_rows.append(dict(st, node=node,
                                             purpose=purpose_name))
                if st.get("breached"):
                    flows_warnings.append(
                        f"node {node}: {purpose_name} over bandwidth "
                        f"budget — {st.get('rate_bps', 0):.0f} B/s "
                        f"sustained against a "
                        f"{st.get('limit_bps', 0):.0f} B/s limit")
        # Device roofline: sustained pipeline-occupancy collapse on a
        # node is a WARNING (like flow budgets) — a starved device
        # wastes the accelerator but serves data fine, so it must
        # never flip healthz to 503.
        device_warnings = []
        device_rows = []
        for dn in leaves:
            dev = getattr(dn, "device", None)
            if not dev:
                continue
            occ = (dev.get("occupancy") or {})
            for kind, row in sorted((occ.get("latest") or {}).items()):
                device_rows.append(dict(row, node=dn.url(),
                                        pipeline=kind))
            for kind, bad in sorted((occ.get("collapsed")
                                     or {}).items()):
                if bad:
                    latest = (occ.get("latest") or {}).get(kind, {})
                    frac = latest.get("fraction")
                    starving = latest.get("starving_stage") or "?"
                    device_warnings.append(
                        f"node {dn.url()}: {kind} pipeline device "
                        f"occupancy collapsed"
                        + (f" to {frac:.0%}" if frac is not None
                           else "")
                        + f" — starved by {starving}")
        # Geo lease rollup (info-only: a moving or remote-held lease
        # is a normal operating state, not a health problem — the
        # fencing failure mode is 409s on the ship path, and those
        # surface as replication lag here).
        lease_doc = {"volumes": 0, "held_local": 0, "moving": 0}
        for dn in leaves:
            lhb = getattr(dn, "leases", None)
            if not lhb:
                continue
            for lrow in (lhb.get("volumes") or {}).values():
                lease_doc["volumes"] += 1
                if lrow.get("holder_is_local"):
                    lease_doc["held_local"] += 1
                if lrow.get("moving"):
                    lease_doc["moving"] += 1
        # Failure-domain audit: replicas that all landed in one
        # rack/DC despite a placement that demands spread, and EC
        # stripes with more shards on one node than same_rack_count+1
        # allows.  Always a WARNING, never 503 — the data is fully
        # readable; the risk is correlated loss.  This is the
        # placement-violation input the autopilot's dedupe /
        # re-placement pass consumes.
        placement_warnings = self._placement_audit()
        # Filer fleet (metadata-HA plane): registered filers appear
        # beside volume nodes; a dead filer or a primary-less shard is
        # a PROBLEM — namespace writes for that shard fail closed.
        filer_rows, filer_problems = self.filer_health_rows()
        problems.extend(filer_problems)
        doc = {"healthy": not problems, "problems": problems,
               "leader": self.leader_url(), "is_leader": self.is_leader(),
               "nodes": nodes, "volumes": volumes,
               "filers": {"nodes": filer_rows,
                          "num_shards": self.filer_shards},
               "ec_volumes": ec_volumes, "slo": slo_doc,
               "replication": {"lag_slo": self.replication_lag_slo,
                               "cluster_id": self.geo_cluster_id
                               or None,
                               "leases": lease_doc,
                               "volumes": replication_rows},
               "lifecycle": self.lifecycle.status(),
               "tenancy": {"rules": len(self.tenant_policy.rules),
                           "warnings": tenancy_warnings,
                           "tenants": tenancy_rows},
               "flows": {"budgets": flow_budget_rows,
                         "warnings": flows_warnings},
               "device": {"occupancy": device_rows,
                          "warnings": device_warnings},
               "placement": {"warnings": placement_warnings},
               "repair": {"enabled": self.repair.enabled,
                          "paused": self.repair.paused,
                          "queue": len(self.repair._queue),
                          "inflight": len(self.repair._inflight)}}
        return not problems, doc

    def _placement_audit(self) -> list[str]:
        """Failure-domain audit rows for health_report (warning-only):
        replicated volumes whose copies all share one rack/DC when the
        placement demands spread, and EC stripes concentrating more
        than same_rack_count+1 shards on a single node."""
        warnings = []
        with self.topo._lock:
            for cname, coll in self.topo.collections.items():
                label = cname or "(default)"
                for layout in coll.layouts.values():
                    rp = layout.rp
                    for vid, locs in sorted(
                            layout.vid2location.items()):
                        if len(locs) < 2:
                            continue
                        dcs = {dn.get_data_center().id for dn in locs}
                        racks = {(dn.get_data_center().id,
                                  dn.get_rack().id) for dn in locs}
                        if rp.diff_data_center_count and len(dcs) == 1:
                            warnings.append(
                                f"volume {vid} ({label}, rp={rp}): all "
                                f"{len(locs)} replicas in data center "
                                f"{next(iter(dcs))}")
                        elif rp.diff_rack_count and len(racks) == 1:
                            warnings.append(
                                f"volume {vid} ({label}, rp={rp}): all "
                                f"{len(locs)} replicas in rack "
                                f"{next(iter(racks))[1]}")
            for vid, loc in sorted(self.topo.ec_shard_map.items()):
                rp = None
                coll = self.topo.collections.get(loc.collection)
                if coll is not None and coll.layouts:
                    rp = next(iter(coll.layouts.values())).rp
                if rp is None:
                    rp = ReplicaPlacement.parse(self.default_replication)
                limit = rp.same_rack_count + 1
                per_node: dict[str, int] = {}
                for sid, dns in loc.locations.items():
                    for dn in dns:
                        url = dn.url()
                        per_node[url] = per_node.get(url, 0) + 1
                for url, n in sorted(per_node.items()):
                    if n > limit:
                        warnings.append(
                            f"ec volume {vid} "
                            f"({loc.collection or '(default)'}): "
                            f"{n} shards on {url} "
                            f"(placement allows {limit})")
        return warnings

    def _cluster_mirror(self, query: dict, body: bytes) -> dict:
        """GET /cluster/mirror — the pairing status rollup: which
        nodes ship to which standby master, per-volume watermarks and
        lag, the configured lag SLO, and a cluster-level verdict
        (`caught_up` = every mirrored volume's lag is zero) — the
        cutover gate the shell polls."""
        if not self.is_leader():
            return self._proxy_to_leader("/cluster/mirror", query,
                                         body, "GET")
        rows = []
        peers = set()
        paused = []
        leases: dict[str, dict] = {}
        with self.topo._lock:
            leaves = list(self.topo.leaves())
        for dn in leaves:
            lhb = getattr(dn, "leases", None)
            if lhb:
                for vid, lrow in sorted(
                        (lhb.get("volumes") or {}).items()):
                    leases[vid] = dict(lrow, node=dn.url())
            repl = getattr(dn, "replication", None)
            if not repl:
                continue
            peers.add(repl.get("peer", ""))
            if repl.get("paused"):
                paused.append(dn.url())
            for vid, rrow in sorted(
                    (repl.get("volumes") or {}).items()):
                rows.append(dict(rrow, volume=int(vid),
                                 node=dn.url(),
                                 peer=repl.get("peer", "")))
        return {"paired": bool(rows or peers),
                "peers": sorted(p for p in peers if p),
                "paused_nodes": paused,
                "lag_slo": self.replication_lag_slo,
                "caught_up": bool(rows) and all(
                    not r.get("lag_seq") for r in rows),
                "cluster_id": self.geo_cluster_id or None,
                "leases": leases,
                "volumes": rows}

    def _cluster_tenants(self, query: dict, body: bytes) -> dict:
        """GET /cluster/tenants — the tenancy rollup: per-tenant stored
        usage (heartbeat-fed, replicas per copy), the matching quota
        rule, and an over_quota verdict per tenant — the shell's
        `cluster.tenants` / `tenant.ls` source."""
        if not self.is_leader():
            return self._proxy_to_leader("/cluster/tenants", query,
                                         body, "GET")
        tenants: dict[str, dict] = {}
        for t, ent in sorted(self.usage_rollup.totals().items()):
            row = {"bytes": ent["bytes"], "objects": ent["objects"],
                   "collections": ent["collections"]}
            rule = self.tenant_policy.rule_for(t)
            if rule is not None:
                row["rule"] = rule.to_dict()
                over = []
                if rule.max_bytes and ent["bytes"] >= rule.max_bytes:
                    over.append("bytes")
                if rule.max_objects and \
                        ent["objects"] >= rule.max_objects:
                    over.append("objects")
                row["over_quota"] = over
                row["enforcement"] = "soft" if rule.soft else "hard"
            tenants[t] = row
        return {"tenants": tenants,
                "rules": self.tenant_policy.to_dict()["rules"],
                "leader": self.url()}

    # -- wire-flow traffic matrix (stats/flows.py) ---------------------------

    def _flow_samples(self) -> dict:
        """node -> (current flow sample, previous sample or None) for
        every flow source: heartbeat-fed volume servers plus this
        master's own live ledger (the master doesn't heartbeat to
        itself — snapshot it here, keeping the last poll's snapshot
        so back-to-back /cluster/flows calls still have a rate base)."""
        samples: dict[str, tuple] = {}
        with self.topo._lock:
            leaves = list(self.topo.leaves())
        for dn in leaves:
            cur = getattr(dn, "flows", None)
            if cur:
                samples[dn.url()] = (cur,
                                     getattr(dn, "flows_prev", None))
        # Scheme-less "host:port", matching the ledger's local
        # identity and the X-Weed-Node header the peers recorded.
        me = f"{self.server.host}:{self.server.port}"
        now = time.time()
        cur = {"ts": now,
               "rows": _flows.LEDGER.snapshot(local=me),
               "budgets": _flows.LEDGER.budget_status(local=me)}
        prev = getattr(self, "_flows_self_prev", None)
        if prev is None or now - prev["ts"] >= 1.0:
            self._flows_self_prev = cur
        samples[me] = (cur, prev)
        return samples

    def _cluster_flows(self, query: dict, body: bytes) -> dict:
        """GET /cluster/flows — the cluster traffic matrix: per
        (src, dst, purpose) cell, cumulative GB both as sent by the
        source and as received by the destination, a rate derived
        from successive ledger samples, per-purpose totals, a
        top-talker link ranking, the per-node budget rollup, and a
        conservation verdict (sender's count must match the
        receiver's within max(1%, 4KB); a reporting node's control
        cell additionally gets the gap MEASURED at merge time — the
        heartbeat POST carries a snapshot that can't include its own
        bytes).  ?purpose= filters to one catalog entry."""
        if not self.is_leader():
            return self._proxy_to_leader("/cluster/flows", query,
                                         body, "GET")
        want = query.get("purpose", "")
        if want:
            _flows.validate(want)
        samples = self._flow_samples()
        cells: dict[tuple, dict] = {}
        for node, (cur, prev) in samples.items():
            prows: dict[tuple, int] = {}
            dt = 0.0
            if prev:
                dt = max(cur["ts"] - prev["ts"], 1e-9)
                for r in prev.get("rows", []):
                    prows[(r["peer"], r["purpose"],
                           r["direction"])] = r["bytes"]
            for r in cur.get("rows", []):
                purpose = r["purpose"]
                if want and purpose != want:
                    continue
                if r["direction"] == "out":
                    key = (node, r["peer"], purpose)
                    side = "sent"
                else:
                    key = (r["peer"], node, purpose)
                    side = "recv"
                c = cells.setdefault(key, {
                    "src": key[0], "dst": key[1], "purpose": purpose,
                    "sent_bytes": None, "recv_bytes": None,
                    "sent_ops": 0, "recv_ops": 0, "rate_bps": 0.0})
                c[side + "_bytes"] = (c[side + "_bytes"] or 0) \
                    + r["bytes"]
                c[side + "_ops"] += r["ops"]
                if prev and r["direction"] == "out":
                    delta = r["bytes"] - prows.get(
                        (r["peer"], purpose, "out"), 0)
                    if delta > 0:
                        c["rate_bps"] += delta / dt
        me = f"{self.server.host}:{self.server.port}"
        gaps = {node: cur.get("gap", 0)
                for node, (cur, _p) in samples.items()}
        paired = 0
        violations: list[dict] = []
        purpose_totals: dict[str, int] = {}
        links: dict[tuple, int] = {}
        for c in cells.values():
            sent, recv = c["sent_bytes"], c["recv_bytes"]
            if sent is not None and recv is not None:
                paired += 1
                skew = abs(sent - recv)
                slack = gaps.get(c["src"], 0) \
                    if c["dst"] == me and c["purpose"] == "control" \
                    else 0
                if skew > max(0.01 * max(sent, recv), 4096 + slack):
                    violations.append({
                        "src": c["src"], "dst": c["dst"],
                        "purpose": c["purpose"], "sent": sent,
                        "recv": recv, "skew": skew})
            vol = sent if sent is not None else (recv or 0)
            c["gb"] = round(vol / float(1 << 30), 6)
            c["rate_bps"] = round(c["rate_bps"], 1)
            purpose_totals[c["purpose"]] = \
                purpose_totals.get(c["purpose"], 0) + vol
            links[(c["src"], c["dst"])] = \
                links.get((c["src"], c["dst"]), 0) + vol
        top = [{"src": s, "dst": d, "bytes": b,
                "gb": round(b / float(1 << 30), 6)}
               for (s, d), b in sorted(links.items(),
                                       key=lambda kv: -kv[1])[:10]]
        budgets = {node: cur.get("budgets", {})
                   for node, (cur, _p) in samples.items()
                   if cur.get("budgets")}
        rows = sorted(cells.values(),
                      key=lambda c: -(c["sent_bytes"]
                                      if c["sent_bytes"] is not None
                                      else (c["recv_bytes"] or 0)))
        return {"ts": time.time(), "leader": self.url(),
                "nodes": sorted(samples),
                "purposes": {p: {"bytes": b,
                                 "gb": round(b / float(1 << 30), 6)}
                             for p, b in sorted(purpose_totals.items(),
                                                key=lambda kv:
                                                -kv[1])},
                "cells": rows, "top_talkers": top, "budgets": budgets,
                "conservation": {"paired_cells": paired,
                                 "ok": not violations,
                                 "violations": violations}}

    def _cluster_device(self, query: dict, body: bytes) -> dict:
        """GET /cluster/device — the device roofline rollup: every
        node's heartbeat-carried kernel rows merged into one cluster
        table keyed by (kernel, codec, dtype, geometry), per-node
        pipeline occupancy with collapse verdicts, and this master's
        own probed peaks.  ?codec= / ?kernel= filter the table."""
        from ..stats import roofline as _roofline
        if not self.is_leader():
            return self._proxy_to_leader("/cluster/device", query,
                                         body, "GET")
        want_kernel = query.get("kernel", "")
        if want_kernel:
            _roofline.validate(want_kernel)
        want_codec = query.get("codec", "")
        with self.topo._lock:
            leaves = list(self.topo.leaves())
        nodes: dict[str, dict] = {}
        merged: dict[tuple, dict] = {}
        warnings: list[str] = []
        for dn in leaves:
            dev = getattr(dn, "device", None)
            if not dev:
                continue
            occ = dev.get("occupancy") or {}
            nodes[dn.url()] = {"ts": dev.get("ts"),
                               "occupancy": occ,
                               "kernels": dev.get("kernels", [])}
            if occ.get("any_collapsed"):
                slow = [k for k, v in
                        (occ.get("collapsed") or {}).items() if v]
                warnings.append(
                    f"{dn.url()}: device occupancy collapsed on "
                    f"{','.join(sorted(slow)) or 'pipeline'}")
            for row in dev.get("kernels", []):
                if want_kernel and row["kernel"] != want_kernel:
                    continue
                if want_codec and row["codec"] != want_codec:
                    continue
                key = (row["kernel"], row["codec"], row["dtype"],
                       row["geometry"])
                m = merged.setdefault(key, {
                    "kernel": key[0], "codec": key[1],
                    "dtype": key[2], "geometry": key[3], "count": 0,
                    "seconds": 0.0, "bytes": 0, "work": 0,
                    "achieved_p50": None, "nodes": 0})
                m["count"] += row.get("count", 0)
                m["seconds"] = round(
                    m["seconds"] + row.get("seconds", 0.0), 6)
                m["bytes"] += row.get("bytes", 0)
                m["work"] += row.get("work", 0)
                m["nodes"] += 1
                p50 = row.get("achieved_p50")
                if p50 is not None:
                    # Worst node's median: the headline should surface
                    # the laggard, not average it away.
                    cur = m["achieved_p50"]
                    m["achieved_p50"] = p50 if cur is None \
                        else min(cur, p50)
        # In-process multi-role stacks run kernels in the master
        # process itself; fold the local ledger in under our own url.
        local = _roofline.LEDGER.heartbeat_view()
        if local["kernels"] and self.url() not in nodes:
            nodes[self.url()] = {"ts": time.time(),
                                 "occupancy": local["occupancy"],
                                 "kernels": local["kernels"]}
        table = sorted(merged.values(),
                       key=lambda m: (-m["seconds"], m["kernel"]))
        return {"ts": time.time(), "leader": self.url(),
                "peaks": _roofline.probe_peaks(),
                "nodes": nodes, "kernels": table,
                "warnings": warnings}

    def _cluster_lifecycle(self, query: dict, body: bytes) -> dict:
        """GET /cluster/lifecycle — the daemon's rules, scan history,
        and recent actions (the shell's cluster.lifecycle)."""
        if not self.is_leader():
            return self._proxy_to_leader("/cluster/lifecycle", query,
                                         body, "GET")
        return self.lifecycle.status()

    def _cluster_lifecycle_run(self, query: dict, body: bytes) -> dict:
        """POST /cluster/lifecycle/run — one synchronous policy scan
        (the shell's `cluster.lifecycle run`; tests drive the daemon
        through this instead of waiting out -lifecycle.interval)."""
        if not self.is_leader():
            return self._proxy_to_leader("/cluster/lifecycle/run",
                                         query, body, "POST")
        return self.lifecycle.scan_once()

    def _cluster_repair(self, query: dict, body: bytes) -> dict:
        """GET /cluster/repair — durability autopilot status: queue,
        in-flight repairs with per-repair phase, fresh scan (dry-run
        plan with hysteresis/suppression annotations), history tail,
        MTTR histogram."""
        if not self.is_leader():
            return self._proxy_to_leader("/cluster/repair", query,
                                         b"", "GET")
        return self.repair.status()

    def _cluster_repair_run(self, query: dict, body: bytes) -> dict:
        """POST /cluster/repair/run — one synchronous repair drain
        (the shell's `cluster.repair run` / `volume.fix.replication`;
        tests drive the daemon through this instead of waiting out
        hysteresis).  Body may carry {"kinds": ["replicate"|"ec"]}."""
        if not self.is_leader():
            return self._proxy_to_leader("/cluster/repair/run",
                                         query, body, "POST")
        kinds = None
        if body:
            kinds = json.loads(body).get("kinds")
        return self.repair.run_now(kinds=kinds)

    def _cluster_repair_switch(self, query: dict, body: bytes,
                               pause: bool) -> dict:
        """POST /cluster/repair/pause|resume — runtime governor (pause
        before risky maintenance the drain fence can't see)."""
        path = "/cluster/repair/" + ("pause" if pause else "resume")
        if not self.is_leader():
            return self._proxy_to_leader(path, query, body, "POST")
        return self.repair.pause() if pause else self.repair.resume()

    def _healthz(self, query: dict, body: bytes):
        """GET /cluster/healthz — 200/503 for load balancers, JSON
        detail for humans.  A follower answers for itself: 200 while a
        leader is known (it can proxy), 503 when the cluster is
        leaderless."""
        if not self.is_leader():
            leader = self.raft.leader()
            return (200 if leader else 503,
                    {"healthy": bool(leader), "is_leader": False,
                     "leader": leader,
                     "problems": [] if leader else ["no leader elected"]})
        ok, doc = self.health_report()
        return (200 if ok else 503, doc)

    def _cluster_events(self, query: dict, body: bytes):
        """GET /cluster/events — master-side aggregation into one
        cluster timeline: this process's journal merged with every
        registered data node's /debug/events, deduplicated by
        (journal token, seq) so roles sharing an in-process journal
        are not double-counted."""
        import urllib.parse

        from ..events import JOURNAL, TYPES
        type_ = query.get("type", "")
        if type_ and type_ not in TYPES:
            raise rpc.RpcError(400, f"unknown event type {type_!r}")
        severity = query.get("severity", "")
        try:
            since = float(query.get("since", 0) or 0)
            limit = int(query.get("limit", 0) or 0)
        except ValueError:
            raise rpc.RpcError(400, "since/limit must be numbers") \
                from None
        fwd = {k: v for k, v in (("type", type_),
                                 ("since", query.get("since", "")),
                                 ("severity", severity)) if v}
        qs = urllib.parse.urlencode(fwd)
        merged: dict[tuple, dict] = {}
        for ev in JOURNAL.snapshot(type_=type_, since=since,
                                   severity=severity):
            merged[(JOURNAL.token, ev["seq"])] = ev
        # Fan the per-node fetches out: during an incident (exactly
        # when this timeline is being polled) unreachable nodes are
        # likely, and N serial 5s connect timeouts would stall the
        # handler thread for the whole window.
        nodes = list(self.topo.leaves())

        def _fetch(dn):
            url = f"http://{dn.url()}/debug/events" \
                + (f"?{qs}" if qs else "")
            try:
                out = rpc.call(url, timeout=5.0)
                return dn, out if isinstance(out, dict) else None
            except Exception:  # noqa: BLE001 — endpoint off / node gone
                return dn, None

        results = []
        threads = []
        for dn in nodes:
            th = threading.Thread(
                target=lambda d=dn: results.append(_fetch(d)))
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        reached, failed = 1, 0
        for dn, out in results:
            if out is None:
                failed += 1
                continue
            reached += 1
            token = out.get("token", dn.url())
            for ev in out.get("events", []):
                merged.setdefault((token, ev.get("seq", 0)), ev)
        events = sorted(merged.values(), key=lambda e: e["ts"])
        if limit > 0:
            events = events[-limit:]
        return {"events": events, "servers_reached": reached,
                "servers_failed": failed}

    def _vol_list(self, query: dict, body: bytes) -> dict:
        """Detailed topology dump (master VolumeList RPC): every node with
        its full per-volume info and EC shard bits — the shell's view."""
        if not self.is_leader():
            return self._proxy_to_leader("/vol/list", query, body, "GET")
        dcs = []
        with self.topo._lock:  # heartbeats mutate these dicts concurrently
            for dc in list(self.topo.children.values()):
                racks = []
                for rack in list(dc.children.values()):
                    nodes = []
                    for dn in list(rack.children.values()):
                        nodes.append({
                            "id": dn.id, "url": dn.url(),
                            "public_url": dn.public_url,
                            "max_volume_count": dn.max_volume_count,
                            "volumes": [vinfo_to_dict(v)
                                        for v in list(dn.volumes.values())],
                            "ec_shards": [
                                {"id": vid, "shard_bits": bits,
                                 "codec": self.topo.ec_codec(vid)}
                                for vid, bits in dn.ec_shards.items()],
                        })
                    racks.append({"id": rack.id, "nodes": nodes})
                dcs.append({"id": dc.id, "racks": racks})
        return {"topology": {"data_centers": dcs},
                "volume_size_limit": self.topo.volume_size_limit}

    def _admin_lease(self, query: dict, body: bytes) -> dict:
        """LeaseAdminToken: grant/renew the exclusive maintenance lock."""
        if not self.is_leader():
            return self._proxy_to_leader("/admin/lease", query, body)
        req = json.loads(body) if body else {}
        name = req.get("name", "shell")
        prev = req.get("token")
        now = time.time()
        with self._admin_lock:
            held = (self._admin_token is not None
                    and now < self._admin_expires)
            if held and self._admin_token != prev:
                raise rpc.RpcError(
                    409, f"admin lock held by {self._admin_holder}")
            self._admin_token = prev or (hash((name, now)) & 0x7FFFFFFF)
            self._admin_holder = name
            self._admin_expires = now + self._admin_lock_ttl
            return {"token": self._admin_token,
                    "ttl": self._admin_lock_ttl}

    def _admin_release(self, query: dict, body: bytes) -> dict:
        if not self.is_leader():
            return self._proxy_to_leader("/admin/release", query, body)
        req = json.loads(body) if body else {}
        with self._admin_lock:
            if self._admin_token == req.get("token"):
                self._admin_token = None
                self._admin_holder = ""
                self._admin_expires = 0.0
        return {}

    # -- vacuum orchestration ------------------------------------------------

    def _vacuum(self, query: dict, body: bytes) -> dict:
        threshold = float(query.get("garbageThreshold",
                                    self.garbage_threshold))
        return {"vacuumed": self._run_vacuum_scan(threshold)}

    def _run_vacuum_scan(self, threshold: float) -> list[int]:
        """Ask each node for garbage ratios; vacuum replicas over threshold
        (reference: topology/topology_vacuum.go)."""
        vacuumed = []
        for dn in list(self.topo.leaves()):
            try:
                status = rpc.call_json(f"http://{dn.url()}/admin/status",
                                       payload={})
            except Exception:  # noqa: BLE001
                continue
            for v in status.get("volumes", []):
                if v.get("garbage_ratio", 0) > threshold:
                    try:
                        rpc.call_json(
                            f"http://{dn.url()}/admin/vacuum",
                            payload={"volume": v["id"]})
                        vacuumed.append(v["id"])
                    except rpc.RpcError:
                        pass
        return vacuumed

    # -- filer metadata-HA plane (shard map + filer registry) ----------------

    def _load_shard_map(self) -> None:
        if not self._shard_map_path:
            return
        try:
            with open(self._shard_map_path) as f:
                doc = json.load(f)
            self._shard_map = {int(k): v
                               for k, v in doc.get("shards",
                                                   {}).items()}
            self._shard_map_version = int(doc.get("version", 0))
            if not self.filer_shards:
                self.filer_shards = int(doc.get("num_shards", 0))
        except (OSError, ValueError):
            pass

    def _store_shard_map(self) -> None:
        """Atomic tmp+fsync+rename: a restart must never regress an
        epoch (that would un-fence a deposed primary)."""
        if not self._shard_map_path:
            return
        import os
        tmp = f"{self._shard_map_path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"version": self._shard_map_version,
                           "num_shards": self.filer_shards,
                           "shards": {str(k): v for k, v in
                                      self._shard_map.items()}}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._shard_map_path)
        except OSError:
            pass

    def _shard_map_doc(self) -> dict:
        return {"num_shards": self.filer_shards,
                "version": self._shard_map_version,
                "shards": {str(k): v
                           for k, v in self._shard_map.items()}}

    def _filer_fresh_cutoff(self) -> float:
        return time.time() - 2 * self.topo.pulse_seconds

    def _live_filers(self) -> list[str]:
        cutoff = self._filer_fresh_cutoff()
        return sorted(u for u, row in self._filers.items()
                      if row.get("last_seen", 0) >= cutoff)

    def _filer_heartbeat(self, query: dict, body: bytes):
        """Filer registration + pulse (the volume-server /heartbeat
        analog).  The response carries the shard map when the plane is
        armed — map distribution rides the beat, no extra poll."""
        if not self.is_leader():
            return {"leader": self.raft.leader(), "is_leader": False}
        hb = json.loads(body or b"{}")
        url = hb.get("url", "")
        if not url:
            raise rpc.RpcError(400, "filer heartbeat without url")
        with self._filer_lock:
            known = url in self._filers
            self._filers[url] = {
                "url": url, "last_seen": time.time(),
                "signature": hb.get("signature", 0),
                "shards": hb.get("shards", {}),
            }
            if not known:
                from ..events import emit as emit_event
                emit_event("heartbeat.recovered", node=url,
                           role="filer")
            if self.filer_shards > 0:
                self._assign_filer_shards()
                return {"is_leader": True, "pulse_seconds":
                        self.topo.pulse_seconds, **self._shard_map_doc()}
        return {"is_leader": True,
                "pulse_seconds": self.topo.pulse_seconds}

    def _assign_filer_shards(self) -> None:
        """Round-robin unowned shards over the live fleet and keep
        follower sets current.  Runs under _filer_lock.  Never touches
        a shard whose primary is alive — reassignment of dead
        primaries is the sweep's job (promotion needs the
        most-caught-up follower, not the next in rotation)."""
        live = self._live_filers()
        if not live:
            return
        changed = False
        for k in range(self.filer_shards):
            row = self._shard_map.get(k)
            if row is None or not row.get("primary"):
                primary = live[k % len(live)]
                row = {"primary": primary,
                       "epoch": (row or {}).get("epoch", 0) + 1,
                       "followers": [u for u in live
                                     if u != primary][:2]}
                self._shard_map[k] = row
                changed = True
                continue
            followers = [u for u in live
                         if u != row["primary"]][:2]
            if set(followers) - set(row.get("followers", [])):
                # Grow-only refresh: new fleet members join as
                # followers; members missing a beat are NOT dropped
                # here (the sweep owns death) — flapping would churn
                # the sync set.
                row["followers"] = sorted(
                    set(row.get("followers", [])) | set(followers))
                changed = True
        if changed:
            self._shard_map_version += 1
            self._store_shard_map()

    def _sweep_dead_filers(self) -> None:
        """Failover: a shard whose primary missed 2 pulses promotes
        the most-caught-up live follower at epoch+1 (the epoch fence
        makes the deposed primary's late pushes refusable)."""
        if self.filer_shards <= 0:
            return
        from ..events import emit as emit_event
        with self._filer_lock:
            live = set(self._live_filers())
            for url in sorted(set(self._filers) - live):
                if not self._filers[url].get("_mourned"):
                    self._filers[url]["_mourned"] = True
                    emit_event("heartbeat.lost", node=url,
                               severity="warn", role="filer")
            changed = False
            lease_cutoff = time.time() - 3 * self.topo.pulse_seconds
            for k, row in sorted(self._shard_map.items()):
                primary = row.get("primary")
                if primary in live:
                    continue
                prow = self._filers.get(primary)
                if prow and prow.get("last_seen", 0) >= lease_cutoff:
                    # Dead to us, but its primary lease (renewed for
                    # 3 pulses at its last heartbeat) may still be
                    # live behind a partition — promoting now could
                    # produce two acking primaries.  Wait it out.
                    continue
                # Most-caught-up follower: ask each candidate for its
                # LIVE journal position — the heartbeat rows can be a
                # pulse stale, and promoting the wrong follower would
                # lose every op acked since its beat.  Fall back to
                # the heartbeat row when a candidate can't answer.
                from ..fault import registry as _fault
                best, best_seq = None, -1
                for f in row.get("followers", []):
                    if f not in live:
                        continue
                    try:
                        if _fault.ARMED:
                            _fault.hit("wan.partition", peer=f,
                                       shard=k)
                        st = rpc.call(
                            f + f"/.meta/shard/status?shard={k}",
                            timeout=2.0)
                        seq = int(st.get("last_seq", 0))
                    except Exception:  # noqa: BLE001 — stale fallback
                        srow = self._filers[f].get("shards",
                                                   {}).get(str(k), {})
                        seq = int(srow.get("last_seq", 0))
                    if seq > best_seq:
                        best, best_seq = f, seq
                if best is None:
                    continue  # contested: fails closed until a
                    #           follower comes back
                old = primary
                row["primary"] = best
                row["epoch"] = int(row.get("epoch", 0)) + 1
                row["followers"] = [u for u in live if u != best]
                changed = True
                emit_event("shard.promote", node=best, severity="warn",
                           shard=k, old_primary=old or "",
                           epoch=row["epoch"], last_seq=best_seq)
                self._push_shard_acquire(k, row,
                                         self._shard_map_version + 1)
            if changed:
                self._shard_map_version += 1
                self._store_shard_map()

    def _push_shard_acquire(self, shard: int, row: dict,
                            version: int) -> None:
        """Best-effort immediate acquire push — the next heartbeat
        map is the backstop if this misses."""
        from ..fault import registry as _fault
        try:
            if _fault.ARMED:
                _fault.hit("wan.partition", peer=row["primary"],
                           shard=shard)
            rpc.call_json(row["primary"] + "/.meta/shard/acquire",
                          payload={"shard": shard,
                                   "epoch": row["epoch"],
                                   "followers": row["followers"],
                                   "version": version},
                          timeout=5.0)
        except Exception:  # noqa: BLE001
            pass

    def _cluster_filer_shards(self, query: dict, body: bytes):
        with self._filer_lock:
            cutoff = self._filer_fresh_cutoff()
            filers = [{"url": u,
                       "alive": row.get("last_seen", 0) >= cutoff,
                       "age_seconds": round(
                           time.time() - row.get("last_seen", 0), 3),
                       "shards": row.get("shards", {})}
                      for u, row in sorted(self._filers.items())]
            return {**self._shard_map_doc(), "filers": filers}

    def _filer_shard_move(self, query: dict, body: bytes):
        """filer.shards.move: demote-first primary transfer.  The old
        primary stops acking BEFORE the new one exists anywhere;
        mid-move the shard is contested and fails closed (the
        lease.py begin_move stance)."""
        if not self.is_leader():
            return self._proxy_to_leader("/cluster/filer/shards/move",
                                         query, body)
        req = json.loads(body or b"{}")
        shard = int(req.get("shard", -1))
        to = req.get("to", "")
        from ..events import emit as emit_event
        with self._filer_lock:
            row = self._shard_map.get(shard)
            if row is None:
                raise rpc.RpcError(404, f"no such shard {shard}")
            if to not in self._live_filers():
                raise rpc.RpcError(
                    409, f"target filer {to} not registered/alive")
            if to == row.get("primary"):
                return {"moved": False, "already": True, **row}
            old = row.get("primary")
            if old:
                from ..fault import registry as _fault
                try:
                    if _fault.ARMED:
                        _fault.hit("wan.partition", peer=old,
                                   shard=shard)
                    rpc.call_json(old + "/.meta/shard/demote",
                                  payload={"shard": shard,
                                           "epoch": row["epoch"]},
                                  timeout=5.0)
                except Exception:  # noqa: BLE001 — unreachable old
                    # primary.  Demote-first fails CLOSED (the geo
                    # lease-move stance): while its lease may still
                    # be live behind a partition, transferring the
                    # shard could produce two acking primaries.
                    # Once the lease TTL has surely lapsed, the
                    # epoch bump below fences its pushes instead.
                    last = self._filers.get(old, {}).get("last_seen",
                                                         0)
                    if last >= time.time() - \
                            3 * self.topo.pulse_seconds:
                        raise rpc.RpcError(
                            503, f"shard {shard} NOT moved: old "
                            f"primary {old} unreachable and its "
                            "lease may still be live; retry after "
                            "the lease TTL") from None
            row["primary"] = to
            row["epoch"] = int(row.get("epoch", 0)) + 1
            row["followers"] = [u for u in self._live_filers()
                                if u != to]
            self._shard_map_version += 1
            self._store_shard_map()
            emit_event("shard.move", node=to, shard=shard,
                       old_primary=old or "", epoch=row["epoch"])
            self._push_shard_acquire(shard, row,
                                     self._shard_map_version)
            return {"moved": True, "shard": shard,
                    "old_primary": old or "", **row}

    def filer_health_rows(self) -> tuple[list[dict], list[str]]:
        """(rows, problems) for /cluster/healthz + cluster.check."""
        with self._filer_lock:
            cutoff = self._filer_fresh_cutoff()
            rows, problems = [], []
            for u, row in sorted(self._filers.items()):
                alive = row.get("last_seen", 0) >= cutoff
                nprim = sum(
                    1 for r in self._shard_map.values()
                    if r.get("primary") == u)
                rows.append({
                    "url": u, "alive": alive,
                    "age_seconds": round(
                        time.time() - row.get("last_seen", 0), 3),
                    "shards_primary": nprim})
                if not alive:
                    problems.append(f"filer {u} missed heartbeats "
                                    "(last seen "
                                    f"{rows[-1]['age_seconds']}s ago)")
            for k in range(self.filer_shards):
                row = self._shard_map.get(k)
                if row is None or not row.get("primary") or \
                        row["primary"] not in {
                            r["url"] for r in rows if r["alive"]}:
                    problems.append(
                        f"filer shard {k} has no live primary "
                        "(writes fail closed)")
            return rows, problems

    def _sweep_loop(self) -> None:
        """Dead-node detection (CollectDeadNodeAndFullVolumes)."""
        while not self._stop.wait(self.topo.pulse_seconds):
            if self.raft is not None and not self.is_leader():
                # Deposed: heartbeats now land on the new leader, so
                # our watch streams would heartbeat forever without
                # deltas — end them; clients redial and find the
                # leader.
                with self._watchers_lock:
                    doomed, self._watchers = self._watchers, []
                for w in doomed:
                    try:
                        w.end()
                    except Exception:  # noqa: BLE001
                        pass
                continue
            self._sweep_dead_nodes()
            self._sweep_dead_filers()
            # Durability autopilot rides the sweep cadence: scan for
            # redundancy deficits the sweep just created (or healed)
            # and drive the repair queue.  tick() never raises.
            self.repair.tick()

    def _sweep_dead_nodes(self) -> None:
        """One dead-node collection round — the sweep loop's body,
        callable directly so tests can drive heartbeat.lost through the
        real path without waiting out a pulse interval."""
        from ..events import emit as emit_event
        from ..trace import root_span
        for dn in self.topo.collect_dead_nodes():
            with root_span("master.dead_node_sweep", "master",
                           node=dn.url()):
                # Snapshot what the node held BEFORE unregistering:
                # unregister_ec_shards drains dn.ec_shards, and both
                # the journal record and the location broadcast must
                # report the pre-death holdings.
                held_volumes = sorted(dn.volumes)
                held_ec = sorted(dn.ec_shards)
                self.topo.unregister_data_node(dn)
                self._hb_known.discard(dn.url())
                emit_event("heartbeat.lost", node=dn.url(),
                           severity="warn",
                           age_seconds=round(
                               time.time() - dn.last_seen, 3),
                           volumes=len(held_volumes),
                           ec_shards=len(held_ec))
                # Dead node: every vid it held needs re-lookup.
                vids = sorted(set(held_volumes) | set(held_ec))
                if vids:
                    self._broadcast_locations({
                        "url": dn.url(), "public_url": dn.public_url,
                        "new_vids": [], "deleted_vids": vids})
