"""Durability autopilot: risk-ranked automatic re-replication and EC
rebuild after node loss.

The master's dead-node sweep (``_sweep_dead_nodes``) unregisters a lost
node and broadcasts the vanished vids — and then the cluster just sits
degraded, one more failure away from data loss, until an operator runs
``volume.scrub -repair`` or ``ec.rebuild`` by hand.  At warehouse scale
the window between loss and repair is exactly the MTTDL term a human
cannot bound, so this daemon closes the loop: every sweep it joins the
live topology against the *declared* redundancy (``ReplicaPlacement``
copy counts for replicated volumes, codec geometry for EC stripes),
ranks every deficit by surviving redundancy, and drives the queue to
empty.

Design rules, in the order they matter:

- **Risk first.**  A volume on its last replica and a stripe at its
  decode minimum sort ahead of everything else (risk = number of extra
  failures survivable; 0 drains first).
- **Hysteresis.**  A deficit is only enqueued after it has persisted
  for ``delay`` seconds (default 2x the dead-sweep threshold, i.e. 4x
  the heartbeat pulse).  Transient blips — a rolling restart that beats
  the sweep, a brief partition — heal themselves without a single byte
  of repair traffic.
- **Planned maintenance never repairs.**  A node that said goodbye
  (drain) is fenced: every vid it held is suppressed until a new
  generation of that node registers.  Rolling restarts are silent.
- **Resurrection fencing.**  A dead node coming back cancels its queued
  repairs (the deficit heals, the reconcile pass drops the task).  If a
  repair already *landed* when the original holder returns, the volume
  is over-replicated; the dedupe pass trims back to placement,
  newest-placement-first, and never below the declared copy count.
- **Budget governance.**  All repair traffic rides the low-priority
  admission lane tagged ``repair.fetch`` / ``ec.gather``, so an armed
  ``-flows.budget`` paces it below user traffic; ``concurrent`` bounds
  parallel repairs and the daemon can be paused/resumed at runtime.
- **Crash safety without a ledger.**  There is no repair journal to
  corrupt: on leader change the queue is rebuilt from topology truth by
  the next scan.  An executor dying mid-copy leaves only ``.part``
  files the receiving volume server reaps at startup.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..codecs import get_codec
from ..core.replica_placement import ReplicaPlacement
from ..events import emit as emit_event
from ..stats.metrics import Counter, Histogram
from ..storage.store import VolumeInfo
from ..topology.volume_growth import VolumeGrowth
from ..trace import root_span
from ..utils import glog
from . import rpc

repairs_total = Counter(
    "SeaweedFS_repairs_total",
    "Completed automatic repair operations by kind and outcome.",
    ("kind", "outcome"))

REPAIR_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                  120.0, 300.0)

repair_seconds = Histogram(
    "SeaweedFS_repair_seconds",
    "Time from degradation detection to converged redundancy (MTTR).",
    ("kind",), buckets=REPAIR_BUCKETS)


class _Canceled(Exception):
    """Raised inside an executor when the deficit healed under it."""


@dataclass
class RepairTask:
    kind: str                # "replicate" | "ec"
    vid: int
    collection: str = ""
    risk: int = 0            # extra failures survivable; 0 drains first
    have: int = 0
    want: int = 0
    missing: tuple = ()      # EC: missing shard ids
    codec: str = ""
    replication: str = ""
    ttl: int = 0
    degraded_since: float = 0.0
    phase: str = "queued"
    started: float = 0.0
    error: str = ""

    @property
    def key(self) -> tuple:
        return (self.kind, self.vid)

    def doc(self) -> dict:
        d = {"kind": self.kind, "volume": self.vid, "risk": self.risk,
             "have": self.have, "want": self.want, "phase": self.phase}
        if self.collection:
            d["collection"] = self.collection
        if self.kind == "ec":
            d["codec"] = self.codec
            d["missing"] = list(self.missing)
        else:
            d["replication"] = self.replication
        if self.started:
            d["running_seconds"] = round(time.time() - self.started, 3)
        if self.error:
            d["error"] = self.error
        return d


class RepairDaemon:
    """Leader-only repair orchestrator, ticked by the master's sweep
    loop.  All public entry points are safe on non-leaders (no-ops);
    ``run_now`` is the synchronous operator path (shell ``cluster.repair
    run`` / ``volume.fix.replication``) and works even while disarmed
    or paused — an explicit command outranks the autopilot switch."""

    MTTR_KEEP = 200
    HISTORY_KEEP = 100

    def __init__(self, master, enabled: bool = False,
                 delay: float | None = None, concurrent: int = 2):
        self.master = master
        self.enabled = enabled
        # Hysteresis default: 2x the dead-sweep threshold (itself 2x
        # the pulse) so a node must miss the sweep AND stay gone.
        self.delay = (4.0 * master.topo.pulse_seconds
                      if delay is None else delay)
        self.concurrent = max(1, concurrent)
        self.paused = False
        self._lock = threading.RLock()
        self._wake = threading.Condition(self._lock)
        self._queue: list[RepairTask] = []
        self._inflight: dict[tuple, RepairTask] = {}
        self._degraded_since: dict[tuple, float] = {}
        # node_key -> vids it held when it said goodbye (drain fence)
        self._goodbye_held: dict[str, set[int]] = {}
        # vid -> [(placed_at, node_url)] — dedupe trims newest first
        self._placed: dict[int, list[tuple[float, str]]] = {}
        self._dedupe_pending = False
        self._mttr: list[tuple[str, float]] = []
        self._history: list[dict] = []
        self._mesh = None

    # ------------------------------------------------------------------
    # lifecycle hooks (called from master heartbeat / goodbye paths)

    def node_goodbyed(self, node_key: str, vids: set[int]) -> None:
        """Drain fence: vids held by a goodbyed node never enqueue."""
        with self._lock:
            self._goodbye_held[node_key] = set(vids)

    def node_returned(self, node_key: str) -> None:
        """A known-dead or goodbyed node re-registered.  Lift the drain
        fence and schedule a dedupe pass — NOT inline, because
        heartbeat.recovered fires before the returning node's volume
        list has been applied; the next tick sees settled topology."""
        with self._lock:
            self._goodbye_held.pop(node_key, None)
            self._dedupe_pending = True

    # ------------------------------------------------------------------
    # scanning

    def scan(self) -> list[RepairTask]:
        """Join topology truth against declared redundancy.  Returns
        candidate tasks sorted most-at-risk first.  Pure read — no
        queue mutation, usable for dry-run plans."""
        topo = self.master.topo
        out: list[RepairTask] = []
        with topo._lock:
            for cname, coll in topo.collections.items():
                for layout in coll.layouts.values():
                    want = layout.rp.copy_count()
                    if want <= 1:
                        continue
                    for vid, locs in layout.vid2location.items():
                        have = len(locs)
                        if 0 < have < want:
                            out.append(RepairTask(
                                kind="replicate", vid=vid,
                                collection=cname, risk=have - 1,
                                have=have, want=want,
                                replication=str(layout.rp),
                                ttl=layout.ttl.to_uint32()))
            for vid, loc in topo.ec_shard_map.items():
                codec = get_codec(loc.codec)
                present = sorted(
                    sid for sid, dns in loc.locations.items() if dns)
                missing = sorted(
                    set(range(codec.total_shards)) - set(present))
                if not missing or not present:
                    continue
                try:
                    codec.repair_plan(tuple(present), list(missing))
                except Exception:
                    continue  # unrecoverable — nothing to do
                out.append(RepairTask(
                    kind="ec", vid=vid, collection=loc.collection,
                    risk=max(0, len(present) - codec.data_shards),
                    have=len(present), want=codec.total_shards,
                    missing=tuple(missing), codec=loc.codec))
        out.sort(key=lambda t: (t.risk, t.vid, t.kind))
        return out

    def _suppressed(self, task: RepairTask) -> bool:
        """True while the deficit is explained by a drained node whose
        goodbye fence is still standing (no new generation yet)."""
        live = getattr(self.master, "_goodbye_epochs", {})
        stale = [nk for nk in self._goodbye_held if nk not in live]
        for nk in stale:
            self._goodbye_held.pop(nk, None)
        return any(task.vid in vids
                   for vids in self._goodbye_held.values())

    def reconcile(self, now: float | None = None) -> None:
        """Diff the scan against the queue: start hysteresis clocks,
        enqueue ripe deficits, cancel healed ones."""
        now = time.time() if now is None else now
        cands = {t.key: t for t in self.scan()}
        with root_span("master.repair_reconcile", "master"), \
                self._lock:
            for key in list(self._degraded_since):
                if key not in cands and key not in self._inflight:
                    self._degraded_since.pop(key)
            healed = [t for t in self._queue if t.key not in cands]
            for t in healed:
                self._queue.remove(t)
                repairs_total.inc(kind=t.kind, outcome="canceled")
                emit_event("repair.cancel", node=self.master.url(),
                           kind=t.kind, volume=t.vid, reason="healed")
            for key, t in sorted(cands.items(),
                                 key=lambda kv: (kv[1].risk,
                                                 kv[1].vid)):
                since = self._degraded_since.setdefault(key, now)
                if (key in self._inflight
                        or any(q.key == key for q in self._queue)
                        or self._suppressed(t)
                        or now - since < self.delay):
                    continue
                t.degraded_since = since
                self._enqueue(t)

    def _enqueue(self, t: RepairTask) -> None:
        self._queue.append(t)
        self._queue.sort(key=lambda x: (x.risk, x.vid, x.kind))
        emit_event("repair.plan", node=self.master.url(),
                   severity="warn", kind=t.kind, volume=t.vid,
                   risk=t.risk, have=t.have, want=t.want,
                   missing=len(t.missing), collection=t.collection)

    # ------------------------------------------------------------------
    # driving

    def tick(self) -> None:
        """Called from the master's sweep loop every pulse.  Must never
        raise — a repair bug must not take down the dead-node sweep."""
        try:
            if not self.enabled or not self.master.is_leader():
                return
            self.reconcile()
            if self._dedupe_pending:
                with self._lock:
                    self._dedupe_pending = False
                self.dedupe()
            if not self.paused:
                self._start_workers()
        except Exception as e:  # noqa: BLE001
            glog.warningf("repair tick failed: %s", e)

    def _start_workers(self) -> None:
        with self._lock:
            while self._queue and len(self._inflight) < self.concurrent:
                t = self._queue.pop(0)
                self._inflight[t.key] = t
                threading.Thread(target=self._execute, args=(t,),
                                 daemon=True,
                                 name=f"repair-{t.kind}-{t.vid}").start()

    def run_now(self, kinds: list[str] | None = None,
                timeout: float = 600.0) -> dict:
        """Synchronous drain for the operator surfaces.  Ignores the
        hysteresis delay and the pause switch (an explicit command),
        still honours the drain fence and dedupe invariants."""
        cands = self.scan()
        if kinds:
            cands = [t for t in cands if t.kind in kinds]
        with root_span("master.repair_run", "master"), self._lock:
            for t in cands:
                if (t.key in self._inflight
                        or any(q.key == t.key for q in self._queue)
                        or self._suppressed(t)):
                    continue
                t.degraded_since = self._degraded_since.setdefault(
                    t.key, time.time())
                self._enqueue(t)
            todo = [t.key for t in self._queue] + list(self._inflight)
            deadline = time.monotonic() + timeout
            while self._queue or self._inflight:
                self._start_workers_locked()
                if not self._wake.wait(timeout=1.0) \
                        and time.monotonic() > deadline:
                    break
        trimmed = self.dedupe()
        with self._lock:
            results = [h for h in self._history
                       if (h["kind"], h["volume"]) in
                       {(k[0], k[1]) for k in todo}]
        return {"ran": len(todo), "results": results,
                "trimmed": trimmed}

    def _start_workers_locked(self) -> None:
        # run_now holds the lock; _start_workers re-acquires (RLock).
        self._start_workers()

    # ------------------------------------------------------------------
    # executors

    def _execute(self, t: RepairTask) -> None:
        with root_span("master.repair", "master", kind=t.kind,
                       volume=t.vid):
            self._execute_traced(t)

    def _execute_traced(self, t: RepairTask) -> None:
        t.phase = "running"
        t.started = time.time()
        emit_event("repair.start", node=self.master.url(),
                   kind=t.kind, volume=t.vid, risk=t.risk)
        outcome = "ok"
        try:
            if not self.master.is_leader():
                raise _Canceled("deposed")
            if t.kind == "replicate":
                self._replicate(t)
            else:
                self._rebuild_ec(t)
            t.phase = "done"
            mttr = time.time() - (t.degraded_since or t.started)
            repair_seconds.observe(mttr, kind=t.kind)
            emit_event("repair.finish", node=self.master.url(),
                       kind=t.kind, volume=t.vid,
                       seconds=round(time.time() - t.started, 3),
                       mttr_seconds=round(mttr, 3))
            with self._lock:
                self._mttr.append((t.kind, mttr))
                del self._mttr[:-self.MTTR_KEEP]
        except _Canceled as e:
            outcome = "canceled"
            t.phase = "canceled"
            t.error = str(e)
            emit_event("repair.cancel", node=self.master.url(),
                       kind=t.kind, volume=t.vid, reason=str(e))
        except Exception as e:  # noqa: BLE001
            outcome = "error"
            t.phase = "failed"
            t.error = str(e)
            glog.warningf("repair %s volume %d failed: %s",
                          t.kind, t.vid, e)
            emit_event("repair.cancel", node=self.master.url(),
                       severity="warn", kind=t.kind, volume=t.vid,
                       reason="error", error=str(e))
        finally:
            repairs_total.inc(kind=t.kind, outcome=outcome)
            with self._lock:
                self._inflight.pop(t.key, None)
                # Drop the hysteresis clock: success means healed; a
                # failure restarts the clock so retries are paced, not
                # hot-looped.
                self._degraded_since.pop(t.key, None)
                self._history.append(
                    {**t.doc(), "outcome": outcome,
                     "finished_at": time.time()})
                del self._history[:-self.HISTORY_KEEP]
                self._wake.notify_all()
                # Self-draining: a finishing executor pulls the next
                # queued task instead of waiting for the next tick —
                # otherwise queue drain is paced by the sweep interval
                # and MTTR inflates by pulse-multiples per task.
                if not self.paused:
                    self._start_workers()

    def _replicate(self, t: RepairTask) -> None:
        topo = self.master.topo
        locs = topo.lookup(t.collection, t.vid)
        if not locs:
            raise RuntimeError(f"volume {t.vid}: no surviving replica")
        rp = ReplicaPlacement.parse(t.replication or "000")
        if len(locs) >= rp.copy_count():
            raise _Canceled("healed")
        src = locs[0]
        target = self._pick_target(t.vid, locs, rp)
        t.phase = "copy"
        vinfo = src.volumes.get(t.vid)
        was_readonly = bool(vinfo and vinfo.read_only)
        # Freeze the source so the copied .dat/.idx pair is a
        # consistent point-in-time snapshot, checksum-verifiable.
        rpc.call_json(f"http://{src.url()}/admin/readonly",
                      payload={"volume": t.vid, "readonly": True})
        try:
            rpc.call_json(
                f"http://{target.url()}/admin/volume/receive",
                payload={"volume": t.vid, "collection": t.collection,
                         "source": src.url()},
                timeout=600.0)
        finally:
            if not was_readonly:
                try:
                    rpc.call_json(
                        f"http://{src.url()}/admin/readonly",
                        payload={"volume": t.vid, "readonly": False})
                except Exception:  # noqa: BLE001
                    glog.warningf("repair: could not unfreeze volume "
                                  "%d on %s", t.vid, src.url())
        t.phase = "register"
        # Optimistic registration (the receiver's heartbeat confirms):
        # mirrors _allocate_volume so lookups route immediately.
        v = VolumeInfo(
            id=t.vid, collection=t.collection,
            size=vinfo.size if vinfo else 0,
            file_count=vinfo.file_count if vinfo else 0,
            delete_count=vinfo.delete_count if vinfo else 0,
            deleted_byte_count=(vinfo.deleted_byte_count
                                if vinfo else 0),
            read_only=was_readonly,
            replica_placement=rp.to_byte(),
            ttl=t.ttl,
            compact_revision=(vinfo.compact_revision if vinfo else 0))
        topo.register_volume(v, target)
        with self._lock:
            self._placed.setdefault(t.vid, []).append(
                (time.time(), target.url()))
            del self._placed[t.vid][:-8]

    def _pick_target(self, vid: int, holders, rp: ReplicaPlacement):
        """Placement-aware target choice: prefer restoring the failure
        domain diversity the placement demands, then most free space,
        deterministic tiebreak."""
        topo = self.master.topo
        held_urls = {dn.url() for dn in holders}
        held_dcs = {dn.get_data_center().id for dn in holders}
        held_racks = {dn.get_rack().id for dn in holders}
        cands = []
        with topo._lock:
            for dn in topo.leaves():
                if dn.url() in held_urls:
                    continue
                if not VolumeGrowth._node_eligible(dn):
                    continue
                cands.append(dn)
        if not cands:
            raise RuntimeError(
                f"volume {vid}: no eligible repair target")

        def score(dn):
            new_dc = dn.get_data_center().id not in held_dcs
            new_rack = dn.get_rack().id not in held_racks
            diversity = 0
            if rp.diff_data_center_count and new_dc:
                diversity -= 2
            if rp.diff_rack_count and new_rack:
                diversity -= 1
            return (diversity, -dn.free_space(), dn.url())

        return min(cands, key=score)

    def _rebuild_ec(self, t: RepairTask) -> None:
        from ..parallel.cluster_rebuild import batch_rebuild, make_mesh
        t.phase = "rebuild"
        if self._mesh is None:
            self._mesh = make_mesh()
        env = _MasterEnv(self.master)
        lines = batch_rebuild(env, vids=[t.vid], mesh=self._mesh)
        if not any("rebuilt" in ln for ln in lines):
            raise RuntimeError(
                f"volume {t.vid}: rebuild produced no shards "
                f"({'; '.join(lines) or 'no output'})")

    # ------------------------------------------------------------------
    # dedupe (resurrection resolution)

    def dedupe(self) -> list[dict]:
        """Trim over-replicated volumes back to declared placement,
        newest-placement-first, never below copy count.  Returns the
        trim records (also journalled)."""
        topo = self.master.topo
        surplus: list[tuple[int, str, int, list]] = []
        with topo._lock:
            for cname, coll in topo.collections.items():
                for layout in coll.layouts.values():
                    want = layout.rp.copy_count()
                    for vid, locs in layout.vid2location.items():
                        if len(locs) > want:
                            surplus.append(
                                (vid, cname, want, list(locs)))
        trimmed: list[dict] = []
        if not surplus:
            return trimmed
        with root_span("master.repair_dedupe", "master"):
            self._dedupe_traced(surplus, trimmed)
        return trimmed

    def _dedupe_traced(self, surplus, trimmed) -> None:
        topo = self.master.topo
        for vid, cname, want, locs in surplus:
            with self._lock:
                recency = {url: ts for ts, url
                           in self._placed.get(vid, [])}
            # Newest placement first; never-placed (original holders)
            # sort last and survive.
            locs.sort(key=lambda dn: -recency.get(dn.url(), -1.0))
            for dn in locs[:len(locs) - want]:
                try:
                    rpc.call_json(
                        f"http://{dn.url()}/admin/delete_volume",
                        payload={"volume": vid})
                except Exception as e:  # noqa: BLE001
                    glog.warningf("dedupe: drop volume %d on %s "
                                  "failed: %s", vid, dn.url(), e)
                    continue
                v = dn.volumes.get(vid)
                if v is not None:
                    topo.unregister_volume(v, dn)
                repairs_total.inc(kind="dedupe", outcome="ok")
                rec = {"volume": vid, "collection": cname,
                       "node": dn.url(), "kept": want}
                trimmed.append(rec)
                emit_event("repair.finish", node=self.master.url(),
                           kind="dedupe", volume=vid,
                           trimmed_from=dn.url())

    # ------------------------------------------------------------------
    # surfaces

    def pause(self) -> dict:
        with self._lock:
            self.paused = True
        return {"paused": True}

    def resume(self) -> dict:
        with self._lock:
            self.paused = False
        return {"paused": False}

    def queue_depth_by_risk(self) -> dict:
        with self._lock:
            depths: dict[tuple, float] = {}
            for t in self._queue:
                k = (str(t.risk),)
                depths[k] = depths.get(k, 0.0) + 1.0
            return depths

    def status(self) -> dict:
        now = time.time()
        plan = []
        for t in self.scan():
            with self._lock:
                since = self._degraded_since.get(t.key)
                d = t.doc()
                d["degraded_for"] = round(now - since, 3) if since \
                    else 0.0
                d["suppressed"] = self._suppressed(t)
            plan.append(d)
        with self._lock:
            mttrs = [s for _, s in self._mttr]
            hist = {f"le_{b}": sum(1 for s in mttrs if s <= b)
                    for b in REPAIR_BUCKETS}
            return {
                "enabled": self.enabled,
                "paused": self.paused,
                "delay_seconds": self.delay,
                "concurrent": self.concurrent,
                "queue": [t.doc() for t in self._queue],
                "inflight": [t.doc()
                             for t in self._inflight.values()],
                "plan": plan,
                "history": self._history[-20:],
                "mttr": {
                    "count": len(mttrs),
                    "mean_seconds": (round(sum(mttrs) / len(mttrs), 3)
                                     if mttrs else 0.0),
                    "max_seconds": (round(max(mttrs), 3)
                                    if mttrs else 0.0),
                    "histogram": hist,
                },
            }


class _MasterEnv:
    """Duck-typed environment adapter so the master can drive the
    shell's codec-aware ``plan_rebuilds``/``batch_rebuild`` planner
    in-process (the planner normally runs against a CommandEnv)."""

    def __init__(self, master):
        self.master = master

    def data_nodes(self) -> list[dict]:
        topo = self.master.topo
        out = []
        with topo._lock:
            for dc in topo.children.values():
                for rack in dc.children.values():
                    for dn in rack.children.values():
                        out.append({
                            "url": dn.url(),
                            "dc": dc.id,
                            "rack": rack.id,
                            "max_volume_count":
                                dn.max_volume_count,
                            "volumes": [{"id": v.id}
                                        for v in dn.volumes.values()],
                            "ec_shards": [
                                {"id": vid, "shard_bits": bits,
                                 "codec": topo.ec_codec(vid)}
                                for vid, bits in dn.ec_shards.items()],
                        })
        return out

    def ec_shard_locations(self, vid: int) -> dict:
        locs = self.master.topo.lookup_ec_shards(vid)
        if not locs:
            return {}
        # Drop shard ids whose holder list emptied out (dead node
        # unregistered): the planner treats every KEY as a survivor,
        # so a lingering empty entry hides the very deficit we are
        # here to rebuild.
        return {sid: [dn.url() for dn in dns]
                for sid, dns in locs.locations.items() if dns}

    def ec_codec(self, vid: int) -> str:
        return self.master.topo.ec_codec(vid)

    def vs_call(self, url: str, path: str, payload=None,
                timeout: float = 120.0):
        return rpc.call_json(f"http://{url}{path}", payload=payload,
                             timeout=timeout)
