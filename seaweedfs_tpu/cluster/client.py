"""Client operations: assign / upload / download / delete, with a
volume-location cache — the wdclient + operation packages of the reference
(weed/wdclient/masterclient.go vidMap, weed/operation/).
"""

from __future__ import annotations

import threading
import time
import urllib.parse

from ..core import types as t
from ..netcore import splice as splice_mod
from ..stats import flows as _flows
from ..trace import current_traceparent
from . import resilience, rpc


def _grpc_trace_metadata():
    """traceparent as gRPC metadata — the gRPC analog of the header the
    HTTP plane injects in rpc._request (the server facade forwards it
    to the JSON handlers)."""
    tp = current_traceparent()
    return (("traceparent", tp),) if tp else None


class VidCache:
    """vid -> locations with TTL + round-robin reads (wdclient/vid_map.go)."""

    def __init__(self, ttl_seconds: float = 60.0):
        self.ttl = ttl_seconds
        self._m: dict[int, tuple[float, list[dict]]] = {}
        self._rr: dict[int, int] = {}
        self._lock = threading.Lock()

    def get(self, vid: int) -> list[dict] | None:
        with self._lock:
            hit = self._m.get(vid)
            if hit is None or time.time() - hit[0] > self.ttl:
                return None
            return hit[1]

    def put(self, vid: int, locations: list[dict]) -> None:
        with self._lock:
            self._m[vid] = (time.time(), locations)

    def forget(self, vid: int) -> None:
        with self._lock:
            self._m.pop(vid, None)

    def pick(self, vid: int) -> dict | None:
        locs = self.get(vid)
        if not locs:
            return None
        with self._lock:
            i = self._rr.get(vid, 0)
            self._rr[vid] = i + 1
        return locs[i % len(locs)]


class ProxiedBody:
    """Streaming volume→client relay for the filer's large-read proxy
    leg: wraps an open upstream GET whose body has NOT been read, and
    hands it to rpc._respond as a file-like payload.  On a plaintext
    downstream, _respond calls sendfile_to and the bytes move
    volume-socket → filer → client-socket kernel-side (netcore/splice);
    TLS or spliceless platforms take the buffered read() loop instead.
    Either way the filer never holds more than one window in memory."""

    def __init__(self, resp, conn, size: int):
        self._resp = resp
        self._conn = conn
        self.size = size
        # Instance attribute, not a method: rpc._respond probes with
        # getattr, and a TLS *upstream* (https volume leg) has no raw
        # fd to splice from — the attribute is simply absent then.
        if splice_mod.HAVE_SPLICE and conn.key[0] == "http":
            self.sendfile_to = self._splice_to

    def read(self, n: int = -1) -> bytes:
        return self._resp.read(n)

    def _splice_to(self, dst, note=None) -> None:
        resp, conn = self._resp, self._conn
        # Wire-flow attribution: spliced bytes bypass resp.read(), so
        # the client leg's "in" note (set by rpc._request) is fed here
        # with the same syscall totals the downstream "out" note gets.
        fin = resp.flow_note

        def _both(n: int) -> None:
            if note is not None:
                note(n)
            if fin is not None:
                fin(n)

        left = resp._remaining
        # The buffered reader that parsed the response head almost
        # always pulled the first body bytes along with it; one read1
        # empties that buffer (<= its 64KB size) without a raw recv,
        # then the rest moves straight off the socket fd.
        head = conn.rf.read1(min(left, 1 << 16)) if left > 0 else b""
        if head:
            splice_mod._write_all(dst.fileno(), head)
            left -= len(head)
            _both(len(head))
        if left:
            splice_mod.copy_fd(conn.sock.fileno(), dst.fileno(), left,
                               note=_both)
        resp._remaining = 0
        resp._done = True

    def close(self) -> None:
        # Fully-relayed bodies return the upstream conn to the pool;
        # an aborted transfer leaves unread bytes, so the conn dies.
        if self._resp._done:
            rpc._finish(self._conn, self._resp)
        else:
            self._conn.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class _GrpcMasterTransport:
    """Master Assign/Lookup over the wire-compatible master_pb.Seaweed
    gRPC plane (http port + 10000) — the transport a ported Go client
    uses (wdclient dials gRPC, pb/grpc_client_server.go).  Selected by
    WeedClient(use_grpc=True) or WEED_INTERNAL_GRPC=1, so the capstone
    stack can run its internal master traffic through the gRPC facade
    instead of the JSON plane (facade-drift canary).  One instance per
    master seed; WeedClient rotates across them on failure
    (tryAllMasters, like the JSON path)."""

    def __init__(self, master_url: str):
        import grpc

        from ..pb import master_pb2
        from ..pb.master_grpc import GRPC_PORT_DELTA
        self.pb = master_pb2
        hostport = master_url.split("://")[-1].rstrip("/")
        host, _, port = hostport.rpartition(":")
        if not host or not port.isdigit():
            host, port = hostport, "80"  # port-less URL: http default
        self.addr = f"{host}:{int(port) + GRPC_PORT_DELTA}"
        self._chan = grpc.insecure_channel(self.addr)
        svc = "/master_pb.Seaweed/"
        self._assign = self._chan.unary_unary(
            svc + "Assign",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=master_pb2.AssignResponse.FromString)
        self._lookup = self._chan.unary_unary(
            svc + "LookupVolume",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=(
                master_pb2.LookupVolumeResponse.FromString))

    def assign(self, count, collection, replication, ttl,
               data_center) -> dict:
        out = self._assign(self.pb.AssignRequest(
            count=count, collection=collection,
            replication=replication or "", ttl=ttl,
            data_center=data_center), timeout=10,
            wait_for_ready=True, metadata=_grpc_trace_metadata())
        if out.error:
            raise rpc.RpcError(500, out.error)
        resp = {"fid": out.fid, "url": out.url,
                "publicUrl": out.public_url, "count": out.count}
        if out.auth:
            resp["auth"] = out.auth
        return resp

    def lookup(self, vid: int) -> list[dict]:
        out = self._lookup(self.pb.LookupVolumeRequest(
            volume_ids=[str(vid)]), timeout=10, wait_for_ready=True,
            metadata=_grpc_trace_metadata())
        for entry in out.volume_id_locations:
            if entry.error:
                return []
            return [{"url": loc.url, "publicUrl": loc.public_url}
                    for loc in entry.locations]
        return []

    def close(self) -> None:
        self._chan.close()


class WeedClient:
    """Accepts one master URL or an HA seed list; master calls fail
    over across seeds like the reference's MasterClient
    (wdclient/masterclient.go tryAllMasters)."""

    def __init__(self, master_url: str | list[str],
                 use_grpc: bool | None = None,
                 retry_policy: "resilience.RetryPolicy | None" = None):
        import os
        urls = master_url if isinstance(master_url, list) \
            else [master_url]
        self.masters = [u.rstrip("/") for u in urls]
        self._master_idx = 0
        # Write-path policy: upload re-assigns to a fresh volume on
        # failure, paced by this policy's backoff.
        self.retry_policy = retry_policy or resilience.RetryPolicy()
        self._secured: bool | None = None  # learned from responses
        self.cache = VidCache()
        self._watch_stop: threading.Event | None = None
        if use_grpc is None:
            use_grpc = os.environ.get("WEED_INTERNAL_GRPC") == "1"
        self._use_grpc = use_grpc
        # Lazily dialed, one per master seed (HA failover rotates).
        self._grpc_transports: dict[str, _GrpcMasterTransport] = {}

    @property
    def _grpc(self) -> "_GrpcMasterTransport | None":
        """Transport for the CURRENT master seed (None when the JSON
        plane is selected)."""
        if not self._use_grpc:
            return None
        url = self.master_url
        t = self._grpc_transports.get(url)
        if t is None:
            t = self._grpc_transports[url] = _GrpcMasterTransport(url)
        return t

    def _grpc_master_call(self, method: str, *args):
        """Try each master seed once over gRPC, rotating past dead
        ones — the gRPC analog of _master_call/tryAllMasters."""
        last_err: Exception | None = None
        for _ in range(len(self.masters)):
            try:
                return getattr(self._grpc, method)(*args)
            except rpc.RpcError:
                raise  # a real server-side answer
            except Exception as e:  # noqa: BLE001 — dead/unreachable
                last_err = e
            self._master_idx = (self._master_idx + 1) % \
                len(self.masters)
        raise last_err or rpc.RpcError(503, "no master reachable")

    def close(self) -> None:
        """Release transport resources (gRPC channels)."""
        if self._watch_stop is not None:
            self._watch_stop.set()
        for t in self._grpc_transports.values():
            t.close()
        self._grpc_transports.clear()

    def start_location_watch(self):
        """Subscribe to the master's /cluster/watch push stream (the
        KeepConnected analog): volume-location changes invalidate the
        vid cache the moment heartbeats land, instead of waiting out
        the TTL.  Returns a stop() function; reconnects with backoff
        while running."""
        stop = threading.Event()
        self._watch_stop = stop
        holder: dict = {}

        def loop():
            while not stop.is_set():
                try:
                    handle = rpc.call_stream(
                        f"{self.master_url}/cluster/watch",
                        stop_event=stop)
                    holder["handle"] = handle
                    for doc in handle.events():
                        if stop.is_set():
                            return
                        for vid in doc.get("new_vids", []) + \
                                doc.get("deleted_vids", []):
                            self.cache.forget(int(vid))
                except rpc.RpcError as e:
                    # A follower refuses watch streams (503): rotate to
                    # the next seed until the leader answers.
                    if e.status == 503 and len(self.masters) > 1:
                        self._master_idx = (self._master_idx + 1) \
                            % len(self.masters)
                except Exception:  # noqa: BLE001 — master down; redial
                    pass
                finally:
                    holder.pop("handle", None)
                stop.wait(1.0)

        threading.Thread(target=loop, daemon=True,
                         name="vid-watch").start()

        def stopper():
            stop.set()
            handle = holder.get("handle")
            if handle is not None:
                handle.close()
        return stopper

    @property
    def master_url(self) -> str:
        return self.masters[self._master_idx]

    def _master_call(self, path_qs: str):
        """Try each master seed once; rotate past dead/leaderless ones
        so the winner stays current for subsequent calls."""
        last_err: Exception | None = None
        for _ in range(len(self.masters)):
            try:
                return rpc.call(self.master_url + path_qs)
            except rpc.RpcError as e:
                if e.status != 503:  # a real answer, not "no leader"
                    raise
                last_err = e
            except OSError as e:
                last_err = e
            self._master_idx = (self._master_idx + 1) % \
                len(self.masters)
        raise last_err or rpc.RpcError(503, "no master reachable")

    # -- master ops ----------------------------------------------------------

    def assign(self, count: int = 1, collection: str = "",
               replication: str | None = None, ttl: str = "",
               data_center: str = "") -> dict:
        if self._use_grpc:
            return self._grpc_master_call(
                "assign", count, collection, replication, ttl,
                data_center)
        q = [f"count={count}"]
        if collection:
            q.append(f"collection={collection}")
        if replication is not None:
            q.append(f"replication={replication}")
        if ttl:
            q.append(f"ttl={ttl}")
        if data_center:
            q.append(f"dataCenter={data_center}")
        return self._master_call("/dir/assign?" + "&".join(q))

    def lookup(self, vid: int, include_ec: bool = False) -> list[dict]:
        """Volume locations.  include_ec adds EC shard holders — READ
        targets only (any holder reconstructs across the cluster); they
        are never cached and never offered to write/delete paths, which
        must keep failing fast on EC'd volumes."""
        cached = self.cache.get(vid)
        if cached is not None:
            return cached
        if self._use_grpc:
            locs = self._grpc_master_call("lookup", vid)
            if locs:
                self.cache.put(vid, locs)
                return locs
            # EC-only / unknown volumes need the richer JSON answer
            # (ecShards); fall through to the HTTP lookup.
        resp = self._master_call(f"/dir/lookup?volumeId={vid}")
        locs = resp.get("locations", [])
        if locs:
            self.cache.put(vid, locs)
            return locs
        if include_ec:
            urls = {d["url"] for dns in resp.get("ecShards", {}).values()
                    for d in dns}
            return [{"url": u} for u in sorted(urls)]
        return []

    # -- object ops ----------------------------------------------------------

    def upload_data(self, data: bytes, collection: str = "",
                    replication: str | None = None, ttl: str = "",
                    name: str = "") -> str:
        """Assign + PUT. Returns the fid."""
        return self.upload(data, collection=collection,
                           replication=replication, ttl=ttl,
                           name=name)["fid"]

    def upload(self, data: bytes, collection: str = "",
               replication: str | None = None, ttl: str = "",
               name: str = "", mime: str = "",
               compress: bool = True, cipher: bool = False) -> dict:
        """Assign + PUT with the full upload pipeline of the
        reference's operation.UploadData (operation/upload_content.go):
        compressible content is gzipped when that shrinks it (sent with
        Content-Encoding so the needle records the flag), and cipher=True
        seals the bytes with a fresh AES-256-GCM key the caller keeps —
        the volume server stores opaque data with no name/mime.

        Returns {fid, url, size, etag, is_compressed, cipher_key}.
        `size` is the logical (plaintext) size; cipher_key is b"" unless
        cipher was requested.

        Write-path resilience: a failed PUT (dead/sick volume server)
        does not surface the first dead server — the client re-assigns,
        which hands it a FRESH volume/fid, and retries there after a
        jittered backoff (retry_policy).  Re-sending to a new fid is
        always safe: the non-idempotent body never replays against the
        same needle, which is the transport's own no-resend rule lifted
        to the application layer.
        """
        size = len(data)
        gzipped = False
        key = b""
        if cipher:
            # Sealed uploads never double as gzip uploads: ciphertext
            # doesn't compress, and the chunk metadata (not the needle)
            # carries everything a reader needs.
            from ..utils.cipher import encrypt
            data, key = encrypt(data)
        elif compress:
            from ..utils.compression import maybe_gzip
            data, gzipped = maybe_gzip(data, name, mime)
        policy = self.retry_policy
        deadline = (time.monotonic() + policy.total_deadline
                    if policy.total_deadline else None)
        last_err: Exception | None = None
        for attempt in range(policy.max_attempts):
            if attempt:
                resilience.rpc_retries_total.inc(reason="reassign")
                delay = policy.backoff(attempt - 1)
                if deadline is not None:
                    delay = min(delay,
                                max(0.0, deadline - time.monotonic()))
                time.sleep(delay)
            # Per-attempt timeout clipped to what remains of the total
            # deadline budget: a sick server costs one bounded attempt,
            # and the whole upload never overstays its budget.
            timeout = policy.per_attempt_timeout
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                timeout = min(timeout, remaining)
            try:
                a = self.assign(collection=collection,
                                replication=replication, ttl=ttl)
            except (rpc.RpcError, OSError) as e:
                last_err = e
                continue
            fid = a["fid"]
            url = f"http://{a['url']}/{fid}"
            q = []
            if name and not cipher:
                q.append("name=" + urllib.parse.quote(name))
            if mime and not cipher:
                q.append("mime=" + urllib.parse.quote(mime))
            if a.get("auth"):  # master-minted write JWT (secured)
                q.append(f"jwt={a['auth']}")
            if q:
                url += "?" + "&".join(q)
            try:
                resp = rpc.call(url, "POST", data, timeout=timeout,
                                headers={"Content-Encoding": "gzip"}
                                if gzipped else None)
            except rpc.RpcError as e:
                if e.status < 500 and e.status != 429:
                    raise  # a definitive answer (auth, bad request)
                # 5xx (failed replication fan-out, sick store,
                # draining replica) or a 429 shed: the volume is
                # suspect — forget it and re-assign; the master's
                # steering hands the retry a volume off the draining/
                # overloaded node.  A shed/drain answer was refused
                # before execution, so re-sending is always safe.
                last_err = e
                self.cache.forget(t.parse_file_id(fid)[0])
                continue
            except OSError as e:  # dead server: re-assign elsewhere
                last_err = e
                self.cache.forget(t.parse_file_id(fid)[0])
                continue
            etag = resp.get("eTag", "") if isinstance(resp, dict) \
                else ""
            return {"fid": fid, "url": a["url"], "size": size,
                    "etag": etag, "is_compressed": gzipped,
                    "cipher_key": key}
        raise last_err or rpc.RpcError(503, "upload: no attempt ran")

    def download(self, fid: str, cipher_key: bytes = b"") -> bytes:
        """Fetch a needle; opens sealed blobs when the caller holds the
        chunk's cipher key (gzip is undone server-side — plain `call`
        never advertises Accept-Encoding)."""
        data = self._download_raw(fid)
        if cipher_key:
            from ..utils.cipher import decrypt
            data = decrypt(data, cipher_key)
        return data

    def _download_raw(self, fid: str) -> bytes:
        vid, _key, _cookie = t.parse_file_id(fid)
        locs = self.lookup(vid, include_ec=True)
        if not locs:
            raise rpc.RpcError(404, f"volume {vid} has no locations")
        last_err: Exception | None = None
        # Round-robin across replicas (vid_map.go's read balancing).
        with self.cache._lock:
            start = self.cache._rr.get(vid, 0)
            self.cache._rr[vid] = start + 1
        relooked = False
        i = 0
        while i < len(locs):
            loc = locs[(start + i) % len(locs)]
            i += 1
            try:
                out = rpc.call(f"http://{loc['url']}/{fid}")
                assert isinstance(out, (bytes, bytearray))
                return bytes(out)
            except rpc.RpcError as e:
                last_err = e
                if e.status == 404 and "volume" in e.message:
                    self.cache.forget(vid)
                elif e.status in (429, 503) and not relooked:
                    # Draining/shedding replica: re-run the master
                    # lookup once instead of burning the rest of a
                    # stale list against a node that is leaving.
                    relooked = True
                    self.cache.forget(vid)
                    fresh = self._relookup(vid, include_ec=True)
                    if fresh:
                        locs, i, start = fresh, 0, 0
            except OSError as e:  # dead server: fail over to next replica
                last_err = e
                self.cache.forget(vid)
                if i >= len(locs) and not relooked:
                    # Every cached location failed at the connection
                    # level: during a rolling restart the cached list
                    # can be stale in BOTH directions (a drained node
                    # still listed, a restarted one missing).  One
                    # fresh master lookup before giving up.
                    relooked = True
                    fresh = self._relookup(vid, include_ec=True)
                    if fresh:
                        locs, i, start = fresh, 0, 0
        raise last_err or rpc.RpcError(404, "not found")

    def _relookup(self, vid: int, include_ec: bool = False) -> list:
        """Best-effort mid-failover lookup refresh: a master outage
        (leaderless window, exactly when a failover is likely running)
        must not abort a replica walk that can still succeed against
        the remaining cached locations."""
        try:
            return self.lookup(vid, include_ec=include_ec)
        except Exception:  # noqa: BLE001 — keep walking the old list
            return []

    def open_stream(self, fid: str, offset: int, size: int,
                    timeout: float = 30.0) -> ProxiedBody | None:
        """Open a ranged GET for `size` bytes of a needle WITHOUT
        reading the body: the filer's direct proxy leg relays (splices,
        when the platform allows) the stream straight to its own
        client.  Returns None when no replica can serve the exact range
        — the caller falls back to the buffered chunk path, so this is
        strictly an optimization, never a correctness dependency."""
        if size <= 0:
            return None
        vid, _key, _cookie = t.parse_file_id(fid)
        try:
            locs = self.lookup(vid, include_ec=True)
        except Exception:  # noqa: BLE001 — fall back to buffered path
            return None
        if not locs:
            return None
        with self.cache._lock:
            start = self.cache._rr.get(vid, 0)
            self.cache._rr[vid] = start + 1
        # The volume leg of a filer proxy read is `proxy` traffic, not
        # a user read — the user-facing read is the filer's own
        # response (stats/flows.py).
        rng = {"Range": f"bytes={offset}-{offset + size - 1}",
               **_flows.tag("proxy")}
        for i in range(len(locs)):
            loc = locs[(start + i) % len(locs)]
            try:
                resp, conn = rpc._request(
                    f"http://{loc['url']}/{fid}", "GET", None, timeout,
                    req_headers=rng)
            except Exception:  # noqa: BLE001 — replica down: try next
                continue
            if resp.status in (200, 206) and not resp._chunks and \
                    resp.getheader("content-length") == str(size):
                return ProxiedBody(resp, conn, size)
            # Error status, chunked framing, or a whole-needle 200 when
            # we asked for a subrange: this replica can't feed the
            # relay.  Closing (not draining) keeps the failure O(1).
            conn.close()
        return None

    def delete(self, fid: str) -> None:
        """Delete a needle, failing over across replicas exactly like
        `_download_raw` does — any live replica fans the delete out to
        its siblings, so the first dead server must not fail the op."""
        vid, _key, _cookie = t.parse_file_id(fid)
        locs = self.lookup(vid)
        if not locs:
            raise rpc.RpcError(404, f"volume {vid} has no locations")
        # Secured cluster: fetch a delete token via lookup?fileId=
        # (operation/delete_content.go).  Once the master answers
        # without auth the cluster is known-unsecured and the extra
        # lookup is skipped.
        jwt = ""
        if self._secured is not False:
            resp = self._master_call(
                f"/dir/lookup?volumeId={vid}&fileId={fid}")
            auth = resp.get("auth", "")
            self._secured = bool(auth)
            if auth:
                jwt = f"?jwt={auth}"
        last_err: Exception | None = None
        relooked = False
        i = 0
        while i < len(locs):
            url = f"http://{locs[i]['url']}/{fid}{jwt}"
            i += 1
            try:
                rpc.call(url, "DELETE")
                return
            except rpc.RpcError as e:
                last_err = e
                if e.status == 404 and "volume" in e.message:
                    self.cache.forget(vid)
                elif e.status in (429, 503) and not relooked:
                    # Draining (or shedding) replica: the cached
                    # location list is going stale — re-run the master
                    # lookup ONCE and walk the fresh replicas instead
                    # of burning the rest of the list against a node
                    # that is leaving.
                    relooked = True
                    self.cache.forget(vid)
                    fresh = self._relookup(vid)
                    if fresh:
                        locs, i = fresh, 0
            except OSError as e:  # dead server: next replica
                last_err = e
                self.cache.forget(vid)
        raise last_err or rpc.RpcError(404, "not found")

    def submit(self, data: bytes, **kw) -> dict:
        """upload + return its result dict (operation/submit.go):
        {fid, size, url} plus etag/is_compressed/cipher_key.  Reuses
        the url the upload already resolved — a transient lookup
        failure must not fail a write that succeeded — and passes the
        full dict through so a cipher=True submit never silently drops
        the one copy of its cipher_key."""
        return self.upload(data, **kw)
