"""Image handling: EXIF auto-orientation + on-the-fly resizing.

Reference: weed/images/orientation.go (FixJpgOrientation applied on
JPEG upload, hooked at storage/needle/needle.go:100-105) and
resizing.go (Resized serving ?width=&height=&mode= reads, hooked at
server/volume_server_handlers_read.go:219-243).

PIL backs both; when it's unavailable every function degrades to a
pass-through so storage semantics never depend on it.
"""

from __future__ import annotations

import io

try:
    from PIL import Image, ImageOps
    HAS_PIL = True
except Exception:  # noqa: BLE001 — optional dependency
    HAS_PIL = False

IMAGE_MIMES = ("image/jpeg", "image/png", "image/gif", "image/webp")


def is_image_mime(mime: str) -> bool:
    return mime in IMAGE_MIMES


def fix_jpeg_orientation(data: bytes) -> bytes:
    """Rotate JPEG pixels per the EXIF Orientation tag and strip it
    (orientation.go FixJpgOrientation)."""
    if not HAS_PIL:
        return data
    try:
        img = Image.open(io.BytesIO(data))
        if img.format != "JPEG":
            return data
        exif = img.getexif()
        if exif.get(0x0112, 1) == 1:  # Orientation tag: already upright
            return data
        fixed = ImageOps.exif_transpose(img)
        out = io.BytesIO()
        fixed.save(out, format="JPEG", quality=90)
        return out.getvalue()
    except Exception:  # noqa: BLE001 — corrupt image: store as-is
        return data


def resized(data: bytes, width: int = 0, height: int = 0,
            mode: str = "") -> tuple[bytes, str]:
    """Resize an image read (resizing.go Resized).

    mode '' : preserve aspect ratio within (width, height)
    'fit'   : fit inside the box, padding to exactly (width, height)
    'fill'  : cover the box and center-crop to exactly (width, height)
    Returns (bytes, mime) — unchanged input when no resize applies."""
    if not HAS_PIL or (not width and not height):
        return data, ""
    try:
        img = Image.open(io.BytesIO(data))
        fmt = img.format or "PNG"
        w, h = img.size
        tw = width or w
        th = height or h
        if mode == "fill":
            out_img = ImageOps.fit(img, (tw, th))
        elif mode == "fit":
            out_img = ImageOps.pad(img.convert("RGB")
                                   if fmt == "JPEG" else img, (tw, th))
        else:
            out_img = img.copy()
            out_img.thumbnail((tw, th))
        out = io.BytesIO()
        if fmt == "JPEG" and out_img.mode not in ("RGB", "L"):
            out_img = out_img.convert("RGB")
        out_img.save(out, format=fmt)
        return out.getvalue(), f"image/{fmt.lower()}"
    except Exception:  # noqa: BLE001 — not an image after all
        return data, ""
