"""AWS Signature V4 verification + identity/action access control.

Reference: weed/s3api/auth_signature_v4.go (doesSignatureMatch),
auth_credentials.go (IdentityAccessManagement, per-identity actions
Read/Write/Admin, anonymous when no identities are configured).
Sig v2 and presigned URLs are not implemented; v4 header auth is what the
AWS SDKs send by default.
"""

from __future__ import annotations

import hashlib
import hmac
import time
import urllib.parse
from dataclasses import dataclass, field

ACTION_READ = "Read"
ACTION_WRITE = "Write"
ACTION_LIST = "List"
ACTION_TAGGING = "Tagging"
ACTION_ADMIN = "Admin"


class AuthError(Exception):
    def __init__(self, code: str, message: str, status: int = 403):
        super().__init__(message)
        self.code = code
        self.status = status


@dataclass
class Identity:
    name: str
    access_key: str
    secret_key: str
    actions: list[str] = field(default_factory=lambda: [ACTION_ADMIN])

    def allows(self, action: str, bucket: str) -> bool:
        for a in self.actions:
            if a == ACTION_ADMIN:
                return True
            base, _, target = a.partition(":")
            if base != action:
                continue
            if not target or target == bucket:
                return True
        return False


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def derive_signing_key(secret: str, date: str, region: str,
                       service: str = "s3") -> bytes:
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def canonical_query(raw_query: str) -> str:
    """AWS canonical query: sorted, URI-encoded key=value pairs."""
    pairs = urllib.parse.parse_qsl(raw_query, keep_blank_values=True)
    enc = [(urllib.parse.quote(k, safe="-_.~"),
            urllib.parse.quote(v, safe="-_.~")) for k, v in pairs]
    return "&".join(f"{k}={v}" for k, v in sorted(enc))


def canonical_uri(path: str) -> str:
    # S3 canonicalizes the path with '/' kept.
    return urllib.parse.quote(urllib.parse.unquote(path), safe="/-_.~")


def compute_signature_v4(method: str, path: str, raw_query: str,
                         headers: dict[str, str], signed_headers: list[str],
                         payload_hash: str, amz_date: str, scope: str,
                         secret_key: str) -> str:
    """The exact AWS sig v4 computation (also usable as a client signer)."""
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n"
        for h in signed_headers)
    canonical_request = "\n".join([
        method, canonical_uri(path), canonical_query(raw_query),
        canon_headers, ";".join(signed_headers), payload_hash])
    date, region, service, _term = scope.split("/")
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        _sha256(canonical_request.encode())])
    key = derive_signing_key(secret_key, date, region, service)
    return hmac.new(key, string_to_sign.encode(),
                    hashlib.sha256).hexdigest()


class IdentityAccessManagement:
    """Identity registry + request authentication (auth_credentials.go)."""

    def __init__(self, identities: list[Identity] | None = None):
        self.identities = {i.access_key: i for i in (identities or [])}

    @property
    def enabled(self) -> bool:
        return bool(self.identities)

    def authenticate(self, method: str, path: str, raw_query: str,
                     headers: dict[str, str],
                     body: bytes | None) -> Identity | None:
        """Verify the v4 Authorization header; returns the Identity.
        With no identities configured every request is anonymous-admin
        (the reference's default when no config is present).

        body=None means the payload is being streamed and is not
        available for hashing: the signature is computed over the
        DECLARED x-amz-content-sha256 (exactly what the reference does
        — auth_signature_v4.go signs the header value and never
        re-hashes the stream); the recompute cross-check below only
        runs when the bytes are in hand."""
        if not self.enabled:
            return None
        auth = headers.get("authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256 "):
            raise AuthError("AccessDenied", "missing v4 authorization")
        parts = {}
        for kv in auth[len("AWS4-HMAC-SHA256 "):].split(","):
            k, _, v = kv.strip().partition("=")
            parts[k] = v
        try:
            cred = parts["Credential"]
            signed_headers = parts["SignedHeaders"].split(";")
            signature = parts["Signature"]
        except KeyError as e:
            raise AuthError("AuthorizationHeaderMalformed",
                            f"missing {e}") from None
        access_key, _, scope = cred.partition("/")
        identity = self.identities.get(access_key)
        if identity is None:
            raise AuthError("InvalidAccessKeyId",
                            f"unknown access key {access_key}")
        amz_date = headers.get("x-amz-date", "")
        self._check_date(amz_date, scope)
        payload_hash = headers.get("x-amz-content-sha256") or \
            _sha256(body or b"")
        if payload_hash == "UNSIGNED-PAYLOAD":
            pass
        elif payload_hash.startswith("STREAMING-"):
            # aws-chunked uploads: trust the seed signature's presence
            # (chunk signature verification not implemented).
            pass
        elif body is not None and \
                headers.get("x-amz-content-sha256") and \
                _sha256(body) != payload_hash:
            raise AuthError("XAmzContentSHA256Mismatch",
                            "payload hash mismatch", 400)
        expect = compute_signature_v4(
            method, path, raw_query, headers, signed_headers,
            payload_hash, amz_date, scope, identity.secret_key)
        if not hmac.compare_digest(expect, signature):
            raise AuthError("SignatureDoesNotMatch",
                            "signature mismatch")
        return identity

    @staticmethod
    def _check_date(amz_date: str, scope: str) -> None:
        """Reject requests outside a 15-minute clock-skew window and
        requests whose x-amz-date disagrees with the credential-scope
        date (auth_signature_v4.go's replay protection)."""
        import calendar
        try:
            ts = calendar.timegm(time.strptime(amz_date,
                                               "%Y%m%dT%H%M%SZ"))
        except ValueError:
            raise AuthError("AuthorizationHeaderMalformed",
                            f"bad x-amz-date {amz_date!r}",
                            400) from None
        if abs(time.time() - ts) > 15 * 60:
            raise AuthError("RequestTimeTooSkewed",
                            "request time differs from server time by "
                            "more than 15 minutes")
        scope_date = scope.split("/", 1)[0]
        if scope_date != amz_date[:8]:
            raise AuthError("AuthorizationHeaderMalformed",
                            "credential scope date does not match "
                            "x-amz-date", 400)

    def authorize(self, identity: Identity | None, action: str,
                  bucket: str) -> None:
        if identity is None:  # anonymous mode: everything allowed
            return
        if not identity.allows(action, bucket):
            raise AuthError("AccessDenied",
                            f"{identity.name} may not {action} "
                            f"on {bucket}")
