"""AWS signature verification + identity/action access control.

Reference: weed/s3api/auth_signature_v4.go (doesSignatureMatch),
auth_signature_v2.go (header + presigned v2, HMAC-SHA1 over the
canonical string), s3api/policy/ (POST-policy form signatures), and
auth_credentials.go (IdentityAccessManagement, per-identity actions
Read/Write/Admin, anonymous when no identities are configured).

Supported: v4 header auth (what SDKs send by default), v4 presigned
URLs, v2 header auth, v2 presigned URLs, and POST-policy form auth in
both v2 and v4 flavors.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import time
import urllib.parse
from dataclasses import dataclass, field

ACTION_READ = "Read"
ACTION_WRITE = "Write"
ACTION_LIST = "List"
ACTION_TAGGING = "Tagging"
ACTION_ADMIN = "Admin"


class AuthError(Exception):
    def __init__(self, code: str, message: str, status: int = 403):
        super().__init__(message)
        self.code = code
        self.status = status


@dataclass
class Identity:
    name: str
    access_key: str
    secret_key: str
    actions: list[str] = field(default_factory=lambda: [ACTION_ADMIN])

    def allows(self, action: str, bucket: str) -> bool:
        for a in self.actions:
            if a == ACTION_ADMIN:
                return True
            base, _, target = a.partition(":")
            if base != action:
                continue
            if not target or target == bucket:
                return True
        return False


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def derive_signing_key(secret: str, date: str, region: str,
                       service: str = "s3") -> bytes:
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def canonical_query(raw_query: str) -> str:
    """AWS canonical query: sorted, URI-encoded key=value pairs."""
    pairs = urllib.parse.parse_qsl(raw_query, keep_blank_values=True)
    enc = [(urllib.parse.quote(k, safe="-_.~"),
            urllib.parse.quote(v, safe="-_.~")) for k, v in pairs]
    return "&".join(f"{k}={v}" for k, v in sorted(enc))


def canonical_uri(path: str) -> str:
    # S3 canonicalizes the path with '/' kept.
    return urllib.parse.quote(urllib.parse.unquote(path), safe="/-_.~")


def compute_signature_v4(method: str, path: str, raw_query: str,
                         headers: dict[str, str], signed_headers: list[str],
                         payload_hash: str, amz_date: str, scope: str,
                         secret_key: str) -> str:
    """The exact AWS sig v4 computation (also usable as a client signer)."""
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n"
        for h in signed_headers)
    canonical_request = "\n".join([
        method, canonical_uri(path), canonical_query(raw_query),
        canon_headers, ";".join(signed_headers), payload_hash])
    date, region, service, _term = scope.split("/")
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        _sha256(canonical_request.encode())])
    key = derive_signing_key(secret_key, date, region, service)
    return hmac.new(key, string_to_sign.encode(),
                    hashlib.sha256).hexdigest()


# Subresources that participate in the v2 canonical resource
# (auth_signature_v2.go resourceList — alphabetically sorted).
RESOURCE_LIST = [
    "acl", "delete", "lifecycle", "location", "logging", "notification",
    "partNumber", "policy", "requestPayment", "response-cache-control",
    "response-content-disposition", "response-content-encoding",
    "response-content-language", "response-content-type",
    "response-expires", "torrent", "uploadId", "uploads", "versionId",
    "versioning", "versions", "website",
]


def identities_from_dict(cfg: dict) -> list[Identity]:
    """Parse the reference's S3 identities config shape
    (auth_credentials.go: {"identities": [{name, credentials:
    [{accessKey, secretKey}], actions}]})."""
    out = []
    for ident in cfg.get("identities", []):
        cred = (ident.get("credentials") or [{}])[0]
        out.append(Identity(name=ident.get("name", ""),
                            access_key=cred.get("accessKey", ""),
                            secret_key=cred.get("secretKey", ""),
                            actions=ident.get("actions", [ACTION_ADMIN])))
    return out


def signature_v2(secret_key: str, string_to_sign: str) -> str:
    """base64(HMAC-SHA1) — the v2 primitive (calculateSignatureV2)."""
    return base64.b64encode(hmac.new(
        secret_key.encode(), string_to_sign.encode(),
        hashlib.sha1).digest()).decode()


def canonical_resource_v2(path: str, raw_query: str) -> str:
    """Path + whitelisted subresources, sorted (CanonicalizedResource)."""
    pairs = urllib.parse.parse_qsl(raw_query, keep_blank_values=True)
    sub = [f"{k}={v}" if v else k
           for k, v in sorted(pairs) if k in RESOURCE_LIST]
    return path + (("?" + "&".join(sub)) if sub else "")


def canonical_string_v2(method: str, path: str, raw_query: str,
                        headers: dict[str, str], date_field: str) -> str:
    """The v2 StringToSign (signatureV2/presignatureV2): method,
    content-md5, content-type, date (or Expires for presigned, or ""
    when x-amz-date supersedes), x-amz-* headers, canonical resource."""
    amz = sorted((k.lower().strip(), " ".join(v.split()))
                 for k, v in headers.items()
                 if k.lower().startswith("x-amz-"))
    canon_amz = "".join(f"{k}:{v}\n" for k, v in amz)
    return "\n".join([
        method,
        headers.get("content-md5", ""),
        headers.get("content-type", ""),
        date_field,
    ]) + "\n" + canon_amz + canonical_resource_v2(path, raw_query)


class IdentityAccessManagement:
    """Identity registry + request authentication (auth_credentials.go)."""

    def __init__(self, identities: list[Identity] | None = None):
        self.identities = {i.access_key: i for i in (identities or [])}
        # Set by a filer-backed gateway that could not reach its IAM
        # config: deny everything rather than default to anonymous
        # all-access.
        self.fail_closed = False

    def replace(self, identities: list[Identity]) -> None:
        """Atomically swap the identity set (filer-backed IAM reload)."""
        self.identities = {i.access_key: i for i in identities}

    @property
    def enabled(self) -> bool:
        return bool(self.identities)

    def authenticate(self, method: str, path: str, raw_query: str,
                     headers: dict[str, str],
                     body: bytes | None) -> Identity | None:
        """Verify the v4 Authorization header; returns the Identity.
        With no identities configured every request is anonymous-admin
        (the reference's default when no config is present).

        body=None means the payload is being streamed and is not
        available for hashing: the signature is computed over the
        DECLARED x-amz-content-sha256 (exactly what the reference does
        — auth_signature_v4.go signs the header value and never
        re-hashes the stream); the recompute cross-check below only
        runs when the bytes are in hand."""
        if self.fail_closed:
            raise AuthError("ServiceUnavailable",
                            "IAM configuration unavailable", 503)
        if not self.enabled:
            return None
        auth = headers.get("authorization", "")
        if auth.startswith("AWS4-HMAC-SHA256 "):
            return self._auth_v4_header(method, path, raw_query, headers,
                                        body, auth)
        if auth.startswith("AWS "):
            return self._auth_v2_header(method, path, raw_query, headers,
                                        auth)
        q = dict(urllib.parse.parse_qsl(raw_query,
                                        keep_blank_values=True))
        if q.get("X-Amz-Algorithm") == "AWS4-HMAC-SHA256":
            return self._auth_v4_presigned(method, path, raw_query,
                                           headers, q)
        if "Signature" in q and "AWSAccessKeyId" in q and "Expires" in q:
            return self._auth_v2_presigned(method, path, raw_query,
                                           headers, q)
        raise AuthError("AccessDenied", "no valid authentication")

    def _lookup(self, access_key: str) -> Identity:
        identity = self.identities.get(access_key)
        if identity is None:
            raise AuthError("InvalidAccessKeyId",
                            f"unknown access key {access_key}")
        return identity

    def _auth_v4_header(self, method, path, raw_query, headers, body,
                        auth) -> Identity:
        parts = {}
        for kv in auth[len("AWS4-HMAC-SHA256 "):].split(","):
            k, _, v = kv.strip().partition("=")
            parts[k] = v
        try:
            cred = parts["Credential"]
            signed_headers = parts["SignedHeaders"].split(";")
            signature = parts["Signature"]
        except KeyError as e:
            raise AuthError("AuthorizationHeaderMalformed",
                            f"missing {e}") from None
        access_key, _, scope = cred.partition("/")
        identity = self.identities.get(access_key)
        if identity is None:
            raise AuthError("InvalidAccessKeyId",
                            f"unknown access key {access_key}")
        amz_date = headers.get("x-amz-date", "")
        self._check_date(amz_date, scope)
        payload_hash = headers.get("x-amz-content-sha256") or \
            _sha256(body or b"")
        if payload_hash == "UNSIGNED-PAYLOAD":
            pass
        elif payload_hash.startswith("STREAMING-"):
            # aws-chunked uploads: trust the seed signature's presence
            # (chunk signature verification not implemented).
            pass
        elif body is not None and \
                headers.get("x-amz-content-sha256") and \
                _sha256(body) != payload_hash:
            raise AuthError("XAmzContentSHA256Mismatch",
                            "payload hash mismatch", 400)
        expect = compute_signature_v4(
            method, path, raw_query, headers, signed_headers,
            payload_hash, amz_date, scope, identity.secret_key)
        if not hmac.compare_digest(expect, signature):
            raise AuthError("SignatureDoesNotMatch",
                            "signature mismatch")
        return identity

    def _auth_v2_header(self, method, path, raw_query, headers,
                        auth) -> Identity:
        """`Authorization: AWS <access>:<sig>` (doesSignV2Match)."""
        access_key, _, signature = auth[4:].strip().partition(":")
        if not signature:
            raise AuthError("AuthorizationHeaderMalformed",
                            "v2 header needs AWS access:signature")
        identity = self._lookup(access_key)
        # When x-amz-date is present it supersedes Date, whose slot in
        # the string-to-sign becomes empty (the spec's replacement
        # rule).
        date_field = "" if "x-amz-date" in headers \
            else headers.get("date", "")
        expect = signature_v2(
            identity.secret_key,
            canonical_string_v2(method, path, raw_query, headers,
                                date_field))
        if not hmac.compare_digest(expect, signature):
            raise AuthError("SignatureDoesNotMatch",
                            "v2 signature mismatch")
        return identity

    def _auth_v2_presigned(self, method, path, raw_query, headers,
                           q) -> Identity:
        """?AWSAccessKeyId=&Expires=&Signature= presigned URLs
        (doesPresignV2SignatureMatch)."""
        identity = self._lookup(q["AWSAccessKeyId"])
        try:
            expires = int(q["Expires"])
        except ValueError:
            raise AuthError("AccessDenied",
                            "malformed Expires", 400) from None
        if time.time() > expires:
            raise AuthError("AccessDenied", "request has expired")
        # Presigned v2 signs Expires in the Date slot and never signs
        # the auth params themselves.
        expect = signature_v2(
            identity.secret_key,
            canonical_string_v2(method, path, raw_query,
                                {k: v for k, v in headers.items()
                                 if k.lower() != "date"},
                                str(expires)))
        if not hmac.compare_digest(expect, q["Signature"]):
            raise AuthError("SignatureDoesNotMatch",
                            "presigned v2 signature mismatch")
        return identity

    def _auth_v4_presigned(self, method, path, raw_query, headers,
                           q) -> Identity:
        """?X-Amz-Algorithm=AWS4-HMAC-SHA256 presigned URLs: the
        canonical query is every parameter except X-Amz-Signature and
        the payload is UNSIGNED (auth_signature_v4.go presigned)."""
        try:
            cred = q["X-Amz-Credential"]
            amz_date = q["X-Amz-Date"]
            signature = q["X-Amz-Signature"]
            signed_headers = q["X-Amz-SignedHeaders"].split(";")
        except KeyError as e:
            raise AuthError("AuthorizationQueryParametersError",
                            f"missing {e}", 400) from None
        access_key, _, scope = cred.partition("/")
        identity = self._lookup(access_key)
        # Unlike header auth, presigned URLs are MEANT to be used long
        # after signing: X-Amz-Expires governs their age (the 15-minute
        # skew window applies only to future-dating).
        import calendar
        try:
            t0 = calendar.timegm(time.strptime(amz_date,
                                               "%Y%m%dT%H%M%SZ"))
        except ValueError:
            raise AuthError("AuthorizationQueryParametersError",
                            f"bad X-Amz-Date {amz_date!r}", 400) from None
        if scope.split("/", 1)[0] != amz_date[:8]:
            raise AuthError("AuthorizationQueryParametersError",
                            "credential scope date does not match "
                            "X-Amz-Date", 400)
        if t0 > time.time() + 15 * 60:
            raise AuthError("AccessDenied", "request is future-dated")
        try:
            expires = int(q.get("X-Amz-Expires", "604800"))
        except ValueError:
            raise AuthError("AuthorizationQueryParametersError",
                            "malformed X-Amz-Expires", 400) from None
        if time.time() > t0 + expires:
            raise AuthError("AccessDenied", "request has expired")
        filtered = urllib.parse.urlencode(
            [(k, v) for k, v in urllib.parse.parse_qsl(
                raw_query, keep_blank_values=True)
             if k != "X-Amz-Signature"])
        expect = compute_signature_v4(
            method, path, filtered, headers, signed_headers,
            "UNSIGNED-PAYLOAD", amz_date, scope, identity.secret_key)
        if not hmac.compare_digest(expect, signature):
            raise AuthError("SignatureDoesNotMatch",
                            "presigned v4 signature mismatch")
        return identity

    def authenticate_policy(self, form: dict[str, str]) -> Identity | None:
        """POST-policy form auth, v2 (AWSAccessKeyId+Signature over the
        base64 policy, doesPolicySignatureV2Match) or v4
        (X-Amz-Signature with the policy as the string-to-sign,
        doesPolicySignatureV4Match)."""
        if self.fail_closed:
            raise AuthError("ServiceUnavailable",
                            "IAM configuration unavailable", 503)
        if not self.enabled:
            return None
        lower = {k.lower(): v for k, v in form.items()}
        policy = lower.get("policy", "")
        if not policy:
            raise AuthError("AccessDenied", "POST form without policy")
        if "x-amz-signature" in lower:
            cred = lower.get("x-amz-credential", "")
            access_key, _, scope = cred.partition("/")
            identity = self._lookup(access_key)
            date, region, service, _term = (scope.split("/") + [""] * 4)[:4]
            key = derive_signing_key(identity.secret_key, date,
                                     region, service or "s3")
            expect = hmac.new(key, policy.encode(),
                              hashlib.sha256).hexdigest()
            if not hmac.compare_digest(expect,
                                       lower["x-amz-signature"]):
                raise AuthError("SignatureDoesNotMatch",
                                "policy v4 signature mismatch")
            return identity
        if "awsaccesskeyid" in lower and "signature" in lower:
            identity = self._lookup(lower["awsaccesskeyid"])
            expect = signature_v2(identity.secret_key, policy)
            if not hmac.compare_digest(expect, lower["signature"]):
                raise AuthError("SignatureDoesNotMatch",
                                "policy v2 signature mismatch")
            return identity
        raise AuthError("AccessDenied", "POST form without signature")

    @staticmethod
    def _check_date(amz_date: str, scope: str) -> None:
        """Reject requests outside a 15-minute clock-skew window and
        requests whose x-amz-date disagrees with the credential-scope
        date (auth_signature_v4.go's replay protection)."""
        import calendar
        try:
            ts = calendar.timegm(time.strptime(amz_date,
                                               "%Y%m%dT%H%M%SZ"))
        except ValueError:
            raise AuthError("AuthorizationHeaderMalformed",
                            f"bad x-amz-date {amz_date!r}",
                            400) from None
        if abs(time.time() - ts) > 15 * 60:
            raise AuthError("RequestTimeTooSkewed",
                            "request time differs from server time by "
                            "more than 15 minutes")
        scope_date = scope.split("/", 1)[0]
        if scope_date != amz_date[:8]:
            raise AuthError("AuthorizationHeaderMalformed",
                            "credential scope date does not match "
                            "x-amz-date", 400)

    def authorize(self, identity: Identity | None, action: str,
                  bucket: str) -> None:
        if identity is None:  # anonymous mode: everything allowed
            return
        if not identity.allows(action, bucket):
            raise AuthError("AccessDenied",
                            f"{identity.name} may not {action} "
                            f"on {bucket}")
