"""S3 SelectObjectContent: request XML parsing + event-stream framing.

Reference: the reference serves S3-Select-ish queries via the volume
Query RPC (server/volume_grpc_query.go); the S3 surface here speaks the
real AWS wire shape — SelectObjectContentRequest XML in, and the
response as the AWS event-stream framing (prelude + CRCs) with
Records / Stats / End events, so aws-sdk/boto3 clients can consume it.
"""

from __future__ import annotations

import struct
import xml.etree.ElementTree as ET
import zlib


def _find_text(root, path: str, default: str = "") -> str:
    # Tolerate both namespaced and bare tags.
    for el in root.iter():
        tag = el.tag.rsplit("}", 1)[-1]
        if tag == path:
            return el.text or default
    return default


def parse_select_request(body: bytes) -> dict:
    """SelectObjectContentRequest -> {expression, input_format,
    csv_header, csv_delimiter, output_format}."""
    root = ET.fromstring(body)
    expression = _find_text(root, "Expression")
    out = {"expression": expression, "input_format": "json",
           "csv_header": True, "csv_delimiter": ",",
           "output_format": "json"}
    for el in root.iter():
        tag = el.tag.rsplit("}", 1)[-1]
        if tag == "InputSerialization":
            for sub in el.iter():
                st = sub.tag.rsplit("}", 1)[-1]
                if st == "CSV":
                    out["input_format"] = "csv"
                    out["csv_header"] = _find_text(
                        sub, "FileHeaderInfo", "USE").upper() != "NONE"
                    out["csv_delimiter"] = _find_text(
                        sub, "FieldDelimiter", ",") or ","
                elif st == "JSON":
                    out["input_format"] = "json"
        elif tag == "OutputSerialization":
            for sub in el.iter():
                st = sub.tag.rsplit("}", 1)[-1]
                if st == "CSV":
                    out["output_format"] = "csv"
    return out


# -- AWS event-stream framing ----------------------------------------------

def _header(name: str, value: str) -> bytes:
    nb = name.encode()
    vb = value.encode()
    return bytes([len(nb)]) + nb + b"\x07" + \
        struct.pack(">H", len(vb)) + vb


def _message(headers: list[tuple[str, str]], payload: bytes) -> bytes:
    hdr = b"".join(_header(n, v) for n, v in headers)
    total = 16 + len(hdr) + len(payload)
    prelude = struct.pack(">II", total, len(hdr))
    prelude_crc = struct.pack(">I", zlib.crc32(prelude))
    body = prelude + prelude_crc + hdr + payload
    return body + struct.pack(">I", zlib.crc32(body))


def event_stream(records: bytes, bytes_scanned: int,
                 bytes_returned: int) -> bytes:
    """Records (chunked) + Stats + End events."""
    out = b""
    chunk = 1 << 20
    for i in range(0, len(records), chunk):
        out += _message(
            [(":message-type", "event"), (":event-type", "Records"),
             (":content-type", "application/octet-stream")],
            records[i:i + chunk])
    stats = (
        "<Stats><BytesScanned>%d</BytesScanned>"
        "<BytesProcessed>%d</BytesProcessed>"
        "<BytesReturned>%d</BytesReturned></Stats>"
        % (bytes_scanned, bytes_scanned, bytes_returned)).encode()
    out += _message(
        [(":message-type", "event"), (":event-type", "Stats"),
         (":content-type", "text/xml")], stats)
    out += _message(
        [(":message-type", "event"), (":event-type", "End")], b"")
    return out
