"""S3-compatible gateway over the filer.

Reference: weed/s3api/ — s3api_server.go (route table), auth_signature_v4.go
(AWS sig v4 verification), auth_credentials.go (identities + actions),
filer_multipart.go (multipart assembled by merging chunk lists),
s3api_object_handlers / bucket_handlers (XML protocol).
"""

from .auth import Identity, IdentityAccessManagement  # noqa: F401
from .server import S3ApiServer  # noqa: F401
