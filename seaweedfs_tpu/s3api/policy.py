"""POST-policy parsing and enforcement (browser-form uploads).

Reference: weed/s3api/policy/post-policy.go + postpolicyform.go — the
base64 JSON policy a client signs lists an expiration plus conditions
(["eq", "$key", v], ["starts-with", "$key", p], {"key": v},
["content-length-range", lo, hi]); every form field must satisfy its
condition and, conversely, fields not covered by the policy are
rejected (checkPostPolicy) so a signature can't be replayed with
extra fields.
"""

from __future__ import annotations

import base64
import calendar
import json
import time

from .auth import AuthError

# Form fields that never need a policy condition
# (postpolicyform.go ignores these in the coverage check).
_EXEMPT = {
    "policy", "signature", "awsaccesskeyid", "file",
    "x-amz-signature", "x-amz-credential", "x-amz-algorithm",
    "x-amz-date", "success_action_status",
}


class PostPolicy:
    def __init__(self, expiration: float,
                 conditions: list, raw: dict):
        self.expiration = expiration
        self.conditions = conditions
        self.raw = raw

    @classmethod
    def parse(cls, policy_b64: str) -> "PostPolicy":
        try:
            doc = json.loads(base64.b64decode(policy_b64))
        except Exception as e:  # noqa: BLE001
            raise AuthError("MalformedPOSTRequest",
                            f"unparseable policy: {e}", 400) from None
        exp_raw = doc.get("expiration", "")
        exp = None
        for fmt in ("%Y-%m-%dT%H:%M:%S.%fZ", "%Y-%m-%dT%H:%M:%SZ"):
            try:
                exp = calendar.timegm(time.strptime(exp_raw, fmt))
                break
            except ValueError:
                continue
        if exp is None:
            raise AuthError("MalformedPOSTRequest",
                            f"bad policy expiration {exp_raw!r}", 400)
        return cls(exp, doc.get("conditions", []), doc)

    def check(self, form: dict[str, str], content_length: int) -> None:
        """Enforce expiration, every condition, and full coverage of
        the submitted fields (checkPostPolicy)."""
        if time.time() > self.expiration:
            raise AuthError("AccessDenied", "policy has expired")
        covered: set[str] = set()
        lower = {k.lower(): v for k, v in form.items()}
        for cond in self.conditions:
            if isinstance(cond, dict):
                items = [["eq", f"${k}", v] for k, v in cond.items()]
            elif isinstance(cond, list) and len(cond) == 3:
                items = [cond]
            else:
                raise AuthError("MalformedPOSTRequest",
                                f"bad condition {cond!r}", 400)
            for op, target, value in items:
                op = str(op).lower()
                if op not in ("eq", "starts-with",
                              "content-length-range"):
                    # An unrecognized operator must REJECT the policy,
                    # not silently leave the field unconstrained.
                    raise AuthError("MalformedPOSTRequest",
                                    f"unsupported condition {op!r}", 400)
                if op == "content-length-range":
                    try:
                        lo, hi = int(target), int(value)
                    except (TypeError, ValueError):
                        raise AuthError(
                            "MalformedPOSTRequest",
                            "non-numeric content-length-range",
                            400) from None
                    if not lo <= content_length <= hi:
                        raise AuthError(
                            "EntityTooLarge" if content_length > hi
                            else "EntityTooSmall",
                            f"content length {content_length} outside "
                            f"[{lo}, {hi}]", 400)
                    continue
                name = str(target).lstrip("$").lower()
                covered.add(name)
                got = lower.get(name, "")
                if op == "eq" and got != value:
                    raise AuthError(
                        "AccessDenied",
                        f"policy condition failed: {name} == {value!r}")
                if op == "starts-with" and \
                        not got.startswith(str(value)):
                    raise AuthError(
                        "AccessDenied",
                        f"policy condition failed: {name} "
                        f"starts-with {value!r}")
        for name in lower:
            if name in _EXEMPT or name.startswith("x-ignore-"):
                continue
            if name not in covered:
                raise AuthError(
                    "AccessDenied",
                    f"form field {name!r} not covered by the policy")


def parse_multipart_form(body: bytes, content_type: str
                         ) -> tuple[dict[str, str], str, bytes, str]:
    """multipart/form-data -> (fields, file_name, file_bytes,
    file_content_type).

    Minimal RFC 7578 parser for the browser-POST upload surface; the
    `file` part must come last (AWS requires it: fields after the file
    are ignored — here rejected implicitly by coverage checks).  The
    file part's own Content-Type is returned separately — it is part
    of the upload, NOT a form field needing policy coverage.
    """
    marker = "boundary="
    i = content_type.find(marker)
    if i < 0:
        raise AuthError("MalformedPOSTRequest",
                        "multipart body without boundary", 400)
    boundary = content_type[i + len(marker):].split(";")[0].strip()
    if boundary.startswith('"') and boundary.endswith('"'):
        boundary = boundary[1:-1]
    delim = b"--" + boundary.encode()
    fields: dict[str, str] = {}
    file_name, file_bytes, file_ctype = "", b"", ""
    # Split on CRLF+delimiter so part content keeps its own trailing
    # newlines byte-exact (RFC 2046: the CRLF before a boundary belongs
    # to the boundary, not the content).  Normalize the first
    # delimiter, which has no preceding CRLF.
    if body.startswith(delim):
        body = b"\r\n" + body
    segments = body.split(b"\r\n" + delim)
    for part in segments[1:]:  # [0] is the preamble
        if part.startswith(b"--"):
            break  # closing delimiter
        if part.startswith(b"\r\n"):
            part = part[2:]
        head, _, content = part.partition(b"\r\n\r\n")
        disp = ""
        ptype = ""
        for line in head.split(b"\r\n"):
            text = line.decode("utf-8", "replace")
            if text.lower().startswith("content-disposition:"):
                disp = text
            elif text.lower().startswith("content-type:"):
                ptype = text.split(":", 1)[1].strip()
        name = _disp_param(disp, "name")
        if name is None:
            continue
        filename = _disp_param(disp, "filename")
        if name == "file" or filename is not None:
            file_name = filename or ""
            file_bytes = content
            file_ctype = ptype
        else:
            fields[name] = content.decode("utf-8", "replace")
    return fields, file_name, file_bytes, file_ctype


def _disp_param(disposition: str, param: str) -> str | None:
    for piece in disposition.split(";"):
        piece = piece.strip()
        if piece.startswith(param + "="):
            val = piece[len(param) + 1:]
            return val[1:-1] if val.startswith('"') else val
    return None
