"""S3 gateway server: path-style S3 REST protocol over the filer.

Reference: weed/s3api/s3api_server.go:38-131 (route table) and the
handlers in s3api_object_handlers.go, s3api_bucket_handlers.go,
filer_multipart.go, s3api_object_tagging_handlers.go.

Objects live under /buckets/<bucket>/<key> in the filer namespace, like
the reference's filerBucketsPath.  Multipart parts are uploaded as
ordinary filer files and the completed object is assembled by merging the
parts' chunk lists with adjusted offsets — no data copy (the reference
does exactly this with gRPC CreateEntry; here it is the filer's
?entry=true raw-create endpoint).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
import xml.etree.ElementTree as ET

from ..cluster import rpc
from ..filer.client import FilerProxy
from .auth import (ACTION_ADMIN, ACTION_LIST, ACTION_READ, ACTION_TAGGING,
                   ACTION_WRITE, AuthError, Identity,
                   IdentityAccessManagement)

BUCKETS_PATH = "/buckets"
UPLOADS_DIR = ".uploads"
XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"


def _xml(root: ET.Element) -> bytes:
    return (b'<?xml version="1.0" encoding="UTF-8"?>'
            + ET.tostring(root))


def _el(parent, tag, text=None):
    e = ET.SubElement(parent, tag)
    if text is not None:
        e.text = str(text)
    return e


def _error_xml(code: str, message: str) -> bytes:
    root = ET.Element("Error")
    _el(root, "Code", code)
    _el(root, "Message", message)
    return _xml(root)


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts))


def _as_bytes(body) -> bytes:
    """Materialize a (possibly streaming) request body."""
    return body.read() if hasattr(body, "read") else body


class _AwsChunkedReader:
    """Incrementally strips aws-chunked framing from a streaming body
    — the streaming analog of _decode_aws_chunked, so a multi-GB SDK
    upload never materializes (the reference wraps the request body in
    a chunkedReader the same way)."""

    def __init__(self, inner, decoded_length: int | None):
        self._inner = inner
        self.length = decoded_length
        self._in_chunk = 0
        self._done = False
        self._decoded = 0

    def _read_line(self) -> bytes:
        out = bytearray()
        while not out.endswith(b"\r\n"):
            if len(out) >= 8192:
                raise ConnectionError(
                    "aws-chunked header line exceeds 8KB")
            b = self._inner.read(1)
            if not b:
                break
            out += b
        return bytes(out)

    def read(self, n: int = -1) -> bytes:
        out = bytearray()
        while not self._done and (n < 0 or len(out) < n):
            if self._in_chunk == 0:
                header = self._read_line().strip()
                if not header:
                    # EOF where a chunk header belongs before the
                    # 0-size terminator: the framing is truncated.  A
                    # malformed stream must ERROR, never 200 as a
                    # silently-truncated object.
                    raise ConnectionError(
                        "aws-chunked framing truncated")
                size_hex = header.split(b";", 1)[0].strip()
                try:
                    size = int(size_hex, 16)
                except ValueError:
                    raise ConnectionError(
                        f"malformed aws-chunked size line "
                        f"{header[:32]!r}") from None
                if size == 0:
                    self._read_line()  # trailing CRLF / trailers
                    self._done = True
                    if self.length is not None and \
                            self._decoded != self.length:
                        raise ConnectionError(
                            f"aws-chunked decoded {self._decoded} bytes "
                            f"!= declared x-amz-decoded-content-length "
                            f"{self.length}")
                    break
                self._in_chunk = size
            want = self._in_chunk if n < 0 \
                else min(n - len(out), self._in_chunk)
            piece = self._inner.read(want)
            if not piece:
                raise ConnectionError(
                    "aws-chunked data truncated mid-chunk")
            out += piece
            self._decoded += len(piece)
            if self.length is not None and self._decoded > self.length:
                # More payload than declared: storing it would truncate
                # at the forwarded Content-Length — fail loudly instead.
                raise ConnectionError(
                    f"aws-chunked payload exceeds declared "
                    f"x-amz-decoded-content-length {self.length}")
            self._in_chunk -= len(piece)
            if self._in_chunk == 0:
                self._inner.read(2)  # chunk-data CRLF
        return bytes(out)


class _HashingReader:
    """Tee reader computing md5 as bytes flow through (streamed PUT
    ETags without buffering)."""

    def __init__(self, inner):
        self._inner = inner
        self.length = getattr(inner, "length", None)
        self._md5 = hashlib.md5()
        self.bytes_read = 0

    def read(self, n: int = -1) -> bytes:
        data = self._inner.read(n)
        self._md5.update(data)
        self.bytes_read += len(data)
        return data

    @property
    def md5_hex(self) -> str:
        return self._md5.hexdigest()


def _valid_bucket_name(name: str) -> bool:
    """AWS bucket naming rules (the subset the reference enforces):
    3-63 chars of [a-z0-9.-], starting/ending alphanumeric — which also
    keeps reserved names like '.uploads' out of the bucket namespace."""
    import re
    return bool(re.fullmatch(r"[a-z0-9][a-z0-9.-]{1,61}[a-z0-9]", name))


class S3Error(Exception):
    def __init__(self, status: int, code: str, message: str):
        super().__init__(message)
        self.status = status
        self.code = code
        self.message = message


# Filer path holding the live S3 identities config (the reference's
# filer-backed IAM: auth_credentials.go loads the same JSON shape from
# the filer's /etc tree and reloads on change).
IAM_CONFIG_PATH = "/etc/iam/identity.json"


class S3ApiServer:
    def __init__(self, filer_url: str, host: str = "127.0.0.1",
                 port: int = 0,
                 identities: list[Identity] | None = None,
                 metrics_port: int | None = None,
                 ssl_context=None,
                 iam_refresh_seconds: float = 5.0):
        self.filer = FilerProxy(filer_url)
        self.iam = IdentityAccessManagement(identities)
        # Filer-backed IAM: with no explicit identities, the config
        # lives IN the cluster at /etc/iam/identity.json and hot-
        # reloads — update the file through any filer and every S3
        # gateway picks it up.
        self._iam_from_filer = identities is None
        self._iam_raw: bytes | None = None
        self._iam_refresh = iam_refresh_seconds
        self._iam_stop = threading.Event()
        self._iam_thread = None
        if self._iam_from_filer:
            self._reload_iam()
        self.server = rpc.JsonHttpServer(host, port, pass_headers=True,
                                         ssl_context=ssl_context)
        for method in ("GET", "HEAD", "PUT", "POST", "DELETE"):
            self.server.prefix_route(method, "/", self._route,
                                     stream_body=True)
        # Bucket names own the URL namespace, so /metrics lives on its
        # own port (the reference's -metricsPort behaves the same).
        self.metrics_registry = self.server.enable_metrics(
            "s3", serve_route=False)
        self.metrics_server = None
        if metrics_port is not None:
            self.metrics_server = rpc.JsonHttpServer(host, metrics_port)
            self.metrics_server.serve_metrics_route(
                self.metrics_registry)
        try:
            self.filer.mkdir(BUCKETS_PATH)
        except Exception:  # noqa: BLE001 — filer may not be up yet
            pass

    def start(self) -> None:
        self.server.start()
        if self.metrics_server is not None:
            self.metrics_server.start()
        if self._iam_from_filer:
            self._iam_thread = threading.Thread(
                target=self._iam_reload_loop, daemon=True,
                name="s3-iam-reload")
            self._iam_thread.start()

    def stop(self) -> None:
        self._iam_stop.set()
        if self.metrics_server is not None:
            self.metrics_server.stop()
        self.server.stop()

    def _reload_iam(self) -> bool:
        """Pull /etc/iam/identity.json from the filer; swap the
        identity set when it changed.  A definitive 404 means IAM is
        intentionally unconfigured (anonymous mode); any OTHER failure
        before the first successful read fails CLOSED — a filer outage
        at startup must not open the gateway to the world."""
        import urllib.error
        try:
            with self.filer.get(IAM_CONFIG_PATH) as resp:
                raw = resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                # Definitive: the config does not exist.  If identities
                # were previously loaded from the filer, the file's
                # deletion revokes them (back to anonymous — the
                # pre-config state).  _iam_raw keeps a sentinel so a
                # LATER transient error doesn't flip to fail-closed.
                if self._iam_raw not in (None, b""):
                    self.iam.replace([])
                self._iam_raw = b""
                self.iam.fail_closed = False
                return False
            self._iam_fetch_failed()
            return False
        except Exception:  # noqa: BLE001 — filer down / timeout
            self._iam_fetch_failed()
            return False
        if raw == self._iam_raw:
            self.iam.fail_closed = False
            return False
        try:
            from .auth import identities_from_dict
            idents = identities_from_dict(json.loads(raw))
        except Exception as e:  # noqa: BLE001 — keep serving with the
            from ..utils import glog  # last-good identities
            glog.warningf("s3 iam: unparseable %s: %s",
                          IAM_CONFIG_PATH, e)
            return False
        self._iam_raw = raw
        self.iam.replace(idents)
        self.iam.fail_closed = False
        return True

    def _iam_fetch_failed(self) -> None:
        if self._iam_raw is None:
            # Never successfully read the config: we cannot tell
            # "anonymous intended" from "filer unreachable" — deny
            # until a poll succeeds.
            self.iam.fail_closed = True

    def _iam_reload_loop(self) -> None:
        while not self._iam_stop.wait(self._iam_refresh):
            try:
                self._reload_iam()
            except Exception:  # noqa: BLE001
                pass

    def url(self) -> str:
        return self.server.url()

    # -- routing -------------------------------------------------------------

    # Bodies at or below this size are buffered so the payload-hash
    # cross-check still runs; larger signed PUTs stream and the
    # signature covers the declared hash (reference behavior).
    _VERIFY_BUFFER_MAX = 8 * 1024 * 1024
    # Browser-form POST uploads are parsed in memory; cap the body.
    _POST_FORM_MAX = 256 * 1024 * 1024

    def _route(self, path: str, query: dict, body):
        method = query.get("_method", "GET")
        headers = query.get("_headers", {})
        raw_query = query.get("_raw_query", "")
        try:
            if method == "POST" and headers.get(
                    "content-type", "").startswith("multipart/form-data"):
                # Browser-form upload: authentication is the signed
                # POST policy inside the form, not a header
                # (s3api/policy/post-policy.go).  The multipart body is
                # buffered for parsing (the reference's
                # ParseMultipartForm buffers/spills too) — capped so a
                # giant form can't balloon RSS; large objects belong on
                # the streaming PUT path.
                length = getattr(body, "length", None)
                if length is not None and length > self._POST_FORM_MAX:
                    raise S3Error(413, "EntityTooLarge",
                                  "POST form uploads are capped at "
                                  f"{self._POST_FORM_MAX >> 20}MB; use "
                                  "a signed PUT for larger objects")
                data = body.read(self._POST_FORM_MAX + 1) \
                    if hasattr(body, "read") else body
                if len(data) > self._POST_FORM_MAX:
                    raise S3Error(413, "EntityTooLarge",
                                  "POST form uploads are capped at "
                                  f"{self._POST_FORM_MAX >> 20}MB; use "
                                  "a signed PUT for larger objects")
                return self._post_object(path, headers, data)
            sha_hdr = headers.get("x-amz-content-sha256", "")
            length = getattr(body, "length", None)
            if self.iam.enabled and not sha_hdr:
                # No declared hash: the signature needs the payload.
                body = _as_bytes(body)
            elif sha_hdr and sha_hdr != "UNSIGNED-PAYLOAD" \
                    and not sha_hdr.startswith("STREAMING-") \
                    and length is not None \
                    and length <= self._VERIFY_BUFFER_MAX:
                # Small declared-hash body: buffer so the recompute
                # cross-check still runs.  Large or unknown-length
                # (chunked TE) bodies stream — auth signs the declared
                # hash, and RSS stays O(chunk).
                body = _as_bytes(body)
            identity = self.iam.authenticate(
                method, path, raw_query, headers,
                body if isinstance(body, (bytes, bytearray)) else None)
            if identity is not None and identity.name:
                # Tenancy principal = the authenticated S3 identity.
                # set_principal makes every downstream filer/volume hop
                # carry X-Weed-Tenant (rpc._request injects it), so
                # quotas, fair admission, usage ledgers and /debug/hot
                # all attribute to the S3 user, not the gateway.
                from ..tenancy import context as _tenant_ctx
                _tenant_ctx.set_principal(identity.name,
                                          _tenant_ctx.current_client())
            if sha_hdr.startswith("STREAMING-"):
                # aws-chunked framing: strip the chunk headers and
                # signatures or the framed wire bytes would be stored
                # as content.  (STREAMING- payloads are never buffered
                # by the branches above, so body is always a reader.)
                decoded = headers.get("x-amz-decoded-content-length")
                body = _AwsChunkedReader(
                    body, int(decoded) if decoded else None)
            return self._dispatch(method, path, query, headers, body,
                                  identity)
        except AuthError as e:
            return (e.status, _error_xml(e.code, str(e)),
                    {"Content-Type": "application/xml"})
        except S3Error as e:
            return (e.status, _error_xml(e.code, e.message),
                    {"Content-Type": "application/xml"})
        except rpc.RpcError as e:
            # Tenancy verdicts from the filer/master surface in S3
            # shape: hard quota -> 403 QuotaExceeded, throttle -> the
            # AWS SlowDown error (503) with Retry-After preserved.
            ans = self._tenancy_error(e.status, e.message,
                                      e.retry_after)
            if ans is None:
                raise
            return ans
        except urllib.error.HTTPError as e:
            # FilerProxy's streaming calls ride urllib, not the rpc
            # pool — same tenancy mapping for their error shape.
            msg = e.read().decode("utf-8", "replace")
            ra = e.headers.get("Retry-After") if e.headers else None
            ans = self._tenancy_error(
                e.code, msg, float(ra) if ra else None)
            if ans is None:
                raise
            return ans

    @staticmethod
    def _tenancy_error(status: int, message: str,
                       retry_after: float | None):
        if status == 403 and "QuotaExceeded" in message:
            return (403, _error_xml("QuotaExceeded", message),
                    {"Content-Type": "application/xml"})
        if status == 429:
            hdrs = {"Content-Type": "application/xml"}
            if retry_after is not None:
                hdrs["Retry-After"] = f"{retry_after:g}"
            return (503, _error_xml(
                "SlowDown", "Reduce your request rate."), hdrs)
        return None

    def _dispatch(self, method: str, path: str, query: dict,
                  headers: dict, body,
                  identity: Identity | None):
        path = urllib.parse.unquote(path)
        parts = path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        # Only object/part PUTs stream; every other operation's body is
        # small control XML/JSON.
        if not (method == "PUT" and key and "tagging" not in query
                and not headers.get("x-amz-copy-source", "")):
            body = _as_bytes(body)
        auth = lambda action: self.iam.authorize(identity, action, bucket)  # noqa: E731

        if not bucket:  # service level
            auth(ACTION_LIST)
            return self._list_buckets(identity)
        if not key:  # bucket level
            if method == "PUT":
                auth(ACTION_ADMIN)
                return self._create_bucket(bucket)
            if method == "DELETE":
                auth(ACTION_ADMIN)
                return self._delete_bucket(bucket)
            if method == "HEAD":
                auth(ACTION_READ)
                return self._head_bucket(bucket)
            if method == "POST" and "delete" in query:
                auth(ACTION_WRITE)
                return self._delete_multiple(bucket, body)
            if method == "GET":
                if "uploads" in query:
                    auth(ACTION_LIST)
                    return self._list_multipart_uploads(bucket)
                auth(ACTION_LIST)
                return self._list_objects(bucket, query)
            raise S3Error(405, "MethodNotAllowed", method)

        # object level
        if method == "POST" and "select" in query:
            auth(ACTION_READ)
            return self._select_object_content(bucket, key, body)
        if method == "POST" and "uploads" in query:
            auth(ACTION_WRITE)
            return self._initiate_multipart(bucket, key, headers)
        if method == "PUT" and "partNumber" in query:
            auth(ACTION_WRITE)
            return self._upload_part(bucket, key, query, body)
        if method == "POST" and "uploadId" in query:
            auth(ACTION_WRITE)
            return self._complete_multipart(bucket, key, query, body)
        if method == "DELETE" and "uploadId" in query:
            auth(ACTION_WRITE)
            return self._abort_multipart(bucket, key, query)
        if "tagging" in query:
            if method == "PUT":
                auth(ACTION_TAGGING)
                return self._put_tagging(bucket, key, body)
            if method == "GET":
                auth(ACTION_READ)
                return self._get_tagging(bucket, key)
            if method == "DELETE":
                auth(ACTION_TAGGING)
                return self._delete_tagging(bucket, key)
        if method == "PUT":
            auth(ACTION_WRITE)
            src = headers.get("x-amz-copy-source", "")
            if src:
                # The caller must also be allowed to READ the source
                # bucket (s3api_object_copy_handlers.go checks both).
                sbucket = urllib.parse.unquote(src).lstrip("/") \
                    .partition("/")[0]
                self.iam.authorize(identity, ACTION_READ, sbucket)
                return self._copy_object(bucket, key, src)
            return self._put_object(bucket, key, headers, body)
        if method in ("GET", "HEAD"):
            auth(ACTION_READ)
            return self._get_object(bucket, key, headers,
                                    head=(method == "HEAD"))
        if method == "DELETE":
            auth(ACTION_WRITE)
            return self._delete_object(bucket, key)
        raise S3Error(405, "MethodNotAllowed", method)

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _bucket_path(bucket: str) -> str:
        return f"{BUCKETS_PATH}/{bucket}"

    def _obj_path(self, bucket: str, key: str) -> str:
        return f"{BUCKETS_PATH}/{bucket}/{key}"

    def _require_bucket(self, bucket: str) -> dict:
        meta = self.filer.meta(self._bucket_path(bucket))
        if meta is None or not meta.get("is_directory"):
            raise S3Error(404, "NoSuchBucket",
                          f"bucket {bucket} does not exist")
        return meta

    # -- service / bucket ----------------------------------------------------

    def _list_buckets(self, identity: Identity | None = None):
        root = ET.Element("ListAllMyBucketsResult",
                          {"xmlns": XMLNS})
        owner = _el(root, "Owner")
        _el(owner, "ID", "seaweedfs")
        buckets = _el(root, "Buckets")
        for e in self.filer.list_all(BUCKETS_PATH):
            if not e.get("is_directory") or e["name"] == UPLOADS_DIR:
                continue
            # Only buckets the caller may actually touch
            # (s3api_bucket_handlers.go filters by identity.canDo).
            if identity is not None and not (
                    identity.allows(ACTION_LIST, e["name"])
                    or identity.allows(ACTION_READ, e["name"])):
                continue
            b = _el(buckets, "Bucket")
            _el(b, "Name", e["name"])
            _el(b, "CreationDate", _iso(e.get("mtime", 0)))
        return (200, _xml(root), {"Content-Type": "application/xml"})

    def _create_bucket(self, bucket: str):
        if not _valid_bucket_name(bucket):
            raise S3Error(400, "InvalidBucketName",
                          f"{bucket!r} is not a valid bucket name")
        self.filer.mkdir(self._bucket_path(bucket))
        return (200, b"", {"Location": f"/{bucket}"})

    def _delete_bucket(self, bucket: str):
        self._require_bucket(bucket)
        if self.filer.list(self._bucket_path(bucket), limit=1):
            raise S3Error(409, "BucketNotEmpty",
                          f"bucket {bucket} is not empty")
        self.filer.delete(self._bucket_path(bucket), recursive=True)
        # Abort any in-progress multipart uploads with the bucket, or
        # their part chunks leak and resurface on bucket re-create.
        self.filer.delete(f"{BUCKETS_PATH}/{UPLOADS_DIR}/{bucket}",
                          recursive=True)
        return (204, b"")

    def _head_bucket(self, bucket: str):
        self._require_bucket(bucket)
        return (200, b"")

    # -- objects -------------------------------------------------------------

    def _put_object(self, bucket: str, key: str, headers: dict,
                    body):
        self._require_bucket(bucket)
        if key.endswith("/"):  # directory marker
            _as_bytes(body)
            self.filer.mkdir(self._obj_path(bucket, key.rstrip("/")))
            return (200, b"", {"ETag": '"d41d8cd98f00b204e9800998ecf8427e"'})
        ctype = headers.get("content-type",
                            "application/octet-stream")
        path = self._obj_path(bucket, key)
        fallback_etag = self._put_body(path, body, ctype)
        # Return the same ETag GET/HEAD will serve (computed from the
        # stored chunk list) so sync clients' change detection is stable.
        meta = self.filer.meta(path)
        etag = self._entry_etag(meta) if meta else fallback_etag
        return (200, b"", {"ETag": f'"{etag}"'})

    def _post_object(self, path: str, headers: dict, body: bytes):
        """POST-policy upload: multipart form to the bucket URL with a
        signed policy; the file lands at the form's `key`
        (s3api_object_handlers PostPolicyBucketHandler analog)."""
        from .policy import PostPolicy, parse_multipart_form
        bucket = urllib.parse.unquote(path).lstrip("/").split("/", 1)[0]
        if not bucket:
            raise S3Error(405, "MethodNotAllowed",
                          "POST uploads go to a bucket URL")
        fields, file_name, file_bytes, file_ctype = parse_multipart_form(
            body, headers.get("content-type", ""))
        key = fields.get("key", "")
        if not key:
            raise S3Error(400, "InvalidArgument",
                          "POST form needs a key field")
        # Substitute ${filename} BEFORE the policy runs: conditions
        # must constrain the FINAL key, or an attacker-chosen filename
        # escapes the signed prefix (post-policy.go substitutes first).
        key = key.replace("${filename}", file_name)
        # Authenticate before touching the bucket — a 404-vs-403 split
        # for anonymous callers would be a bucket-existence oracle.
        identity = self.iam.authenticate_policy(fields)
        if self.iam.enabled:
            self.iam.authorize(identity, ACTION_WRITE, bucket)
            lower = {k.lower(): v for k, v in fields.items()}
            PostPolicy.parse(lower["policy"]).check(
                dict(fields, key=key), len(file_bytes))
        self._require_bucket(bucket)
        obj_path = self._obj_path(bucket, key)
        etag = self._put_body(
            obj_path, file_bytes,
            fields.get("Content-Type") or file_ctype
            or "application/octet-stream")
        status = fields.get("success_action_status", "204")
        loc = f"/{bucket}/{urllib.parse.quote(key)}"
        if status == "201":
            root = ET.Element("PostResponse")
            _el(root, "Location", loc)
            _el(root, "Bucket", bucket)
            _el(root, "Key", key)
            _el(root, "ETag", f'"{etag}"')
            return (201, _xml(root),
                    {"Content-Type": "application/xml"})
        return (200 if status == "200" else 204, b"",
                {"ETag": f'"{etag}"', "Location": loc})

    def _put_body(self, path: str, body, ctype: str = "") -> str:
        """Store a request body (bytes or streaming reader) at a filer
        path; returns its md5 hex.  Readers stream straight through —
        RSS stays O(chunk) for however large the PUT."""
        if hasattr(body, "read"):
            tee = _HashingReader(body)
            self.filer.put(path, tee, ctype, length=tee.length)
            return tee.md5_hex
        self.filer.put(path, body, ctype)
        return hashlib.md5(body).hexdigest()

    def _copy_object(self, bucket: str, key: str, src: str):
        self._require_bucket(bucket)
        src = urllib.parse.unquote(src).lstrip("/")
        sbucket, _, skey = src.partition("/")
        spath = self._obj_path(sbucket, skey)
        smeta = self.filer.meta(spath)
        if smeta is None or smeta.get("is_directory"):
            raise S3Error(404, "NoSuchKey", f"source {src} not found")
        # Re-upload the bytes: sharing chunk ids between two entries would
        # double-free when either copy is later deleted (the filer GC has
        # no refcounting; the reference copies data too).
        with self.filer.get(spath) as resp:
            data = resp.read()
        ctype = smeta.get("attributes", {}).get(
            "mime", "application/octet-stream")
        dpath = self._obj_path(bucket, key)
        self.filer.put(dpath, data, ctype)
        dmeta = self.filer.meta(dpath)
        etag = self._entry_etag(dmeta) if dmeta else \
            hashlib.md5(data).hexdigest()
        root = ET.Element("CopyObjectResult", {"xmlns": XMLNS})
        _el(root, "LastModified", _iso(time.time()))
        _el(root, "ETag", f'"{etag}"')
        return (200, _xml(root), {"Content-Type": "application/xml"})

    def _select_object_content(self, bucket: str, key: str,
                               body: bytes):
        """SelectObjectContent: run a SELECT over one object and stream
        the result as AWS event-stream frames (volume Query RPC
        analog at the S3 surface)."""
        from ..query import run_query
        from ..query.sql import SqlError
        from .select import event_stream, parse_select_request
        try:
            req = parse_select_request(body)
        except Exception as e:  # noqa: BLE001 — malformed XML
            raise S3Error(400, "MalformedXML", str(e)) from None
        path = self._obj_path(bucket, key)
        meta = self.filer.meta(path)
        if meta is None or meta.get("is_directory"):
            raise S3Error(404, "NoSuchKey", f"{key} not found")
        with self.filer.get(path) as resp:
            data = resp.read()
        try:
            records = run_query(
                data, req["expression"],
                input_format=req["input_format"],
                csv_header=req["csv_header"],
                csv_delimiter=req["csv_delimiter"],
                output_format=req["output_format"])
        except (SqlError, ValueError) as e:
            raise S3Error(400, "InvalidTextEncoding"
                          if "format" in str(e) else
                          "InvalidExpression", str(e)) from None
        payload = event_stream(records, len(data), len(records))
        return (200, payload,
                {"Content-Type": "application/octet-stream"})

    def _get_object(self, bucket: str, key: str, headers: dict,
                    head: bool = False):
        path = self._obj_path(bucket, key)
        meta = self.filer.meta(path)
        if meta is None or meta.get("is_directory"):
            raise S3Error(404, "NoSuchKey", f"{key} not found")
        attrs = meta.get("attributes", {})
        size = sum(c["size"] for c in self._visible_sizes(meta))
        base_headers = {
            "Content-Type": attrs.get("mime",
                                      "application/octet-stream"),
            "Last-Modified": time.strftime(
                "%a, %d %b %Y %H:%M:%S GMT",
                time.gmtime(attrs.get("mtime", 0))),
            "Accept-Ranges": "bytes",
            "ETag": f'"{self._entry_etag(meta)}"',
        }
        if head:
            base_headers["Content-Length"] = str(size)
            return (200, b"", base_headers)
        rng = headers.get("range", "")
        # Hand the open filer response to the rpc layer, which streams
        # it to the client — a 10GB GET stays O(1MB) in gateway memory.
        resp = self.filer.get(path, rng)
        base_headers["Content-Length"] = \
            resp.headers.get("Content-Length", str(size))
        if resp.status == 206:
            base_headers["Content-Range"] = \
                resp.headers.get("Content-Range", "")
            return (206, resp, base_headers)
        return (200, resp, base_headers)

    @staticmethod
    def _entry_etag(meta: dict) -> str:
        from ..filer.entry import FileChunk
        from ..filer.filechunks import etag as chunks_etag
        chunks = [FileChunk.from_dict(c) for c in meta.get("chunks", [])]
        return chunks_etag(chunks)

    @staticmethod
    def _visible_sizes(meta: dict) -> list[dict]:
        from ..filer.entry import FileChunk
        from ..filer.filechunks import non_overlapping_visible_intervals
        chunks = [FileChunk.from_dict(c) for c in meta.get("chunks", [])]
        return [{"size": v.stop - v.start}
                for v in non_overlapping_visible_intervals(chunks)]

    def _delete_object(self, bucket: str, key: str):
        self.filer.delete(self._obj_path(bucket, key), recursive=True)
        return (204, b"")

    def _delete_multiple(self, bucket: str, body: bytes):
        root = ET.fromstring(body)
        ns = ""
        if root.tag.startswith("{"):
            ns = root.tag[:root.tag.index("}") + 1]
        deleted, errors = [], []
        for obj in root.iter(f"{ns}Object"):
            key_el = obj.find(f"{ns}Key")
            if key_el is None or not key_el.text:
                continue
            key = key_el.text
            try:
                self.filer.delete(self._obj_path(bucket, key),
                                  recursive=True)
                deleted.append(key)
            except Exception as e:  # noqa: BLE001
                errors.append((key, str(e)))
        out = ET.Element("DeleteResult", {"xmlns": XMLNS})
        for key in deleted:
            d = _el(out, "Deleted")
            _el(d, "Key", key)
        for key, msg in errors:
            er = _el(out, "Error")
            _el(er, "Key", key)
            _el(er, "Message", msg)
        return (200, _xml(out), {"Content-Type": "application/xml"})

    # -- listing -------------------------------------------------------------

    def _walk_keys(self, bucket: str, prefix: str, after: str = ""):
        """Yield (key, entry) in S3 key order (lexicographic over full
        key names), depth-first under prefix, skipping keys <= after.

        Within one directory the filer lists by entry name, but S3 order
        compares full keys — a subtree under dir `a` sorts as `a/`, which
        is AFTER file `a.txt` ('.' < '/').  So each directory's entries
        are re-sorted by their effective key (name + '/' for dirs) before
        descending.  Subtrees that cannot intersect [prefix, after..) are
        pruned, so prefix listings don't walk the whole bucket.
        """
        base = self._bucket_path(bucket)

        def rec(dir_rel: str):
            dir_abs = base + ("/" + dir_rel if dir_rel else "")
            entries = self.filer.list_all(dir_abs)
            entries.sort(key=lambda e: e["name"] +
                         ("/" if e.get("is_directory") else ""))
            for e in entries:
                rel = (dir_rel + "/" if dir_rel else "") + e["name"]
                if e.get("is_directory"):
                    if e["name"] == UPLOADS_DIR and not dir_rel:
                        continue
                    sub = rel + "/"
                    # prune: subtree keys all start with `sub`
                    if prefix and not (sub.startswith(prefix)
                                       or prefix.startswith(sub)):
                        continue
                    if after and after > sub and \
                            not after.startswith(sub):
                        continue  # whole subtree sorts <= after
                    yield from rec(rel)
                else:
                    if rel.startswith(prefix) and \
                            not (after and rel <= after):
                        yield rel, e

        # Start from the deepest directory fully inside the prefix.
        start = prefix.rsplit("/", 1)[0] if "/" in prefix else ""
        if start.split("/", 1)[0] == UPLOADS_DIR:
            # Starting inside the multipart staging subtree would bypass
            # rec()'s root-level skip and leak in-progress upload parts.
            return
        if start and self.filer.meta(base + "/" + start) is None:
            return
        yield from rec(start)

    def _list_objects(self, bucket: str, query: dict):
        self._require_bucket(bucket)
        prefix = query.get("prefix", "")
        delimiter = query.get("delimiter", "")
        max_keys = int(query.get("max-keys", 1000))
        v2 = query.get("list-type") == "2"
        after = query.get("continuation-token",
                          query.get("start-after", "")) if v2 else \
            query.get("marker", "")
        contents, common = [], []
        truncated = False
        seen_prefixes = set()
        last_item = ""  # last key or common prefix actually included
        for key, e in self._walk_keys(bucket, prefix, after):
            cp = None
            if delimiter:
                rest = key[len(prefix):]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter)[0] + delimiter
                    if cp in seen_prefixes:
                        continue
                    if after and cp <= after:
                        continue  # prefix already reported on a prior page
            # CommonPrefixes count toward MaxKeys like Contents do; only
            # report IsTruncated when a further item actually exists.
            if len(contents) + len(common) >= max_keys:
                truncated = True
                break
            if cp is not None:
                seen_prefixes.add(cp)
                common.append(cp)
                last_item = cp
            else:
                contents.append((key, e))
                last_item = key
        root = ET.Element("ListBucketResult", {"xmlns": XMLNS})
        _el(root, "Name", bucket)
        _el(root, "Prefix", prefix)
        _el(root, "MaxKeys", max_keys)
        _el(root, "IsTruncated", "true" if truncated else "false")
        if v2:
            _el(root, "KeyCount", len(contents) + len(common))
            if truncated and last_item:
                _el(root, "NextContinuationToken", last_item)
        elif truncated and last_item:
            _el(root, "NextMarker", last_item)
        for key, e in contents:
            c = _el(root, "Contents")
            _el(c, "Key", key)
            _el(c, "LastModified", _iso(e.get("mtime", 0)))
            _el(c, "Size", e.get("size", 0))
            _el(c, "StorageClass", "STANDARD")
        for cp in common:
            p = _el(root, "CommonPrefixes")
            _el(p, "Prefix", cp)
        return (200, _xml(root), {"Content-Type": "application/xml"})

    # -- multipart -----------------------------------------------------------

    def _uploads_path(self, bucket: str, upload_id: str) -> str:
        return f"{BUCKETS_PATH}/{UPLOADS_DIR}/{bucket}/{upload_id}"

    def _initiate_multipart(self, bucket: str, key: str, headers: dict):
        self._require_bucket(bucket)
        upload_id = uuid.uuid4().hex
        self.filer.mkdir(self._uploads_path(bucket, upload_id))
        # Remember the target key + content type on the upload dir.
        self.filer.create_entry(
            self._uploads_path(bucket, upload_id) + "/.manifest",
            {"attributes": {"mime": "application/json"},
             "extended": {"key": key,
                          "content_type": headers.get(
                              "content-type",
                              "application/octet-stream")}})
        root = ET.Element("InitiateMultipartUploadResult",
                          {"xmlns": XMLNS})
        _el(root, "Bucket", bucket)
        _el(root, "Key", key)
        _el(root, "UploadId", upload_id)
        return (200, _xml(root), {"Content-Type": "application/xml"})

    def _upload_part(self, bucket: str, key: str, query: dict,
                     body: bytes):
        part = int(query["partNumber"])
        if not 1 <= part <= 10000:
            raise S3Error(400, "InvalidArgument",
                          "partNumber must be between 1 and 10000")
        upload_id = query["uploadId"]
        updir = self._uploads_path(bucket, upload_id)
        if self.filer.meta(updir + "/.manifest") is None:
            raise S3Error(404, "NoSuchUpload", upload_id)
        path = f"{updir}/{part:05d}.part"
        md5 = self._put_body(path, body)
        return (200, b"", {"ETag": f'"{md5}"'})

    def _complete_multipart(self, bucket: str, key: str, query: dict,
                            body: bytes):
        upload_id = query["uploadId"]
        updir = self._uploads_path(bucket, upload_id)
        manifest = self.filer.meta(updir + "/.manifest")
        if manifest is None:
            raise S3Error(404, "NoSuchUpload", upload_id)
        uploaded = sorted(
            (e["name"] for e in self.filer.list_all(updir)
             if e["name"].endswith(".part")))
        # S3 semantics: only the parts listed in the request body are
        # assembled; unlisted uploaded parts are excluded.
        wanted = self._requested_part_numbers(body)
        if wanted is not None:
            by_number = {int(n.split(".")[0]): n for n in uploaded}
            missing = [p for p in wanted if p not in by_number]
            if missing:
                raise S3Error(400, "InvalidPart",
                              f"parts {missing} were not uploaded")
            parts = [by_number[p] for p in sorted(wanted)]
        else:
            parts = uploaded
        if not parts:
            raise S3Error(400, "MalformedXML",
                          "completion requires at least one part")
        chunks: list[dict] = []
        offset = 0
        for name in parts:
            meta = self.filer.meta(f"{updir}/{name}")
            if meta is None:
                continue
            for c in sorted(meta.get("chunks", []),
                            key=lambda c: c["offset"]):
                chunks.append({**c, "offset": offset + c["offset"]})
            offset += sum(c["size"] for c in meta.get("chunks", []))
        ctype = manifest.get("extended", {}).get(
            "content_type", "application/octet-stream")
        self.filer.create_entry(
            self._obj_path(bucket, key),
            {"attributes": {"mime": ctype}, "chunks": chunks})
        # Excluded parts' chunks are NOT in the final object: free them.
        for name in uploaded:
            if name not in parts:
                self.filer.delete(f"{updir}/{name}")
        # Metadata-only delete of the used parts: their chunks now belong
        # to the completed object (filer_multipart.go does the same merge).
        self.filer.delete(updir, recursive=True, keep_chunks=True)
        root = ET.Element("CompleteMultipartUploadResult",
                          {"xmlns": XMLNS})
        _el(root, "Bucket", bucket)
        _el(root, "Key", key)
        _el(root, "ETag", f'"{upload_id}-{len(parts)}"')
        return (200, _xml(root), {"Content-Type": "application/xml"})

    @staticmethod
    def _requested_part_numbers(body: bytes) -> list[int] | None:
        """PartNumbers from a CompleteMultipartUpload body; None when the
        body lists none (legacy/minimal clients: use all parts)."""
        if not body.strip():
            return None
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise S3Error(400, "MalformedXML",
                          "cannot parse completion body") from None
        ns = root.tag[:root.tag.index("}") + 1] if \
            root.tag.startswith("{") else ""
        nums = [int(el.text) for el in root.iter(f"{ns}PartNumber")
                if el.text and el.text.strip().isdigit()]
        return nums or None

    def _abort_multipart(self, bucket: str, key: str, query: dict):
        self.filer.delete(
            self._uploads_path(bucket, query["uploadId"]),
            recursive=True)
        return (204, b"")

    def _list_multipart_uploads(self, bucket: str):
        root = ET.Element("ListMultipartUploadsResult", {"xmlns": XMLNS})
        _el(root, "Bucket", bucket)
        base = f"{BUCKETS_PATH}/{UPLOADS_DIR}/{bucket}"
        for e in self.filer.list(base):
            if not e.get("is_directory"):
                continue
            manifest = self.filer.meta(f"{base}/{e['name']}/.manifest")
            u = _el(root, "Upload")
            _el(u, "UploadId", e["name"])
            if manifest:
                _el(u, "Key",
                    manifest.get("extended", {}).get("key", ""))
        return (200, _xml(root), {"Content-Type": "application/xml"})

    # -- tagging -------------------------------------------------------------

    def _put_tagging(self, bucket: str, key: str, body: bytes):
        meta = self.filer.meta(self._obj_path(bucket, key))
        if meta is None:
            raise S3Error(404, "NoSuchKey", key)
        root = ET.fromstring(body)
        ns = root.tag[:root.tag.index("}") + 1] if \
            root.tag.startswith("{") else ""
        tags = {}
        for t in root.iter(f"{ns}Tag"):
            k = t.find(f"{ns}Key")
            v = t.find(f"{ns}Value")
            if k is not None and k.text:
                tags[k.text] = v.text or "" if v is not None else ""
        extended = meta.get("extended", {})
        extended = {k: v for k, v in extended.items()
                    if not k.startswith("x-amz-tag-")}
        for k, v in tags.items():
            extended[f"x-amz-tag-{k}"] = v
        meta["extended"] = extended
        self.filer.create_entry(self._obj_path(bucket, key), meta)
        return (200, b"")

    def _get_tagging(self, bucket: str, key: str):
        meta = self.filer.meta(self._obj_path(bucket, key))
        if meta is None:
            raise S3Error(404, "NoSuchKey", key)
        root = ET.Element("Tagging", {"xmlns": XMLNS})
        ts = _el(root, "TagSet")
        for k, v in meta.get("extended", {}).items():
            if k.startswith("x-amz-tag-"):
                t = _el(ts, "Tag")
                _el(t, "Key", k[len("x-amz-tag-"):])
                _el(t, "Value", v)
        return (200, _xml(root), {"Content-Type": "application/xml"})

    def _delete_tagging(self, bucket: str, key: str):
        meta = self.filer.meta(self._obj_path(bucket, key))
        if meta is None:
            raise S3Error(404, "NoSuchKey", key)
        meta["extended"] = {k: v for k, v in
                            meta.get("extended", {}).items()
                            if not k.startswith("x-amz-tag-")}
        self.filer.create_entry(self._obj_path(bucket, key), meta)
        return (204, b"")
