"""Client-side AWS signature v4 signer.

Used by the S3 replication sink and tests to authenticate against any
S3-compatible endpoint, including our own gateway.  The computation is
shared with the server-side verifier (auth.compute_signature_v4), so
client and server can never drift.
"""

from __future__ import annotations

import hashlib
import time
import urllib.parse

from .auth import compute_signature_v4


def sign_request(method: str, url: str, headers: dict[str, str],
                 payload: bytes, access_key: str, secret_key: str,
                 region: str = "us-east-1",
                 payload_hash: str | None = None,
                 service: str = "s3") -> dict[str, str]:
    """Returns headers + the sig v4 Authorization set for this request.
    Pass a precomputed payload_hash to sign a streamed body without
    materializing it.  `service` scopes the credential — the same
    signing core serves S3 and SQS (the notification queue client)."""
    parsed = urllib.parse.urlparse(url)
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    date = amz_date[:8]
    scope = f"{date}/{region}/{service}/aws4_request"
    if payload_hash is None:
        payload_hash = hashlib.sha256(payload).hexdigest()
    out = dict(headers)
    out["Host"] = parsed.netloc
    out["x-amz-date"] = amz_date
    out["x-amz-content-sha256"] = payload_hash
    lower = {k.lower(): v for k, v in out.items()}
    signed = sorted(lower)
    sig = compute_signature_v4(
        method, parsed.path, parsed.query, lower, signed,
        payload_hash, amz_date, scope, secret_key)
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    return out
